"""Online SLO monitor: sliding-window goodput and overload incidents.

Production serving is judged by GOODPUT UNDER SLO — the rate of requests
that were actually useful to a client (answered, TTFT within target,
steady token cadence) — not by raw req/s, which counts a 90-second
answer the user abandoned as a success (APEX frames online inference
exactly this way; PAPERS.md).  This module turns the per-request truth
the router already derives at its exactly-once ``_finish_request`` exit
(obs/spans.py timings) into:

- per-(strategy, tier) sliding-window goodput gauges
  (``dllm_slo_goodput{strategy,tier}``),
- violation counters by kind (``dllm_slo_violations_total{kind}``,
  kind ∈ error | ttft | tbt),
- rising-edge OVERLOAD INCIDENTS: when a tier's windowed goodput drops
  under ``goodput_floor``, one incident record opens — carrying the
  start time, the violating tier, the goodput at open, the peak queue
  depth so far, and a sampler timeline slice (obs/sampler.py) — and is
  pushed into the flight recorder immediately (an incident that is
  STILL OPEN when the process dies must already be on the post-mortem
  surface); recovery past ``goodput_floor + recover_margin`` closes it
  in place (duration, end goodput, final peak).

A request MEETS its SLO iff it completed ok (not error-shaped, not
degraded) AND its TTFT ≤ ``slo_ttft_ms`` AND its per-request p95
time-between-tokens ≤ ``slo_tbt_ms`` (targets per tier —
``TierConfig.slo_ttft_ms`` / ``slo_tbt_ms``, globally overridable via
``DLLM_SLO_TTFT_MS`` / ``DLLM_SLO_TBT_MS``; a None target skips that
check).  Cache hits count as good: a reply served from cache in
microseconds is the best SLO outcome there is, it just has no engine
latency to judge.

The ONLY sanctioned feed point is ``Router._finish_request`` — enforced
statically by the ``obs_discipline`` lint checker (a second feed site
would double-count requests and halve every goodput reading).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

DEFAULT_WINDOW = 64            # requests per (strategy, tier) goodput window
DEFAULT_GOODPUT_FLOOR = 0.5    # tier goodput below this opens an incident
DEFAULT_MIN_SAMPLES = 12       # window fill before incidents can fire
DEFAULT_RECOVER_MARGIN = 0.1   # hysteresis: close at floor + margin
INCIDENT_TIMELINE_SAMPLES = 40  # sampler slice attached to an incident
INCIDENT_HISTORY = 16          # closed incidents kept for /stats

# Placeholder parked in ``_active`` between reserving a tier's incident
# slot and the recorder entry existing.  It is NOT a live incident: a
# concurrent recovered request must not take the closing branch against
# it (it would finalize a throwaway dict and push a malformed history
# record), so the close edge requires a real entry and fires on the
# next feed after ``_open_incident`` lands.
_OPENING: Any = object()


class SLOMonitor:
    """Sliding-window goodput per (strategy, tier) + overload incidents.

    ``targets``: ``{tier: (slo_ttft_ms | None, slo_tbt_ms | None)}``.
    ``metrics``: optional ServingMetrics (gauges/counters mirror).
    ``recorder``: optional FlightRecorder (incident records).
    ``timeline``: optional zero-arg callable returning a sampler slice
    (list of samples) to attach to incidents.
    """

    def __init__(self, targets: Dict[str, Tuple[Optional[float],
                                                Optional[float]]],
                 metrics: Any = None, recorder: Any = None,
                 timeline: Optional[Callable[[], List[dict]]] = None,
                 window: int = DEFAULT_WINDOW,
                 goodput_floor: float = DEFAULT_GOODPUT_FLOOR,
                 min_samples: int = DEFAULT_MIN_SAMPLES,
                 recover_margin: float = DEFAULT_RECOVER_MARGIN):
        self.targets = dict(targets)
        self._metrics = metrics
        self._recorder = recorder
        self._timeline = timeline
        self.window = max(4, int(window))
        self.goodput_floor = float(goodput_floor)
        self.min_samples = max(1, int(min_samples))
        self.recover_margin = float(recover_margin)
        self._lock = threading.Lock()
        self._windows: Dict[Tuple[str, str], deque] = {}
        self._tier_windows: Dict[str, deque] = {}
        # Per-tenant goodput windows (ISSUE 17).  The feed passes
        # ALREADY-BOUNDED tenant labels (Observability.tenant_labels:
        # 64-char truncation, 256 distinct then '~overflow'), so this
        # dict — and the dllm_tenant_goodput gauge children it mirrors
        # to — inherits the same cardinality bound; the belt-and-braces
        # local cap below covers recorder-less monitors fed raw ids.
        self._tenant_windows: Dict[str, deque] = {}
        self._tenant_window_cap = 256
        self.observed_total = 0
        self.good_total = 0
        self.violations: Dict[str, int] = {"error": 0, "ttft": 0, "tbt": 0}
        # tier -> the OPEN incident's ring entry (mutated in place on
        # close via FlightRecorder.update_incident).
        self._active: Dict[str, Dict[str, Any]] = {}
        self.incidents: "deque[Dict[str, Any]]" = deque(
            maxlen=INCIDENT_HISTORY)
        self.incidents_total = 0

    # -- target resolution -------------------------------------------------

    def targets_for(self, tier: str) -> Tuple[Optional[float],
                                              Optional[float]]:
        return self.targets.get(tier, (None, None))

    # -- the feed (Router._finish_request ONLY — obs_discipline lint) ------

    def record_request(self, strategy: str, tier: Optional[str], ok: bool,
                       ttft_ms: Optional[float] = None,
                       tbt_p95_ms: Optional[float] = None,
                       cache_hit: bool = False,
                       tenant: Optional[str] = None) -> bool:
        """Score one finished request against its tier's SLO; returns
        whether it met it.  ``ok`` must already fold in degraded service
        (a degraded reply is not goodput).  ``tenant`` (ISSUE 17,
        already label-bounded by the caller) additionally feeds that
        tenant's goodput window and gauge — the per-tenant view the
        noisy-neighbor bench reads: whose SLO actually degraded."""
        tier = tier or "none"
        ttft_target, tbt_target = self.targets_for(tier)
        kind: Optional[str] = None
        if not ok:
            kind = "error"
        elif not cache_hit:
            if (ttft_target is not None and ttft_ms is not None
                    and ttft_ms > ttft_target):
                kind = "ttft"
            elif (tbt_target is not None and tbt_p95_ms is not None
                    and tbt_p95_ms > tbt_target):
                kind = "tbt"
        good = kind is None

        m = self._metrics
        with self._lock:
            self.observed_total += 1
            if good:
                self.good_total += 1
            else:
                self.violations[kind] = self.violations.get(kind, 0) + 1
            key = (strategy or "unknown", tier)
            win = self._windows.get(key)
            if win is None:
                win = self._windows[key] = deque(maxlen=self.window)
            win.append(good)
            goodput = sum(win) / len(win)
            twin = self._tier_windows.get(tier)
            if twin is None:
                twin = self._tier_windows[tier] = deque(maxlen=self.window)
            twin.append(good)
            tier_goodput = sum(twin) / len(twin)
            tier_samples = len(twin)
            tenant_goodput = None
            if tenant is not None:
                tw = self._tenant_windows.get(tenant)
                if tw is None and (len(self._tenant_windows)
                                   < self._tenant_window_cap):
                    tw = self._tenant_windows[tenant] = deque(
                        maxlen=self.window)
                if tw is not None:
                    tw.append(good)
                    tenant_goodput = sum(tw) / len(tw)
        if m is not None:
            try:
                if not good:
                    m.slo_violations.labels(kind).inc()
                m.slo_goodput.labels(key[0], tier).set(round(goodput, 4))
                if tenant_goodput is not None:
                    m.tenant_goodput_g.labels(tenant).set(
                        round(tenant_goodput, 4))
            except Exception:
                pass
        self._incident_edge(tier, tier_goodput, tier_samples)
        return good

    # -- incident lifecycle ------------------------------------------------

    def _timeline_slice(self) -> List[dict]:
        if self._timeline is None:
            return []
        try:
            return list(self._timeline())[-INCIDENT_TIMELINE_SAMPLES:]
        except Exception:
            return []

    @staticmethod
    def _peak_queue_depth(tier: str, samples: List[dict]) -> int:
        peak = 0
        for s in samples:
            st = (s.get("tiers") or {}).get(tier) or {}
            try:
                peak = max(peak, int(st.get("queue_depth") or 0))
            except (TypeError, ValueError):
                pass
        return peak

    def _incident_edge(self, tier: str, goodput: float,
                       samples: int) -> None:
        with self._lock:
            active = self._active.get(tier)
            opening = (active is None and samples >= self.min_samples
                       and goodput < self.goodput_floor)
            closing = (active is not None and active is not _OPENING
                       and goodput >= self.goodput_floor
                       + self.recover_margin)
            if opening:
                # Reserve the slot under the lock; build outside it (the
                # timeline callback takes the sampler's lock).
                self._active[tier] = _OPENING
            elif closing:
                del self._active[tier]
            else:
                return
        if opening:
            self._open_incident(tier, goodput)
        else:
            self._close_incident(tier, active, goodput)

    def _open_incident(self, tier: str, goodput: float) -> None:
        timeline = self._timeline_slice()
        info = {
            "tier": tier,
            "start_unix": round(time.time(), 3),
            "goodput_at_open": round(goodput, 4),
            "peak_queue_depth": self._peak_queue_depth(tier, timeline),
            "open": True,
            "timeline": timeline,
        }
        entry = None
        if self._recorder is not None:
            try:
                entry = self._recorder.record_incident("overload", info)
            except Exception:
                entry = None
        if entry is None:                       # recorder-less monitors
            entry = {"reason": "overload", "incident": info}
        m = self._metrics
        if m is not None:
            try:
                m.overload_incidents.labels(tier).inc()
                m.flight_records.labels("overload").inc()
            except Exception:
                pass
        with self._lock:
            self.incidents_total += 1
            self._active[tier] = entry

    def _close_incident(self, tier: str, entry: Dict[str, Any],
                        goodput: float) -> None:
        timeline = self._timeline_slice()
        start = entry.get("incident", {}).get("start_unix") or time.time()
        end = round(time.time(), 3)
        updates = {
            "open": False,
            "end_unix": end,
            "duration_s": round(max(0.0, end - start), 3),
            "goodput_at_close": round(goodput, 4),
            "peak_queue_depth": max(
                entry.get("incident", {}).get("peak_queue_depth", 0),
                self._peak_queue_depth(tier, timeline)),
        }
        if self._recorder is not None:
            try:
                self._recorder.update_incident(entry, **updates)
            except Exception:
                entry["incident"] = {**entry.get("incident", {}), **updates}
        else:
            entry["incident"] = {**entry.get("incident", {}), **updates}
        with self._lock:
            closed = dict(entry.get("incident", {}))
            closed.pop("timeline", None)        # history stays compact
            self.incidents.append(closed)

    # -- read --------------------------------------------------------------

    def goodput(self, strategy: Optional[str] = None,
                tier: Optional[str] = None) -> Optional[float]:
        """Windowed goodput for one (strategy, tier), one tier (any
        strategy), or overall (lifetime ratio) — None before any
        sample."""
        with self._lock:
            if strategy is not None and tier is not None:
                win = self._windows.get((strategy, tier))
                return (sum(win) / len(win)) if win else None
            if tier is not None:
                win = self._tier_windows.get(tier)
                return (sum(win) / len(win)) if win else None
            if not self.observed_total:
                return None
            return self.good_total / self.observed_total

    def snapshot(self) -> Dict[str, Any]:
        """The /stats surface: targets, per-(strategy, tier) windowed
        goodput, violation counts, and incident state."""
        with self._lock:
            goodput = {}
            for (strategy, tier), win in sorted(self._windows.items()):
                if win:
                    goodput.setdefault(strategy, {})[tier] = round(
                        sum(win) / len(win), 4)
            active = {t: {k: v for k, v in e.get("incident", {}).items()
                          if k != "timeline"}
                      for t, e in self._active.items()
                      if e is not _OPENING}
            tenants = {t: round(sum(w) / len(w), 4)
                       for t, w in sorted(self._tenant_windows.items())
                       if w}
            return {
                "targets": {t: {"slo_ttft_ms": tt, "slo_tbt_ms": tb}
                            for t, (tt, tb) in sorted(self.targets.items())},
                "observed_total": self.observed_total,
                "good_total": self.good_total,
                "goodput_lifetime": (round(self.good_total
                                           / self.observed_total, 4)
                                     if self.observed_total else None),
                "goodput": goodput,
                "tenants": tenants,
                "violations": dict(self.violations),
                "incidents_total": self.incidents_total,
                "active_incidents": active,
                "recent_incidents": list(self.incidents),
            }
