"""Metrics registry: counters, gauges, log-bucketed histograms, and a
Prometheus text exposition — stdlib only.

The serving stack's internal signals (queue depth, breaker state, wedge
flags, admission rejects — PRs 1 and 2) previously surfaced only as an
untyped ``GET /stats`` dict; this registry gives them a typed, scrapeable
shape, served as Prometheus exposition text at ``GET /metrics``
(serving/app.py) and read programmatically by bench.py for the
trace-derived headline columns.

Shape notes:

- A metric is a FAMILY (name + help + label names) of children keyed by
  label values: ``reg.counter("x_total", "…", ("tier",)).labels("nano")``.
  A label-less family is its own single child (``.inc()`` directly).
- Histograms use a fixed LOG-SPACED millisecond bucket ladder
  (sub-ms to minutes): latencies span 4+ orders of magnitude between the
  tiny CPU tiers and a wedged chip's timeout, and log buckets hold the
  relative quantile error roughly constant across that range where
  linear buckets would collapse one end or the other.
- ``Histogram.quantile`` interpolates within the winning bucket
  (the same estimate PromQL's histogram_quantile makes) — good to the
  bucket's width, which is the honest precision of any bucketed store.
- Thread-safety: one lock per registry guards family/child creation;
  each child then updates under its own lock.  Hot-path cost is one
  dict lookup + one lock + a float add (see the overhead test in
  tests/test_obs.py).
"""

from __future__ import annotations

import bisect
import threading
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

# Log-spaced ms ladder: 1-2-5 per decade from 0.5 ms to 120 s.
DEFAULT_BUCKETS_MS: Tuple[float, ...] = (
    0.5, 1, 2, 5, 10, 20, 50, 100, 200, 500,
    1000, 2000, 5000, 10000, 20000, 60000, 120000)


def nearest_rank(values: Iterable[float], q: float,
                 presorted: bool = False) -> Optional[float]:
    """Nearest-rank percentile over raw samples — rank
    ``round(q * (n - 1))`` of the sorted values, None when empty.  The
    ONE rank rule shared by the decode tick ring
    (``ContinuousBatchingEngine.tick_stats``), the per-request TBT
    cadence criterion (``RequestTrace.tbt_p95_ms``), the tick-phase
    profiler (obs/profiler.py) and the open-loop bench leg, so "p95"
    means the same thing in the sampler gauges, the SLO verdicts, and
    the bench artifact.  (Histogram.quantile is the OTHER estimator —
    bucket interpolation over the log ladder — used where raw samples
    are not retained.)

    ``presorted=True`` skips the sort for callers that already hold a
    sorted list and read several quantiles from it (tick_stats runs on
    the 4 Hz sampler path per tier — sorting the 512-entry ring once
    per quantile per collect was the ISSUE 11 small fix).  ``values``
    must then be an indexable sorted sequence."""
    vs = values if presorted else sorted(values)
    if not vs:
        return None
    ix = min(len(vs) - 1, int(q * (len(vs) - 1) + 0.5))
    return vs[ix]


def _fmt(v: float) -> str:
    """Prometheus sample formatting: integers without a trailing .0."""
    if v == int(v):
        return str(int(v))
    return repr(float(v))


def _label_str(names: Sequence[str], values: Sequence[str]) -> str:
    if not names:
        return ""
    inner = ",".join(f'{n}="{_escape(v)}"' for n, v in zip(names, values))
    return "{" + inner + "}"


def _escape(v: Any) -> str:
    return str(v).replace("\\", r"\\").replace('"', r'\"').replace(
        "\n", r"\n")


class Counter:
    __slots__ = ("_lock", "_value")

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        return self._value


class Gauge:
    __slots__ = ("_lock", "_value")

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    def dec(self, n: float = 1.0) -> None:
        self.inc(-n)

    @property
    def value(self) -> float:
        return self._value


class Histogram:
    __slots__ = ("_lock", "buckets", "counts", "sum", "count")

    def __init__(self, buckets: Sequence[float] = DEFAULT_BUCKETS_MS):
        self._lock = threading.Lock()
        self.buckets: Tuple[float, ...] = tuple(sorted(buckets))
        self.counts: List[int] = [0] * (len(self.buckets) + 1)  # +Inf tail
        self.sum = 0.0
        self.count = 0

    def observe(self, v: float) -> None:
        ix = bisect.bisect_left(self.buckets, v)
        with self._lock:
            self.counts[ix] += 1
            self.sum += v
            self.count += 1

    def quantile(self, q: float) -> Optional[float]:
        """Bucket-interpolated quantile estimate (None when empty).
        Matches PromQL histogram_quantile: linear within the winning
        bucket; the +Inf bucket clamps to the highest finite bound."""
        with self._lock:
            total = self.count
            counts = list(self.counts)
        if total == 0:
            return None
        rank = q * total
        cum = 0.0
        for ix, c in enumerate(counts):
            cum += c
            if cum >= rank and c > 0:
                if ix >= len(self.buckets):          # +Inf bucket
                    return self.buckets[-1]
                lo = self.buckets[ix - 1] if ix > 0 else 0.0
                hi = self.buckets[ix]
                frac = (rank - (cum - c)) / c
                return lo + (hi - lo) * frac
        return self.buckets[-1]


class _Family:
    """One metric family: kind + help + label names + children."""

    def __init__(self, name: str, help_: str, kind: str,
                 label_names: Sequence[str],
                 buckets: Sequence[float] = DEFAULT_BUCKETS_MS):
        self.name = name
        self.help = help_
        self.kind = kind                     # "counter" | "gauge" | "histogram"
        self.label_names = tuple(label_names)
        self._buckets = tuple(buckets)
        self._lock = threading.Lock()
        self._children: Dict[Tuple[str, ...], Any] = {}
        if not self.label_names:
            self._default = self._make()
            self._children[()] = self._default

    def _make(self):
        if self.kind == "counter":
            return Counter()
        if self.kind == "gauge":
            return Gauge()
        return Histogram(self._buckets)

    def labels(self, *values: Any):
        """The child for these label values (created on first use)."""
        if len(values) != len(self.label_names):
            raise ValueError(
                f"{self.name}: expected labels {self.label_names}, "
                f"got {values!r}")
        key = tuple(str(v) for v in values)
        child = self._children.get(key)
        if child is None:
            with self._lock:
                child = self._children.setdefault(key, self._make())
        return child

    # Label-less convenience: the family IS its single child.
    def inc(self, n: float = 1.0) -> None:
        self._children[()].inc(n)

    def set(self, v: float) -> None:
        self._children[()].set(v)

    def observe(self, v: float) -> None:
        self._children[()].observe(v)

    @property
    def value(self) -> float:
        return self._children[()].value

    def children(self) -> Dict[Tuple[str, ...], Any]:
        with self._lock:
            return dict(self._children)


class MetricsRegistry:
    """Named families; renders the whole set as Prometheus text."""

    def __init__(self):
        self._lock = threading.Lock()
        self._families: Dict[str, _Family] = {}

    def _family(self, name: str, help_: str, kind: str,
                labels: Sequence[str],
                buckets: Sequence[float] = DEFAULT_BUCKETS_MS) -> _Family:
        fam = self._families.get(name)
        if fam is not None:
            if fam.kind != kind or fam.label_names != tuple(labels):
                raise ValueError(
                    f"metric {name!r} re-registered as {kind}{tuple(labels)} "
                    f"(was {fam.kind}{fam.label_names})")
            return fam
        with self._lock:
            return self._families.setdefault(
                name, _Family(name, help_, kind, labels, buckets))

    def counter(self, name: str, help_: str = "",
                labels: Sequence[str] = ()) -> _Family:
        return self._family(name, help_, "counter", labels)

    def gauge(self, name: str, help_: str = "",
              labels: Sequence[str] = ()) -> _Family:
        return self._family(name, help_, "gauge", labels)

    def histogram(self, name: str, help_: str = "",
                  labels: Sequence[str] = (),
                  buckets: Sequence[float] = DEFAULT_BUCKETS_MS) -> _Family:
        return self._family(name, help_, "histogram", labels, buckets)

    def get(self, name: str) -> Optional[_Family]:
        return self._families.get(name)

    # -- exposition --------------------------------------------------------

    def render(self) -> str:
        """Prometheus text exposition (text/plain; version=0.0.4)."""
        lines: List[str] = []
        with self._lock:
            families = sorted(self._families.values(), key=lambda f: f.name)
        for fam in families:
            lines.append(f"# HELP {fam.name} {_escape(fam.help)}")
            lines.append(f"# TYPE {fam.name} {fam.kind}")
            for key, child in sorted(fam.children().items()):
                if fam.kind == "histogram":
                    cum = 0
                    for ix, bound in enumerate(child.buckets):
                        cum += child.counts[ix]
                        labels = _label_str(
                            fam.label_names + ("le",),
                            key + (_fmt(bound),))
                        lines.append(f"{fam.name}_bucket{labels} {cum}")
                    labels = _label_str(fam.label_names + ("le",),
                                        key + ("+Inf",))
                    lines.append(f"{fam.name}_bucket{labels} {child.count}")
                    base = _label_str(fam.label_names, key)
                    lines.append(f"{fam.name}_sum{base} {_fmt(child.sum)}")
                    lines.append(f"{fam.name}_count{base} {child.count}")
                else:
                    labels = _label_str(fam.label_names, key)
                    lines.append(f"{fam.name}{labels} {_fmt(child.value)}")
        return "\n".join(lines) + "\n"


# -- the metric registry ------------------------------------------------------
#
# Every dllm_* family the serving stack emits, declared ONCE as data:
# (attribute, kind, name, label names, help).  ServingMetrics
# materializes the rows; METRICS.md is generated from them
# (``python -m distributed_llm_tpu.obs.metrics > METRICS.md``); the
# ``metrics_discipline`` lint checker fails tier-1 when an emission
# site and this table disagree in either direction, and every label
# name must carry a cardinality bound in BOUNDED_LABELS below.  Rows
# are PURE LITERALS (the checker reads them from the AST).

METRIC_REGISTRY: Tuple[Tuple[str, str, str, Tuple[str, ...], str], ...] = (
    ("requests", "counter", "dllm_requests_total",
     ("strategy", "tier", "outcome"),
     "Requests completed, by strategy/tier/outcome (outcome: "
     "ok|error|degraded)"),
    ("ttft_ms", "histogram", "dllm_ttft_ms", ("strategy",),
     "Time to first token per request (engine-true when reported, else "
     "first observed token)"),
    ("tbt_ms", "histogram", "dllm_tbt_ms", ("strategy",),
     "Mean time between tokens per request"),
    ("queue_wait_ms", "histogram", "dllm_queue_wait_ms", ("tier",),
     "Submit-to-batch-slot-admission wait in the tier's engine"),
    ("request_ms", "histogram", "dllm_request_ms", ("strategy",),
     "End-to-end routed request wall time"),
    ("admission_rejected", "counter", "dllm_admission_rejected_total",
     ("tier",),
     "Requests shed by tier admission control"),
    ("retries", "counter", "dllm_retries_total", ("tier",),
     "Same-tier transient-error retries"),
    ("failovers", "counter", "dllm_failovers_total", ("tier", "kind"),
     "Tier failovers, by failed tier and kind (sync|stream_setup|"
     "mid_stream)"),
    ("breaker_transitions", "counter", "dllm_breaker_transitions_total",
     ("tier", "to"),
     "Circuit-breaker state transitions, by tier and target state"),
    ("breaker_state", "gauge", "dllm_breaker_state", ("tier",),
     "Circuit state per tier (0=closed, 1=half_open, 2=open)"),
    ("watchdog_wedged", "counter", "dllm_watchdog_wedged_total", ("tier",),
     "Decode-watchdog wedge declarations (health flips ok=False)"),
    ("cache_hits", "counter", "dllm_cache_hits_total", ("cache",),
     "Cache hits by tier of cache (response|response_degraded|"
     "routing|prefix_affinity)"),
    ("degraded", "counter", "dllm_degraded_total", (),
     "Requests served by the degraded path (all circuits open)"),
    ("flight_records", "counter", "dllm_flight_records_total", ("reason",),
     "Flight-recorder captures by reason (error|degraded|slow)"),
    # Resource-pressure family (PR 5): KV-aware admission, mid-decode
    # preemption with replay, context-overflow policy, graceful drain.
    ("preemptions", "counter", "dllm_preemptions_total", ("tier",),
     "Mid-decode slot preemptions under KV block starvation "
     "(victim replays byte-identically on re-admission)"),
    ("kv_admission_rejected", "counter", "dllm_kv_admission_rejected_total",
     ("tier",),
     "Requests shed because projected KV block demand exceeded "
     "free + reclaimable pool blocks"),
    ("overflow", "counter", "dllm_overflow_total", ("tier", "action"),
     "Context-overflow policy applications at the router, by tier "
     "and action (rejected|truncated)"),
    ("drained_requests", "counter", "dllm_drained_requests_total", ("tier",),
     "In-flight requests completed during a graceful drain"),
    # Ragged-decode family (PR 6): the serving path must SHOW which
    # attention kernel is actually running a tier's decode ticks and
    # what each tick costs — cross-round perf deltas get attributed
    # to a kernel, not guessed.
    ("decode_tick_ms", "histogram", "dllm_decode_tick_ms", ("tier",),
     "Batched decode tick device time (decode_steps_per_tick "
     "fused steps per observation)"),
    ("decode_ticks", "counter", "dllm_decode_ticks_total",
     ("tier", "kind", "impl"),
     "Batched decode ticks, by attention dispatch kind "
     "(ragged_decode|paged_decode[+_q8]) and the impl the "
     "measured table chose (xla|pallas)"),
    ("compiled_programs", "gauge", "dllm_compiled_programs",
     ("tier", "stage"),
     "Distinct compiled XLA programs the batched engine has "
     "minted, by stage (prefill|chunk_prefill|writer|decode) — "
     "decode pins at 1 under ragged attention; growth is logged"),
    # Chunked-prefill family (PR 9): long prompts are absorbed one
    # chunk per tick between decode ticks — the chunk histogram IS
    # the TBT bound the design promises (an active stream stalls at
    # most one chunk grant), and the backlog gauge shows a long
    # prompt mid-absorption behind a TTFT spike.
    ("prefill_chunk_ms", "histogram", "dllm_prefill_chunk_ms", ("tier",),
     "Device time of one interleaved prefill chunk — the upper "
     "bound a chunked admission adds to active streams' "
     "time-between-tokens per tick"),
    # Batched-speculation family (ISSUE 15): drafted vs accepted
    # draft tokens per tier (the counter pair whose ratio IS the
    # realized acceptance rate) and the engine's running acceptance
    # ratio mirrored by the system-state sampler — an operator reads
    # whether speculation is paying for its draft FLOPs without
    # diffing counters.
    ("spec_drafted", "counter", "dllm_spec_drafted_total", ("tier",),
     "Draft tokens proposed by batched speculative decoding "
     "(per-slot γ summed over rounds)"),
    ("spec_accepted", "counter", "dllm_spec_accepted_total", ("tier",),
     "Draft tokens accepted by the fused verify's greedy "
     "acceptance rule"),
    ("spec_accept_ratio_g", "gauge", "dllm_spec_accept_ratio", ("tier",),
     "Engine-lifetime accepted/drafted ratio for batched "
     "speculation (sampled; absent until the first draft)"),
    ("prefill_backlog_g", "gauge", "dllm_prefill_backlog", ("tier",),
     "Prompt tokens of the in-flight chunked prefill not yet "
     "absorbed (sampled by the system-state sampler; 0 = no "
     "prefill in flight)"),
    # System-state timeline family (PR 7, obs/sampler.py): the
    # background sampler mirrors its latest per-tier sample to these
    # gauges so dashboards plot the same series the timeline ring
    # stores.  The *_g attribute suffix keeps them apart from the
    # identically-themed request-path counters above.
    ("queue_depth_g", "gauge", "dllm_queue_depth", ("tier",),
     "Requests waiting beyond the tier's batch slots (sampled)"),
    ("active_slots_g", "gauge", "dllm_active_slots", ("tier",),
     "Busy batch slots per tier (sampled)"),
    ("max_slots_g", "gauge", "dllm_max_slots", ("tier",),
     "Configured batch slots per tier (sampled)"),
    ("kv_free_blocks_g", "gauge", "dllm_kv_free_blocks", ("tier",),
     "Free paged-KV pool blocks per tier (sampled)"),
    ("kv_reclaimable_blocks_g", "gauge", "dllm_kv_reclaimable_blocks",
     ("tier",),
     "Pool blocks reclaimable by evicting parked prefixes "
     "(sampled; under shared-prefix KV only refcount-1 blocks of "
     "unpinned entries count — what an eviction sweep could "
     "actually free)"),
    # Shared-prefix KV family (ISSUE 10): how much physical pool the
    # refcounted copy-on-write sharing is saving, and what kind of
    # prefix-cache hits admissions are taking.
    ("kv_shared_blocks_g", "gauge", "dllm_kv_shared_blocks", ("tier",),
     "Physical pool blocks with >= 2 holders (live slots mapping "
     "a shared prefix read-only and/or parked entries; sampled)"),
    ("kv_dedup_ratio_g", "gauge", "dllm_kv_dedup_ratio", ("tier",),
     "Logical block references / physical allocated blocks — the "
     "factor shared-prefix KV multiplies the effective pool by "
     "(1.0 = nothing shared; sampled)"),
    ("prefix_hits", "counter", "dllm_prefix_hits_total", ("tier", "kind"),
     "Prefix-cache lookup outcomes on the batched admit path, "
     "per admission attempt (shared = pinned read-only mapping, "
     "exclusive = take-ownership reuse, host = spill-tier "
     "promotion claim, miss = cold prefill)"),
    # Hierarchical-KV spill family (ISSUE 14, engine/kv_spill.py):
    # the host tier's occupancy and the demote/promote lifecycle —
    # warm TTFT as a function of host-RAM size must be observable,
    # and a promotion losing its race must be countable.
    ("kv_host_blocks_g", "gauge", "dllm_kv_host_blocks", ("tier",),
     "Pool-block equivalents of demoted prefix KV resident in "
     "the host spill tier (sampled)"),
    ("kv_host_bytes_g", "gauge", "dllm_kv_host_bytes", ("tier",),
     "Host bytes held by the KV spill tier against "
     "TierConfig.host_kv_bytes (sampled)"),
    ("kv_promote_backlog_g", "gauge", "dllm_kv_promote_backlog", ("tier",),
     "Blocks the in-flight promotion still has to land "
     "host→device (sampled; 0 = no promotion in flight)"),
    ("kv_demotions", "counter", "dllm_kv_demotions_total", ("tier",),
     "Prefix-cache evictions demoted to the host spill tier "
     "(copy landed; the async device→host copy drains on the "
     "spill copier, never the tick)"),
    ("kv_promotions", "counter", "dllm_kv_promotions_total", ("tier",),
     "Demoted prefixes promoted back to the device pool "
     "(budgeted host→device grants riding the chunked-prefill "
     "lane)"),
    ("kv_promotion_races", "counter", "dllm_kv_promotion_races_total",
     ("tier",),
     "Promotions that lost the race (entry invalidated / copier "
     "stalled) and fell back to a byte-identical cold prefill"),
    ("tier_draining_g", "gauge", "dllm_tier_draining", ("tier",),
     "1 while the tier is gracefully draining, else 0 (sampled)"),
    ("decode_tick_p50_g", "gauge", "dllm_decode_tick_p50_ms", ("tier",),
     "p50 decode-tick device time over the engine's recent-tick "
     "ring (sampled)"),
    # SLO / goodput family (PR 7, obs/slo.py): fed from the router's
    # exactly-once _finish_request exit (obs_discipline lint pins the
    # single feed site).
    ("slo_goodput", "gauge", "dllm_slo_goodput", ("strategy", "tier"),
     "Sliding-window fraction of requests meeting the tier's SLO "
     "(TTFT and p95 TBT targets)"),
    ("slo_violations", "counter", "dllm_slo_violations_total", ("kind",),
     "Requests missing their SLO, by kind (error|ttft|tbt)"),
    ("overload_incidents", "counter", "dllm_overload_incidents_total",
     ("tier",),
     "Rising-edge overload incidents (tier goodput under the "
     "floor); each lands in the flight recorder with a timeline "
     "slice"),
    # Tick-forensics family (ISSUE 11, obs/profiler.py): per-request
    # device-time / KV-residency attribution aggregated at the
    # router's exactly-once completion exit, plus sampled per-phase
    # tick breakdown gauges — the accounting substrate per-tenant
    # quotas and goodput-per-replica-second economics bill against.
    ("device_time", "counter", "dllm_device_time_ms_total",
     ("tier", "strategy", "session"),
     "Attributed decode device time (each tick's device ms "
     "divided across the slots it served), per serving tier, "
     "strategy and session ('-' = sessionless)"),
    ("kv_block_ticks", "counter", "dllm_kv_block_ticks_total",
     ("tier", "strategy", "session"),
     "Attributed KV residency: pool blocks held x decode ticks, "
     "shared prefix blocks charged 1/refcount to each holder"),
    ("tick_phase_p50_g", "gauge", "dllm_tick_phase_p50_ms",
     ("tier", "phase"),
     "p50 per-tick SELF time of one scheduler phase (admit|"
     "prefill|cow_copy|table_upload|decode|emit|chunk_prefill) "
     "over the profiler ring's recent tail (sampled)"),
    ("profile_coverage_g", "gauge", "dllm_profile_coverage", ("tier",),
     "Fraction of tick wall time covered by stamped phase self-"
     "times (sampled; the bench profile leg pins >= 0.95)"),
    # Replicated-tier family (ISSUE 12, serving/replicas.py): how
    # dispatch chose among a tier's engine replicas, and how much of
    # the tier's replica capacity is currently healthy.
    ("replica_routed", "counter", "dllm_replica_routed_total",
     ("tier", "policy"),
     "Requests dispatched to a tier replica, by how the replica "
     "was chosen (affinity|affinity_overridden|least_loaded|"
     "random|single|breaker_fallback)"),
    ("replica_healthy_g", "gauge", "dllm_replica_healthy", ("tier",),
     "Replicas of the tier currently serving (running, not "
     "wedged, breaker not open) out of TierConfig.replicas "
     "(sampled)"),
    # Crash-rescue family (ISSUE 20, serving/replicas.py
    # restart_replica): what happened to a restarted replica's
    # in-flight work and its host spill store.
    ("replica_rescues", "counter", "dllm_replica_rescues_total",
     ("tier", "outcome"),
     "Requests captured off a crashed/wedged replica at restart, "
     "by where they resumed (sibling = adopted by a live sibling "
     "replica, requeue = re-queued on the restarted engine, "
     "failed = no home — failed with the engine-stopped shape)"),
    ("spill_reattach", "counter", "dllm_spill_reattach_total",
     ("tier",),
     "Host KV spill stores that survived an engine restart and "
     "re-attached to the rebuilt engine (spill-state survival — "
     "restart cost is warm-TTFT promotion, not cold prefill)"),
    # Elastic-capacity family (ISSUE 18, serving/autoscaler.py):
    # live membership and the autoscaler's actuation decisions.
    ("replica_count_g", "gauge", "dllm_replica_count", ("tier",),
     "Live replica membership of the tier — static it equals "
     "TierConfig.replicas; under the autoscaler it moves between "
     "autoscale_min_replicas and autoscale_max_replicas "
     "(sampled)"),
    ("autoscale_events", "counter", "dllm_autoscale_events_total",
     ("tier", "direction", "reason"),
     "Autoscaler membership transitions, by direction (up|down) "
     "and the signal that fired them (goodput_floor|queue_growth"
     "|shed|idle|manual)"),
    # Per-tenant isolation family (ISSUE 17, serving/tenants.py):
    # the measured bill and enforcement decisions per tenant.  Every
    # ``tenant`` label value MUST pass through a BoundedLabels set
    # (64-char truncation, 256 distinct then '~overflow') — metric
    # children are permanent, so an unbounded tenant flood would
    # otherwise grow /metrics without bound.
    ("tenant_device_time", "counter", "dllm_tenant_device_time_ms_total",
     ("tier", "tenant"),
     "Attributed decode device time billed to the tenant "
     "(PR 11 per-request attribution, '-' = tenantless direct "
     "engine use)"),
    ("tenant_kv_block_ticks", "counter",
     "dllm_tenant_kv_block_ticks_total", ("tier", "tenant"),
     "Attributed KV residency billed to the tenant (blocks held "
     "x decode ticks at 1/refcount)"),
    ("tenant_rejected", "counter", "dllm_tenant_rejected_total",
     ("tier", "tenant"),
     "Requests shed by per-tenant quota enforcement (in-flight/"
     "queue caps, device-time token bucket, or KV budget)"),
    ("tenant_inflight_g", "gauge", "dllm_tenant_inflight",
     ("tier", "tenant"),
     "Requests a tenant currently has admitted against its "
     "quota (in flight or waiting)"),
    ("tenant_goodput_g", "gauge", "dllm_tenant_goodput", ("tenant",),
     "Sliding-window fraction of the tenant's requests meeting "
     "their SLO (obs/slo.py per-tenant windows)"),
)

# Every label name in METRIC_REGISTRY carries its cardinality bound
# here — metric children are permanent, so a label without a bound is
# a /metrics memory leak waiting for a hostile client.  The
# ``metrics_discipline`` checker fails tier-1 on a registry label
# missing from this table.  Closed sets are enforced by the emitting
# call sites; open (caller-supplied) sets MUST ride a BoundedLabels.

BOUNDED_LABELS: Dict[str, str] = {
    "strategy": "closed set: the router's routing strategies "
                "(serving/router.py STRATEGIES)",
    "tier": "closed set: config-enumerated tier names (TierConfig)",
    "outcome": "closed per-family enums (request outcomes ok|error|"
               "degraded; rescue outcomes sibling|requeue|failed)",
    "kind": "closed per-family enums (failover / dispatch / SLO-violation"
            " / prefix-hit kinds; see each family's help)",
    "to": "closed set: breaker states closed|half_open|open",
    "cache": "closed set: response|response_degraded|routing|"
             "prefix_affinity",
    "reason": "closed per-family enums (flight-record triggers, "
              "autoscale signals)",
    "action": "closed set: rejected|truncated",
    "impl": "closed set: xla|pallas",
    "stage": "closed set: prefill|chunk_prefill|writer|decode",
    "phase": "closed set: admit|prefill|cow_copy|table_upload|decode|"
             "emit|chunk_prefill",
    "session": "open set: BoundedLabels(cap=256) — 64-char truncation, "
               "257th distinct value collapses to '~overflow'",
    "tenant": "open set: BoundedLabels(cap=256) — 64-char truncation, "
              "257th distinct value collapses to '~overflow'",
    "policy": "closed set: affinity|affinity_overridden|least_loaded|"
              "random|single|breaker_fallback",
    "direction": "closed set: up|down",
}


class ServingMetrics:
    """The serving stack's standard metric set, materialized from
    METRIC_REGISTRY so the router, breaker hooks, engine managers,
    /metrics, and bench.py all read/write the same families (one
    assembler, no name drift — the table above is the only place a
    family is declared)."""

    def __init__(self, registry: MetricsRegistry):
        self.registry = registry
        for attr, kind, name, labels, help_ in METRIC_REGISTRY:
            setattr(self, attr, getattr(registry, kind)(
                name, help_, labels))


# -- METRICS.md generation ----------------------------------------------------

def render_markdown() -> str:
    """The METRICS.md body (pinned in sync by tests/test_lint.py)."""
    lines = [
        "# Metrics registry",
        "",
        "Generated from `distributed_llm_tpu/obs/metrics.py` "
        "(`python -m distributed_llm_tpu.obs.metrics > METRICS.md`).",
        "The `metrics_discipline` lint checker fails tier-1 when an "
        "emission site and this registry disagree in either direction.",
        "",
        "## Metric families (`dllm_*`)",
        "",
        "| Name | Kind | Labels | Semantics |",
        "|---|---|---|---|",
    ]

    def cell(text: str) -> str:
        return text.replace("|", "\\|")     # keep table cells intact

    for _attr, kind, name, labels, help_ in sorted(
            METRIC_REGISTRY, key=lambda r: r[2]):
        lab = ", ".join(f"`{x}`" for x in labels) if labels else "(none)"
        lines.append(f"| `{name}` | {kind} | {lab} | {cell(help_)} |")
    lines += [
        "",
        "## Label cardinality bounds",
        "",
        "Metric children are permanent; every label name above rides "
        "one of these bounds.",
        "",
        "| Label | Bound |",
        "|---|---|",
    ]
    for label in sorted(BOUNDED_LABELS):
        lines.append(f"| `{label}` | {cell(BOUNDED_LABELS[label])} |")
    return "\n".join(lines) + "\n"


class BoundedLabels:
    """Cardinality bound for caller-supplied metric label values — the
    PR 11 session-label policy, reusable: '-' when absent, values
    truncated to 64 chars, and past ``cap`` DISTINCT values every new
    one collapses to '~overflow'.  Metric children are permanent, so
    without this a client minting fresh tenant/session ids would grow
    /metrics (and every labeled family) without bound."""

    def __init__(self, cap: int = 256):
        self._cap = cap
        self._seen: set = set()
        self._lock = threading.Lock()

    def label(self, raw: Any) -> str:
        if not raw:
            return "-"
        s = str(raw)[:64]
        with self._lock:
            if s in self._seen:
                return s
            if len(self._seen) < self._cap:
                self._seen.add(s)
                return s
        return "~overflow"


_BREAKER_STATE_VALUE = {"closed": 0, "half_open": 1, "open": 2}


def breaker_state_value(state: str) -> int:
    return _BREAKER_STATE_VALUE.get(state, 0)


if __name__ == "__main__":
    import sys
    sys.stdout.write(render_markdown())
