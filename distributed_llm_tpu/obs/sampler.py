"""Continuous system-state timeline: a background sampler over the
serving stack's cheap load counters.

Request-level truth (spans, metrics, flight recorder — PR 3) answers
"what happened to THAT request"; this module answers "what was the
SYSTEM doing around it".  A ``SystemStateSampler`` snapshots, every
``period_s`` (default 250 ms), each tier's queue depth, slot occupancy,
KV pool pressure, preemption count, breaker state, draining flag, and
decode-tick p50 into a bounded ring of timestamped samples — the
trajectory an overload post-mortem needs (was the queue GROWING when the
request failed, or already draining?).

Three consumers:

- ``GET /metrics``: the latest sample is exported as gauges
  (``dllm_queue_depth{tier}`` etc.) so dashboards plot the same series
  the timeline stores.
- ``GET /stats?timeline=1``: the whole ring, for ad-hoc forensics.
- Flight-recorder entries and SLO overload incidents attach a tail
  slice, so a failed request carries the system TRAJECTORY around it,
  not just a point snapshot (serving/router.py
  ``_obs_state_snapshot`` / obs/slo.py).

Design constraints: the collect callback reads only lock-free /
own-locked in-memory counters (load_snapshot, kv_stats, tick ring — it
must NEVER touch the engine lifecycle lock, which a mid-compile engine
holds for minutes), one sample costs tens of microseconds (pinned by
tests/test_obs.py against the PR 3 < 1 ms observability budget), and the
thread is a daemon that stops cleanly on ``Router.drain`` — a drained
process must not keep a sampler alive.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

logger = logging.getLogger(__name__)

# Defaults; the serving layer overrides them from the registered
# DLLM_OBS_SAMPLE_MS / DLLM_OBS_TIMELINE_SAMPLES knobs.
DEFAULT_PERIOD_S = 0.25
DEFAULT_CAPACITY = 240          # 60 s of history at the default period

# Per-tier numeric fields mirrored to gauges each sample (field name ->
# ServingMetrics attribute).  Booleans export as 0/1; missing fields
# leave the gauge untouched (a stopped tier keeps its last value rather
# than faking a zero).
_GAUGE_FIELDS = (
    ("queue_depth", "queue_depth_g"),
    ("active_slots", "active_slots_g"),
    ("max_slots", "max_slots_g"),
    ("kv_free_blocks", "kv_free_blocks_g"),
    ("kv_reclaimable_blocks", "kv_reclaimable_blocks_g"),
    ("kv_shared_blocks", "kv_shared_blocks_g"),
    ("kv_dedup_ratio", "kv_dedup_ratio_g"),
    ("spec_accept_ratio", "spec_accept_ratio_g"),
    ("kv_host_blocks", "kv_host_blocks_g"),
    ("kv_host_bytes", "kv_host_bytes_g"),
    ("kv_promote_backlog", "kv_promote_backlog_g"),
    ("prefill_backlog_tokens", "prefill_backlog_g"),
    ("draining", "tier_draining_g"),
    ("decode_tick_p50_ms", "decode_tick_p50_g"),
    ("profile_coverage", "profile_coverage_g"),
    ("replica_healthy", "replica_healthy_g"),
    ("replica_count", "replica_count_g"),
)


class SystemStateSampler:
    """Bounded timeline of periodic system-state samples.

    ``collect`` is a zero-arg callable returning ``{tier_name: {field:
    value}}`` (serving/router.py ``_sampler_collect``); the sampler owns
    the cadence, the ring, and the gauge export.  ``metrics`` is an
    optional ``ServingMetrics`` for the gauge mirror.
    """

    def __init__(self, collect: Callable[[], Dict[str, Dict[str, Any]]],
                 metrics: Any = None,
                 period_s: float = DEFAULT_PERIOD_S,
                 capacity: int = DEFAULT_CAPACITY):
        self._collect = collect
        self._metrics = metrics
        self.period_s = max(0.02, float(period_s))
        self.capacity = max(8, int(capacity))
        self._ring: "deque[Dict[str, Any]]" = deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self._stop_evt = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.samples_total = 0
        # EWMA of one sample's wall cost (ms) — the overhead-budget
        # evidence the /stats surface and tests read.
        self.sample_cost_ms: Optional[float] = None

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        """Idempotent daemon-thread start (lazy: the serving layer calls
        this at first request, so constructed-and-dropped routers never
        spawn a thread)."""
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return
            self._stop_evt.clear()
            self._thread = threading.Thread(target=self._run, daemon=True,
                                            name="obs-sampler")
            self._thread.start()

    def stop(self, timeout_s: float = 2.0) -> None:
        """Stop and join the sampler thread (Router.drain path).  Bounded
        join: the thread is a daemon, so a wedged collect callback cannot
        block process exit either way."""
        self._stop_evt.set()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout=timeout_s)

    @property
    def running(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    def _run(self) -> None:
        while not self._stop_evt.wait(self.period_s):
            try:
                self.sample_once()
            except Exception:            # a bad sample must not kill the loop
                logger.exception("state sampler: sample failed")

    # -- sampling ----------------------------------------------------------

    def sample_once(self) -> Dict[str, Any]:    # dllm-lint: hot-path
        """Take one sample NOW (also the on-demand path for
        ``GET /stats?timeline=1`` on an idle router).  Hot-path root for
        the transfer lint: a sample must stay tens-of-microseconds cheap
        and must NEVER touch the device (a host sync here would stall
        the timeline behind a busy chip)."""
        t0 = time.perf_counter()
        try:
            tiers = self._collect() or {}
        except Exception:                # collect must never raise upward
            tiers = {}
        sample = {"ts": round(time.time(), 3), "tiers": tiers}
        self._export_gauges(tiers)
        cost_ms = (time.perf_counter() - t0) * 1000.0
        with self._lock:
            self._ring.append(sample)
            self.samples_total += 1
            self.sample_cost_ms = (cost_ms if self.sample_cost_ms is None
                                   else 0.8 * self.sample_cost_ms
                                   + 0.2 * cost_ms)
        return sample

    def _export_gauges(self, tiers: Dict[str, Dict[str, Any]]) -> None:
        m = self._metrics
        if m is None:
            return
        for name, st in tiers.items():
            for field, attr in _GAUGE_FIELDS:
                val = st.get(field)
                if val is None:
                    continue
                try:
                    getattr(m, attr).labels(name).set(float(val))
                except Exception:
                    pass
            # Tick-phase breakdown (ISSUE 11): the collect callback
            # hands a {phase: p50_self_ms} dict; each phase is its own
            # gauge child so dashboards plot the tick's composition as
            # stacked series.
            phases = st.get("tick_phases")
            if isinstance(phases, dict):
                for phase, val in phases.items():
                    if val is None:
                        continue
                    try:
                        m.tick_phase_p50_g.labels(name, phase).set(
                            float(val))
                    except Exception:
                        pass

    # -- read --------------------------------------------------------------

    def snapshot(self) -> List[Dict[str, Any]]:
        """Oldest-first copy of the ring (the /stats?timeline=1 body)."""
        with self._lock:
            return list(self._ring)

    def tail(self, n: int) -> List[Dict[str, Any]]:
        """The most recent ``n`` samples, oldest-first — the slice
        attached to flight-recorder entries and overload incidents."""
        with self._lock:
            if n <= 0 or not self._ring:
                return []
            return list(self._ring)[-n:]

    def slice_since(self, ts: float) -> List[Dict[str, Any]]:
        """Samples with ``sample["ts"] >= ts`` (incident windows)."""
        with self._lock:
            return [s for s in self._ring if s["ts"] >= ts]

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)
