"""Tick-phase profiler: where each scheduler tick's milliseconds go.

PR 7's observability answers "what happened" (goodput, queues,
incidents); this module answers "why, and who pays".  A
``TickProfiler`` lives on each ``ContinuousBatchingEngine`` and records,
per scheduler pass, a structured breakdown of the tick into phases —
admission/slot bookkeeping (``admit``), the prefill/suffix-chunk device
calls inside an admission (``prefill``), COW boundary copies
(``cow_copy``), host block-table uploads (``table_upload``), the fused
decode dispatch + its one sanctioned device sync (``decode``), token
fanout/detokenize (``emit``), and interleaved chunk-prefill grants
(``chunk_prefill``) — as a bounded ring of typed tick records.  Compile
events (``_note_compile``) and the justified admission-time host syncs
are stitched into the same timeline as instant events, so the
``retrace``/``transfer`` lint invariants get a dynamic counterpart: a
mid-serve compile or an unexpected sync shows up ON the timeline it
stalls.

Design constraints, in priority order:

- **Cheap when on.**  A phase stamp is two ``perf_counter`` calls and a
  list append on a stack the single scheduler thread owns — no locks,
  no allocation beyond the record tuples (the overhead pin in
  tests/test_profiler.py bounds the whole per-tick cost at ≤1% of the
  tiny-CPU tick p50).  Phase context managers are preallocated per
  name and reused; per-entry state lives on the profiler's stack, not
  the CM object.
- **Zero-cost when off.**  ``DLLM_PROFILE=0`` swaps in the shared
  ``NULL_PROFILER`` singleton: every stamp is a no-op method on a
  ``__slots__ = ()`` object returning a shared null context manager —
  the off path allocates nothing and records nothing, and the engine's
  attribution branch (gated on ``profiler.enabled``) never runs.
- **Never inside traced code.**  A ``perf_counter`` stamp inside a
  jit/pallas-traced function would bake one trace-time constant into
  the compiled program and measure nothing thereafter — the
  ``obs_discipline`` lint rule ``profiler-hook-in-traced-code``
  (lint/checkers/obs_discipline.py) statically forbids profiler calls
  anywhere in the project-wide traced closure.

**Self-time vs duration.**  Phases nest (``prefill`` runs inside
``admit``); each recorded span carries both its full duration (what the
Chrome trace renders as a nested slice) and its SELF time (duration
minus children).  Self-times partition the tick wall, so the per-phase
p50/p95 table and the ≥95%-coverage acceptance check sum self-times —
never double-counting a parent and its child.

**Attribution.**  The engine divides each decode tick's device time
evenly across the slots it served and charges every slot's
``RequestTrace`` (``spans.charge``) with its ``device_time_ms`` share
plus ``kv_block_ticks`` — blocks held × ticks, each block weighted
1/refcount so a shared prefix block (PR 10) bills 1/k to each of its k
holders.  The router's exactly-once ``_finish_request`` exit aggregates
the totals per (tier, strategy, session) into the
``dllm_device_time_ms_total`` / ``dllm_kv_block_ticks_total`` metric
families and the bounded cost ledger ``GET /stats`` exposes — the
accounting substrate per-tenant quotas (ROADMAP item 4) and
goodput-per-replica-second economics (item 5) bill against.

Export: ``chrome_trace`` renders any set of per-tier profiler snapshots
as Chrome-trace/Perfetto JSON (``GET /debug/trace``, the bench profile
leg's artifact) — one synthetic thread per tier, ticks as enclosing
slices, phases as properly nested child slices, compile/host-sync
events as instants.
"""

from __future__ import annotations

import time
from collections import deque
from typing import Any, Dict, List, Optional

# Canonical phase taxonomy (DESIGN.md "Tick forensics").  The profiler
# accepts any name — this tuple is the documented set the engine stamps
# and the bench table orders by.  ``demote``/``promote`` (ISSUE 14) are
# the hierarchical-KV spill tier's dispatch costs: the async gather
# snapshot of an evicted prefix and the host→device write-back grants —
# the device↔host DRAIN itself lives on the copier thread and never
# stamps a tick phase.
PHASES = ("admit", "prefill", "cow_copy", "table_upload", "decode",
          "draft", "verify", "emit", "chunk_prefill", "demote", "promote")

DEFAULT_CAPACITY = 512
EVENT_CAPACITY = 512


class _NullPhase:
    """Shared no-op context manager for the disabled profiler."""

    __slots__ = ()

    def __enter__(self) -> "_NullPhase":
        return self

    def __exit__(self, *exc) -> None:
        return None


_NULL_PHASE = _NullPhase()


class NullProfiler:
    """The ``DLLM_PROFILE=0`` twin: every stamp is a no-op on a shared
    singleton — the off path allocates nothing per call (the overhead
    test pins ``phase()`` returning the same object every time)."""

    __slots__ = ()
    enabled = False

    def phase(self, name: str) -> _NullPhase:
        return _NULL_PHASE

    def event(self, name: str, **attrs: Any) -> None:
        pass

    def commit(self, slots: int = 0) -> None:
        pass

    def records(self, last: Optional[int] = None) -> List[Dict[str, Any]]:
        return []

    def events(self) -> List[Any]:
        return []

    def snapshot(self) -> Dict[str, Any]:
        return {"records": [], "events": []}

    def phase_stats(self, last: Optional[int] = None) -> Dict[str, Any]:
        return {"phases": {}, "coverage": None, "ticks": 0, "totals": {}}

    def summary(self) -> Dict[str, Any]:
        return {"enabled": False}


NULL_PROFILER = NullProfiler()


class _Phase:
    """Reusable per-name context manager: enter/exit delegate to the
    profiler's stack, so one object serves every occurrence of its
    phase (nesting state lives on the stack, not here)."""

    __slots__ = ("_prof", "_name")

    def __init__(self, prof: "TickProfiler", name: str):
        self._prof = prof
        self._name = name

    def __enter__(self) -> "_Phase":
        self._prof._push(self._name)
        return self

    def __exit__(self, *exc) -> None:
        self._prof._pop()
        return None


class TickProfiler:
    """Bounded ring of per-tick phase breakdowns for ONE engine.

    Single-writer discipline: only the scheduler thread stamps phases
    and commits records (same ownership model as ``_slots`` and the
    ``tick_ms`` ring); readers (``records``/``phase_stats``/``summary``,
    the sampler, ``GET /debug/trace``) take advisory GIL-safe snapshots
    with the same retry-don't-block policy as ``tick_stats``."""

    enabled = True

    def __init__(self, tier: str = "", capacity: int = DEFAULT_CAPACITY):
        self.tier = tier
        self.capacity = max(16, int(capacity))
        self._ring: "deque[Dict[str, Any]]" = deque(maxlen=self.capacity)
        # Compile / host-sync instants, independent of tick records (a
        # warmup compile lands before any tick exists).  Own bounded
        # ring: (name, t_perf, attrs | None).
        self._events: "deque[tuple]" = deque(maxlen=EVENT_CAPACITY)
        self._cms: Dict[str, _Phase] = {}
        # Open-record state (scheduler thread only): phase stack entries
        # are [name, t0, child_seconds]; spans collect on _pop.
        self._stack: List[List[Any]] = []
        self._spans: List[tuple] = []
        self._t0: Optional[float] = None
        self._seq = 0
        # Lifetime per-phase self-time accumulators {name: [n, total_ms]}
        # — the attribution-conservation denominator must cover EVERY
        # tick ever served, not just the ring's tail.
        self._totals: Dict[str, List[float]] = {}

    # -- stamping (scheduler thread) ---------------------------------------

    def phase(self, name: str) -> _Phase:
        cm = self._cms.get(name)
        if cm is None:
            cm = self._cms[name] = _Phase(self, name)
        return cm

    def _push(self, name: str) -> None:
        now = time.perf_counter()
        if self._t0 is None:
            self._t0 = now
        self._stack.append([name, now, 0.0])

    def _pop(self) -> None:
        name, t0, child_s = self._stack.pop()
        now = time.perf_counter()
        dur_s = now - t0
        if self._stack:
            # The parent's self-time excludes this whole child.
            self._stack[-1][2] += dur_s
        self._spans.append((name, t0, dur_s, max(0.0, dur_s - child_s)))

    def event(self, name: str, **attrs: Any) -> None:
        """Instant event on the timeline (compile, sanctioned host
        sync).  Valid outside any tick — warmup compiles predate the
        first record."""
        self._events.append((name, time.perf_counter(),
                             attrs if attrs else None))

    def commit(self, slots: int = 0) -> None:
        """Close the open record (no-op when nothing was stamped this
        pass — idle loop passes leave no record)."""
        if self._t0 is None:
            return
        now = time.perf_counter()
        t0 = self._t0
        self._seq += 1
        spans = []
        for name, t, dur_s, self_s in self._spans:
            spans.append((name, (t - t0) * 1000.0, dur_s * 1000.0,
                          self_s * 1000.0))
            acc = self._totals.get(name)
            if acc is None:
                acc = self._totals[name] = [0, 0.0]
            acc[0] += 1
            acc[1] += self_s * 1000.0
        self._ring.append({
            "seq": self._seq,
            "t0": t0,
            "dur_ms": (now - t0) * 1000.0,
            "slots": slots,
            "spans": spans,
        })
        self._t0 = None
        self._spans = []
        # A raise mid-phase can strand stack entries past the `with`
        # that owns them only if the CM protocol itself was bypassed;
        # clear defensively so one bad pass cannot skew every later one.
        self._stack.clear()

    # -- reads (any thread; advisory snapshots) ----------------------------

    def _snap_ring(self, ring) -> List[Any]:
        """GIL-safe deque copy with the tick_stats retry policy: a
        concurrent append can abort one iteration pass — retry, and
        report empty rather than block or raise."""
        for _ in range(3):
            try:
                return list(ring)
            except RuntimeError:
                continue
        return []

    def records(self, last: Optional[int] = None) -> List[Dict[str, Any]]:
        recs = self._snap_ring(self._ring)
        if last is not None and last > 0:
            recs = recs[-last:]
        return recs

    def events(self) -> List[tuple]:
        return self._snap_ring(self._events)

    def snapshot(self) -> Dict[str, Any]:
        """Everything the Chrome-trace export needs for this engine."""
        return {"records": self.records(), "events": self.events()}

    def phase_stats(self, last: Optional[int] = None) -> Dict[str, Any]:
        """Per-phase self-time quantiles over the ring's tail plus the
        lifetime totals and the coverage fraction (self-time sum / tick
        wall sum) — the bench profile leg's table and the ≥95% coverage
        acceptance check."""
        from .metrics import nearest_rank
        recs = self.records(last)
        per_phase: Dict[str, List[float]] = {}
        wall = 0.0
        covered = 0.0
        for rec in recs:
            wall += rec["dur_ms"]
            by_name: Dict[str, float] = {}
            for name, _rel, _dur, self_ms in rec["spans"]:
                by_name[name] = by_name.get(name, 0.0) + self_ms
                covered += self_ms
            for name, ms in by_name.items():
                per_phase.setdefault(name, []).append(ms)
        phases = {}
        for name, vals in per_phase.items():
            vals.sort()
            phases[name] = {
                "n": len(vals),
                "p50_ms": round(nearest_rank(vals, 0.5, presorted=True), 4),
                "p95_ms": round(nearest_rank(vals, 0.95, presorted=True), 4),
                "total_ms": round(sum(vals), 3),
            }
        return {
            "phases": phases,
            "ticks": len(recs),
            "coverage": (round(covered / wall, 4) if wall > 0 else None),
            "totals": {name: {"n": int(acc[0]),
                              "total_ms": round(acc[1], 3)}
                       for name, acc in dict(self._totals).items()},
        }

    def total_ms(self, phase: str) -> float:
        """Lifetime self-time total for one phase (the attribution-
        conservation denominator in tests and the bench leg)."""
        acc = self._totals.get(phase)
        return float(acc[1]) if acc else 0.0

    def summary(self) -> Dict[str, Any]:
        """Cheap health()/GET /stats sideband: enabled flag, tick count,
        and coverage over the ring's recent tail."""
        st = self.phase_stats(last=64)
        return {"enabled": True, "ticks_recorded": self._seq,
                "ring": len(self._ring), "capacity": self.capacity,
                "coverage": st["coverage"]}


def make_profiler(tier: str = ""):
    """The engine's profiler, per the registered ``DLLM_PROFILE`` /
    ``DLLM_PROFILE_TICKS`` knobs: '0' → the shared zero-cost
    ``NULL_PROFILER``; anything else (default on) → a live ring."""
    from ..config_registry import env_int, env_str
    raw = (env_str("DLLM_PROFILE", "1") or "1").strip()
    if raw == "0":
        return NULL_PROFILER
    return TickProfiler(tier, capacity=env_int("DLLM_PROFILE_TICKS",
                                               DEFAULT_CAPACITY))


# =============================================================================
# Chrome-trace / Perfetto export
# =============================================================================

def chrome_trace(by_tier: Dict[str, Dict[str, Any]]) -> Dict[str, Any]:
    """Render per-tier profiler snapshots (``TickProfiler.snapshot``)
    as Chrome-trace JSON (the ``chrome://tracing`` / Perfetto "JSON
    Array Format" with metadata): one pid, one synthetic thread per
    tier, each tick an enclosing ``X`` slice with its phases as nested
    child slices (full durations — nesting is the point; self-times
    ride in ``args``), compile/host-sync events as ``i`` instants.

    Timestamps are microseconds from the earliest stamp across ALL
    tiers (perf_counter is one process-wide monotonic clock, so
    cross-tier ordering is real).  Deterministic output ordering:
    tiers sorted by name, events by timestamp within a tier."""
    # Global time origin: earliest stamp anywhere, so every ts >= 0.
    origin: Optional[float] = None
    for snap in by_tier.values():
        for rec in snap.get("records", ()):
            t = rec["t0"]
            origin = t if origin is None else min(origin, t)
        for ev in snap.get("events", ()):
            t = ev[1]
            origin = t if origin is None else min(origin, t)
    if origin is None:
        origin = 0.0

    def us(t_perf: float) -> float:
        return round((t_perf - origin) * 1e6, 1)

    events: List[Dict[str, Any]] = []
    for tid, name in enumerate(sorted(by_tier), start=1):
        snap = by_tier[name]
        events.append({"name": "thread_name", "ph": "M", "pid": 1,
                       "tid": tid, "args": {"name": f"tier:{name}"}})
        for rec in snap.get("records", ()):
            t0 = rec["t0"]
            events.append({
                "name": "tick", "ph": "X", "pid": 1, "tid": tid,
                "ts": us(t0), "dur": round(rec["dur_ms"] * 1000.0, 1),
                "args": {"seq": rec["seq"], "slots": rec["slots"]},
            })
            for span in rec.get("spans", ()):
                pname, rel_ms, dur_ms, self_ms = span
                events.append({
                    "name": pname, "ph": "X", "pid": 1, "tid": tid,
                    "ts": us(t0 + rel_ms / 1000.0),
                    "dur": round(dur_ms * 1000.0, 1),
                    "args": {"self_ms": round(self_ms, 4)},
                })
        for ev in snap.get("events", ()):
            ename, t, attrs = ev[0], ev[1], (ev[2] if len(ev) > 2 else None)
            events.append({
                "name": ename, "ph": "i", "pid": 1, "tid": tid,
                "ts": us(t), "s": "t", "args": dict(attrs or {}),
            })
    return {"traceEvents": events, "displayTimeUnit": "ms"}
