"""Request-level observability: span traces, metrics, flight recorder.

The bundle class ``Observability`` ties the three surfaces together:

- ``obs.trace(...)`` — a fresh per-request span tree (obs/spans.py),
  created by the router at request entry and threaded through tiers and
  engines (``spans.use_trace`` / ``spans.current_trace``).
- ``obs.m`` — the standard serving metric set (obs/metrics.py
  ServingMetrics) over ``obs.metrics``, rendered at ``GET /metrics``.
- ``obs.recorder`` — the failed/degraded/slow flight recorder
  (obs/recorder.py), dumped at ``GET /stats?debug=1``.

One process-global default instance (``get_observability()``) backs the
serving entry points and everything that lacks an injection path (the
engine managers' wedge counter, breaker hooks on default routers); the
Router takes an ``observability=`` override so tests and bench legs can
read from a registry no other traffic writes to.  ``DLLM_OBS_SLOW_MS``
tunes the global recorder's slow threshold (ms; empty/unset = 30000;
``0`` or ``off`` disables the slow trigger — failed/degraded requests
still record); ``DLLM_OBS_FLIGHT_CAPACITY`` sizes its ring.
"""

from __future__ import annotations

import os
import threading
from typing import Optional

from . import metrics, recorder, spans                       # noqa: F401
from .metrics import BoundedLabels, MetricsRegistry, ServingMetrics
from .recorder import FlightRecorder
from .spans import RequestTrace, current_trace, use_trace    # noqa: F401


class Observability:
    """One registry + metric set + recorder + trace factory."""

    def __init__(self, registry: Optional[MetricsRegistry] = None,
                 flight: Optional[FlightRecorder] = None,
                 slow_ms: Optional[float] = 30000.0,
                 flight_capacity: Optional[int] = None):
        from .recorder import DEFAULT_CAPACITY
        self.metrics = registry if registry is not None else MetricsRegistry()
        self.m = ServingMetrics(self.metrics)
        # ONE tenant-label bound per registry: every dllm_tenant_*
        # write site (router billing, SLO windows, quota registries)
        # funnels tenant ids through this so the label space they share
        # stays consistent AND cardinality-bounded (ISSUE 17).
        self.tenant_labels = BoundedLabels()
        self.recorder = (flight if flight is not None
                         else FlightRecorder(
                             capacity=(flight_capacity
                                       if flight_capacity is not None
                                       else DEFAULT_CAPACITY),
                             slow_ms=slow_ms))

    def trace(self, name: str = "request", **attrs) -> RequestTrace:
        return RequestTrace(name, **attrs)


_GLOBAL: Optional[Observability] = None
_GLOBAL_LOCK = threading.Lock()


def get_observability() -> Observability:
    """The process-global default bundle (created on first use)."""
    global _GLOBAL
    if _GLOBAL is None:
        with _GLOBAL_LOCK:
            if _GLOBAL is None:
                from ..config_registry import env_int, env_str
                raw = (env_str("DLLM_OBS_SLOW_MS", "") or "") \
                    .strip().lower()
                slow_ms: Optional[float] = 30000.0
                if raw in ("off", "none"):
                    slow_ms = None
                elif raw:
                    try:
                        slow_ms = float(raw)
                    except ValueError:
                        slow_ms = 30000.0
                    else:
                        # 0-disables, matching the repo's convention
                        # (breaker_failures=0 etc.) — a zero threshold
                        # would otherwise record EVERY request and evict
                        # the post-mortems the ring exists to keep.
                        if slow_ms <= 0:
                            slow_ms = None
                _GLOBAL = Observability(
                    slow_ms=slow_ms,
                    flight_capacity=max(1, env_int(
                        "DLLM_OBS_FLIGHT_CAPACITY", 32)))
    return _GLOBAL
