"""Flight recorder: the last N interesting requests, in full.

Aggregates (obs/metrics.py) answer "how often / how slow"; the flight
recorder answers "what exactly happened to THAT request": a bounded ring
buffer retaining the complete span tree plus the serving stack's state
snapshot (tier load, breaker states) for the last ``capacity``
failed / degraded / slow requests.  Retrieval: ``GET /stats?debug=1``
(serving/app.py) — the post-mortem surface for a request that timed out
or got degraded service hours ago on a box nobody was watching.

Healthy-fast requests are deliberately NOT retained: at serving rates
the interesting requests are a trickle and the boring ones are a flood;
recording everything would evict the post-mortem material the recorder
exists to keep.  The ``slow_ms`` threshold marks "slow" (None disables
the slow trigger; failed/degraded always record).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from .spans import RequestTrace

DEFAULT_CAPACITY = 32
# Incidents (obs/slo.py overload windows) keep a ring of their OWN: an
# overload storm floods the request ring with hundreds of per-request
# error entries in seconds, and the one record that EXPLAINS them — the
# incident with its timeline slice — must not be evicted by its own
# symptoms.
INCIDENT_CAPACITY = 8


class FlightRecorder:
    def __init__(self, capacity: int = DEFAULT_CAPACITY,
                 slow_ms: Optional[float] = 30000.0):
        self.capacity = max(1, int(capacity))
        self.slow_ms = slow_ms
        self._lock = threading.Lock()
        self._ring: "deque[Dict[str, Any]]" = deque(maxlen=self.capacity)
        self._incidents: "deque[Dict[str, Any]]" = deque(
            maxlen=INCIDENT_CAPACITY)
        self.recorded_total = 0

    def classify(self, ok: bool, degraded: bool,
                 duration_ms: Optional[float]) -> Optional[str]:
        """The capture reason for a finished request, or None (don't
        record).  Degraded outranks error (it carries more state worth
        keeping); slow only applies to otherwise-healthy requests."""
        if degraded:
            return "degraded"
        if not ok:
            return "error"
        if (self.slow_ms is not None and duration_ms is not None
                and duration_ms >= self.slow_ms):
            return "slow"
        return None

    def record(self, reason: str, trace: RequestTrace,
               snapshot: Optional[Dict[str, Any]] = None) -> None:
        """Retain one request (trace serialized NOW — span objects must
        not outlive this call's view of them)."""
        entry = {
            "ts": round(time.time(), 3),
            "reason": reason,
            "trace": trace.to_dict(),
        }
        if snapshot:
            entry["state"] = snapshot
        with self._lock:
            self.recorded_total += 1
            entry["seq"] = self.recorded_total   # capture order, ts ties
            self._ring.append(entry)

    # -- incident records (obs/slo.py overload lifecycle) ------------------

    def record_incident(self, kind: str,
                        info: Dict[str, Any]) -> Dict[str, Any]:
        """Retain one traceless incident (e.g. an SLO overload window).
        Returns the ring entry so the caller can finalize it in place
        via ``update_incident`` when the incident closes — an incident
        is recorded at its RISING edge (a process that dies mid-overload
        must already have it on the post-mortem surface)."""
        entry = {
            "ts": round(time.time(), 3),
            "reason": kind,
            "incident": dict(info),
        }
        with self._lock:
            self.recorded_total += 1
            entry["seq"] = self.recorded_total
            self._incidents.append(entry)
        return entry

    def update_incident(self, entry: Dict[str, Any], **info: Any) -> None:
        """Finalize a live incident entry.  The ``incident`` value is
        REPLACED (not mutated): a concurrent ``snapshot`` serializer
        holding the old dict sees a complete earlier view, never a
        half-updated one."""
        with self._lock:
            entry["incident"] = {**entry.get("incident", {}), **info}

    def snapshot(self) -> List[Dict[str, Any]]:
        """Most-recent-first copy of BOTH rings, merged by timestamp
        (the /stats?debug=1 body).  Shallow-copied entries: incident
        finalization swaps top-level values on live entries, and a
        serializer must not iterate a dict being rebound under it."""
        with self._lock:
            merged = list(self._ring) + list(self._incidents)
        merged.sort(key=lambda e: e.get("seq", 0), reverse=True)
        return [dict(e) for e in merged]

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)
