"""Flight recorder: the last N interesting requests, in full.

Aggregates (obs/metrics.py) answer "how often / how slow"; the flight
recorder answers "what exactly happened to THAT request": a bounded ring
buffer retaining the complete span tree plus the serving stack's state
snapshot (tier load, breaker states) for the last ``capacity``
failed / degraded / slow requests.  Retrieval: ``GET /stats?debug=1``
(serving/app.py) — the post-mortem surface for a request that timed out
or got degraded service hours ago on a box nobody was watching.

Healthy-fast requests are deliberately NOT retained: at serving rates
the interesting requests are a trickle and the boring ones are a flood;
recording everything would evict the post-mortem material the recorder
exists to keep.  The ``slow_ms`` threshold marks "slow" (None disables
the slow trigger; failed/degraded always record).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from .spans import RequestTrace

DEFAULT_CAPACITY = 32


class FlightRecorder:
    def __init__(self, capacity: int = DEFAULT_CAPACITY,
                 slow_ms: Optional[float] = 30000.0):
        self.capacity = max(1, int(capacity))
        self.slow_ms = slow_ms
        self._lock = threading.Lock()
        self._ring: "deque[Dict[str, Any]]" = deque(maxlen=self.capacity)
        self.recorded_total = 0

    def classify(self, ok: bool, degraded: bool,
                 duration_ms: Optional[float]) -> Optional[str]:
        """The capture reason for a finished request, or None (don't
        record).  Degraded outranks error (it carries more state worth
        keeping); slow only applies to otherwise-healthy requests."""
        if degraded:
            return "degraded"
        if not ok:
            return "error"
        if (self.slow_ms is not None and duration_ms is not None
                and duration_ms >= self.slow_ms):
            return "slow"
        return None

    def record(self, reason: str, trace: RequestTrace,
               snapshot: Optional[Dict[str, Any]] = None) -> None:
        """Retain one request (trace serialized NOW — span objects must
        not outlive this call's view of them)."""
        entry = {
            "ts": round(time.time(), 3),
            "reason": reason,
            "trace": trace.to_dict(),
        }
        if snapshot:
            entry["state"] = snapshot
        with self._lock:
            self._ring.append(entry)
            self.recorded_total += 1

    def snapshot(self) -> List[Dict[str, Any]]:
        """Most-recent-first copy of the ring (the /stats?debug=1 body)."""
        with self._lock:
            return list(reversed(self._ring))

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)
