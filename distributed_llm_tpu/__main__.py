"""``python -m distributed_llm_tpu`` — serve the chat API.

Convenience launcher for the Flask app (serving/app.py): the same
``/chat`` + ``/history`` + ``/stats`` + ``/ui`` surface the reference
exposes on :8000 (reference: ``python src/app.py``).
"""

from .serving.app import main

if __name__ == "__main__":
    main()
