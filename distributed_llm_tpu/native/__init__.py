"""Native (C++) runtime components, bound over ctypes.

The compute path is XLA; this package holds the host-side hot loops that
warrant native code (SURVEY.md §2.1 — the reference outsources ALL native
work to llama.cpp; here the equivalents we own live in-tree).  Currently:

- ``featurizer.cc`` — hashed n-gram text features for the routing embedder
  (runs on every routed query and semantic-cache lookup).
- ``bpe_encoder.cc`` — the subword tokenizer's merge loop (engine/bpe.py):
  runs on every request's prompt AND every routing token count; the
  Python twin stays the reference semantics and the non-ASCII path.

The library auto-builds with g++ on first import (cached next to the
source), and everything degrades to the pure-Python implementations when
no toolchain is available or DLLM_NATIVE=0 is set — behavior is
bit-identical either way, only speed changes.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import Optional, Sequence

import numpy as np

logger = logging.getLogger(__name__)

_SRC_DIR = os.path.dirname(os.path.abspath(__file__))
_SOURCES = [os.path.join(_SRC_DIR, "featurizer.cc"),
            os.path.join(_SRC_DIR, "bpe_encoder.cc")]
_SRC = _SOURCES[0]                       # kept for log/messages
_LIB = os.path.join(_SRC_DIR, "_libdllm.so")
_ABI_VERSION = 2

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build() -> bool:
    # Compile to a process-unique temp file, then atomically publish:
    # concurrent first-imports must never CDLL a half-written ELF.
    tmp = f"{_LIB}.tmp.{os.getpid()}"
    cmd = (["g++", "-O3", "-shared", "-fPIC", "-std=c++17"]
           + _SOURCES + ["-o", tmp])
    try:
        res = subprocess.run(cmd, capture_output=True, text=True, timeout=120)
        if res.returncode != 0:
            logger.warning("native build failed:\n%s", res.stderr[-2000:])
            return False
        os.replace(tmp, _LIB)
        return True
    except (OSError, subprocess.TimeoutExpired) as exc:
        logger.info("native build unavailable (%s); using Python fallback", exc)
        return False
    finally:
        if os.path.exists(tmp):
            try:
                os.unlink(tmp)
            except OSError:
                pass


def _load() -> Optional[ctypes.CDLL]:
    """Load (building if needed) the native library; None → fallback.
    ANY failure — missing source, stale .so without the expected symbols,
    read-only install dir — degrades to the Python path, never raises."""
    global _lib, _tried
    if _tried:                    # lock-free fast path (hot per query)
        return _lib
    with _lock:
        if _tried:
            return _lib
        lib = None
        try:
            if os.environ.get("DLLM_NATIVE") != "0":
                stale = (os.path.exists(_LIB)
                         and any(os.path.exists(s)
                                 and os.path.getmtime(_LIB)
                                 < os.path.getmtime(s) for s in _SOURCES))
                if (not os.path.exists(_LIB) or stale) and not _build():
                    raise OSError("native build unavailable")
                lib = ctypes.CDLL(_LIB)
                # A missing symbol means a corrupt or pre-ABI binary —
                # recover exactly like a version mismatch: rebuild.
                ver_fn = getattr(lib, "dllm_abi_version", None)
                if ver_fn is None or ver_fn() != _ABI_VERSION:
                    logger.warning("native ABI stale/corrupt; rebuilding")
                    os.unlink(_LIB)
                    if not _build():
                        raise OSError("rebuild failed")
                    lib = ctypes.CDLL(_LIB)
                lib.dllm_featurize_batch.argtypes = [
                    ctypes.POINTER(ctypes.c_char_p), ctypes.c_int,
                    ctypes.POINTER(ctypes.c_float), ctypes.c_int]
                lib.dllm_bpe_load.argtypes = [
                    ctypes.POINTER(ctypes.c_int32), ctypes.c_int]
                lib.dllm_bpe_load.restype = ctypes.c_int
                lib.dllm_bpe_encode.argtypes = [
                    ctypes.c_int, ctypes.c_char_p, ctypes.c_int,
                    ctypes.POINTER(ctypes.c_int32), ctypes.c_int]
                lib.dllm_bpe_encode.restype = ctypes.c_int
        except Exception as exc:
            logger.info("native featurizer unavailable (%s); "
                        "using Python fallback", exc)
            lib = None
        _lib = lib
        _tried = True             # published last: gates the fast path
        return _lib


def available() -> bool:
    return _load() is not None


def bpe_load(merges: Sequence[Sequence[int]]) -> Optional[int]:
    """Register a merge table; returns an encode handle, or None when the
    native library is unavailable."""
    lib = _load()
    if lib is None:
        return None
    flat = np.asarray(merges, dtype=np.int32).reshape(-1)
    return int(lib.dllm_bpe_load(
        flat.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        len(merges)))


def bpe_encode(handle: int, text: str) -> Optional[list]:
    """Encode ASCII ``text`` with a registered merge table.  None on any
    failure (caller falls back to the Python path)."""
    lib = _load()
    if lib is None:
        return None
    data = text.encode("utf-8")
    out = np.empty(max(len(data), 1), dtype=np.int32)
    n = lib.dllm_bpe_encode(
        handle, data, len(data),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), out.size)
    if n < 0:
        return None
    return out[:n].tolist()


def featurize_batch(texts: Sequence[str], dim: int) -> Optional[np.ndarray]:
    """[n, dim] float32 hashed-ngram features, or None if native is
    unavailable (caller falls back to the Python implementation)."""
    lib = _load()
    if lib is None:
        return None
    n = len(texts)
    out = np.zeros((n, dim), dtype=np.float32)
    arr = (ctypes.c_char_p * n)(*[t.encode("utf-8") for t in texts])
    lib.dllm_featurize_batch(
        arr, n, out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), dim)
    return out
