// Native featurizer: hashed n-gram text features for the routing embedder.
//
// The reference's only native code is llama.cpp behind Ollama (SURVEY.md
// §2.1); in this framework the model math runs under XLA, and the remaining
// host-side hot loop is routing/embedder.py::_features — per-word hashing
// executed on EVERY routed query and cache lookup (the reference's analogue
// is SentenceTransformer.encode, its hot loop (b) in SURVEY.md §3.1).  This
// file is that loop in C++17, exposed over a C ABI consumed via ctypes
// (no pybind11 in the image).
//
// Parity contract with the Python fallback (routing/embedder.py) is EXACT:
// same CRC-32 (zlib polynomial) hashing, same tokenization
// ([a-z0-9']+ runs over lowercased bytes), same possessive stripping,
// same stopword set and weights — tests assert bit-identical vectors.
//
// Build: g++ -O3 -shared -fPIC -std=c++17 featurizer.cc -o _libdllm.so
// (auto-built by native/__init__.py; pure-Python fallback when no
// toolchain is present).

#include <cstdint>
#include <cstring>
#include <string>
#include <unordered_set>
#include <vector>

namespace {

// CRC-32 (IEEE 802.3, the zlib/crc32 polynomial), table-driven — must match
// Python's zlib.crc32 exactly.
uint32_t kCrcTable[256];
bool kCrcInit = []() {
  for (uint32_t i = 0; i < 256; ++i) {
    uint32_t c = i;
    for (int k = 0; k < 8; ++k)
      c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
    kCrcTable[i] = c;
  }
  return true;
}();

uint32_t Crc32(const std::string& s) {
  uint32_t c = 0xFFFFFFFFu;
  for (unsigned char ch : s) c = kCrcTable[(c ^ ch) & 0xFFu] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

const std::unordered_set<std::string>& Stopwords() {
  static const std::unordered_set<std::string> kSet = {
      "a", "an", "and", "are", "as", "at", "be", "but", "by", "can", "could",
      "did", "do", "does", "for", "from", "had", "has", "have", "he", "her",
      "his", "how", "i", "if", "in", "is", "it", "its", "may", "me", "my",
      "of", "on", "or", "our", "she", "should", "so", "that", "the", "their",
      "them", "they", "this", "to", "us", "was", "we", "were", "what", "when",
      "where", "which", "who", "why", "will", "with", "would", "you", "your"};
  return kSet;
}

// double, not float: the Python reference does its weight arithmetic in
// float64 and only rounds on store into the float32 vector — bit parity
// requires the same (e.g. 0.4*0.15 differs between fp32 and fp64 rounding).
constexpr double kStopWeight = 0.15;
constexpr double kBigramWeight = 0.4;
constexpr double kTrigramWeight = 0.15;

// [a-z0-9']+ runs over bytewise-lowercased input (non-ASCII bytes are
// delimiters, matching the Python regex on ASCII-range text).
std::vector<std::string> Tokenize(const char* text) {
  std::vector<std::string> words;
  std::string cur;
  for (const unsigned char* p = (const unsigned char*)text; *p; ++p) {
    unsigned char c = *p;
    if (c >= 'A' && c <= 'Z') c += 'a' - 'A';
    if ((c >= 'a' && c <= 'z') || (c >= '0' && c <= '9') || c == '\'') {
      cur.push_back((char)c);
    } else if (!cur.empty()) {
      words.push_back(std::move(cur));
      cur.clear();
    }
  }
  if (!cur.empty()) words.push_back(std::move(cur));

  // Possessive stripping: trailing "'s" dropped, other apostrophes removed.
  for (auto& w : words) {
    size_t n = w.size();
    if (n >= 2 && w[n - 2] == '\'' && w[n - 1] == 's') {
      w.resize(n - 2);
    } else {
      std::string out;
      out.reserve(n);
      for (char ch : w)
        if (ch != '\'') out.push_back(ch);
      w = std::move(out);
    }
  }
  return words;
}

void Bump(float* vec, int dim, const std::string& token, double weight) {
  uint32_t h = Crc32(token);
  double sign = ((h >> 16) & 1u) ? 1.0 : -1.0;
  uint32_t idx = h % (uint32_t)dim;
  vec[idx] = (float)((double)vec[idx] + sign * weight);
}

}  // namespace

extern "C" {

// Fill out[dim] with the signed hashed bag of word 1/2-grams + char
// trigrams for one text.  out must be zeroed by the caller.
void dllm_featurize(const char* text, float* out, int dim) {
  const auto& stop = Stopwords();
  std::vector<std::string> words = Tokenize(text);

  for (const auto& w : words)
    Bump(out, dim, "u:" + w, stop.count(w) ? kStopWeight : 1.0);

  for (size_t i = 0; i + 1 < words.size(); ++i) {
    double wgt = kBigramWeight;
    if (stop.count(words[i]) && stop.count(words[i + 1])) wgt *= kStopWeight;
    Bump(out, dim, "b:" + words[i] + "_" + words[i + 1], wgt);
  }

  std::string squashed;
  for (const auto& w : words)
    if (!stop.count(w)) squashed += w;
  for (size_t i = 0; i + 2 < squashed.size(); ++i)
    Bump(out, dim, "c:" + squashed.substr(i, 3), kTrigramWeight);
}

// Batch entry: texts[n] NUL-terminated strings -> out[n * dim], zeroed by
// the caller.
void dllm_featurize_batch(const char** texts, int n, float* out, int dim) {
  for (int i = 0; i < n; ++i) dllm_featurize(texts[i], out + (size_t)i * dim, dim);
}

int dllm_abi_version() { return 2; }   // 2: + bpe_encoder.cc

}  // extern "C"
