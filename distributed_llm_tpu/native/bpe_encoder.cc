// Native BPE encode hot loop (engine/bpe.py's C++ twin).
//
// Tokenization runs on the host for every request (and again for every
// routing token count); the merge loop is the only quadratic-ish piece of
// that path, so it gets the native treatment like the routing featurizer.
// Semantics are BIT-IDENTICAL to BPETokenizer._encode_chunk for ASCII
// input (the Python caller only routes ASCII here: C byte-wise isspace
// and Python's unicode-aware \s agree exactly on ASCII):
//
//   chunks   = /\s*\S+|\s+$/  (a word plus its leading whitespace)
//   per chunk: repeatedly merge the LOWEST-RANK adjacent pair, merging
//   every occurrence of that pair in the chunk, until no pair has a rank.
//
// Merge tables are registered per tokenizer instance and addressed by
// handle, so differently-trained vocabularies (tests train tiny ones)
// coexist in one process.

#include <cstdint>
#include <deque>
#include <mutex>
#include <unordered_map>
#include <vector>

namespace {

constexpr int32_t kFirstMergeId = 259;   // engine/bpe.py _FIRST_MERGE_ID

std::mutex g_mu;
// deque: push_back never moves existing elements, so a table reference
// taken under the lock stays valid while another thread registers a new
// tokenizer's table concurrently.
std::deque<std::unordered_map<uint64_t, int32_t>>* g_tables =
    new std::deque<std::unordered_map<uint64_t, int32_t>>();

inline uint64_t pair_key(int32_t a, int32_t b) {
  return (static_cast<uint64_t>(static_cast<uint32_t>(a)) << 32) |
         static_cast<uint32_t>(b);
}

void encode_chunk(const std::unordered_map<uint64_t, int32_t>& ranks,
                  std::vector<int32_t>& ids) {
  while (ids.size() > 1) {
    int32_t best_rank = INT32_MAX;
    for (size_t i = 0; i + 1 < ids.size(); ++i) {
      auto it = ranks.find(pair_key(ids[i], ids[i + 1]));
      if (it != ranks.end() && it->second < best_rank) best_rank = it->second;
    }
    if (best_rank == INT32_MAX) break;
    // Rebuild with EVERY occurrence of the winning pair merged — same as
    // the Python reference's inner rewrite loop.
    int32_t target_rank = best_rank;
    int32_t a = 0, b = 0;
    for (size_t i = 0; i + 1 < ids.size(); ++i) {
      auto it = ranks.find(pair_key(ids[i], ids[i + 1]));
      if (it != ranks.end() && it->second == target_rank) {
        a = ids[i];
        b = ids[i + 1];
        break;
      }
    }
    const int32_t new_id = kFirstMergeId + target_rank;
    std::vector<int32_t> out;
    out.reserve(ids.size());
    for (size_t i = 0; i < ids.size();) {
      if (i + 1 < ids.size() && ids[i] == a && ids[i + 1] == b) {
        out.push_back(new_id);
        i += 2;
      } else {
        out.push_back(ids[i]);
        i += 1;
      }
    }
    ids.swap(out);
  }
}

// Python's \s on ASCII: space, \t-\r (0x09-0x0D), AND the file/group/
// record/unit separators 0x1C-0x1F ('\x1c'.isspace() is True).  C's
// isspace() misses the latter, which would silently split chunks
// differently from the Python reference on log-like input.
inline bool is_ws(uint8_t c) {
  return c == 0x20 || (c >= 0x09 && c <= 0x0D) || (c >= 0x1C && c <= 0x1F);
}

}  // namespace

extern "C" {

// Register a merge table (pairs = [a0,b0,a1,b1,...], rank = index).
// Returns a handle for dllm_bpe_encode.
int dllm_bpe_load(const int32_t* pairs, int n_merges) {
  std::unordered_map<uint64_t, int32_t> table;
  table.reserve(static_cast<size_t>(n_merges) * 2);
  for (int i = 0; i < n_merges; ++i)
    table.emplace(pair_key(pairs[2 * i], pairs[2 * i + 1]), i);
  std::lock_guard<std::mutex> lk(g_mu);
  g_tables->push_back(std::move(table));
  return static_cast<int>(g_tables->size()) - 1;
}

// Encode `len` bytes of ASCII text into `out` (capacity `cap` ids).
// Returns the id count, or -1 on bad handle / overflow (caller falls
// back to Python).
int dllm_bpe_encode(int handle, const uint8_t* text, int len, int32_t* out,
                    int cap) {
  const std::unordered_map<uint64_t, int32_t>* ranks;
  {
    std::lock_guard<std::mutex> lk(g_mu);
    if (handle < 0 || handle >= static_cast<int>(g_tables->size())) return -1;
    ranks = &(*g_tables)[handle];
  }
  int n_out = 0;
  std::vector<int32_t> ids;
  int i = 0;
  while (i < len) {
    const int start = i;
    while (i < len && is_ws(text[i])) ++i;
    while (i < len && !is_ws(text[i])) ++i;
    // A pure-whitespace tail is its own chunk (/\s+$/), same as Python.
    if (i == start) break;
    ids.assign(text + start, text + i);
    encode_chunk(*ranks, ids);
    if (n_out + static_cast<int>(ids.size()) > cap) return -1;
    for (int32_t id : ids) out[n_out++] = id;
  }
  return n_out;
}

}  // extern "C"
