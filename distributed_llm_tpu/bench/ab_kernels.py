"""A/B the attention kernel implementations on the current backend.

Usage::

    python -m distributed_llm_tpu.bench.ab_kernels [--tier nano|orin]
        [--prompt-tokens N] [--max-new N] [--repeat K]

For each ``DLLM_ATTENTION`` setting (xla, pallas) this builds a fresh
bench-tier engine, warms it, and measures steady-state TTFT (prefill) and
decode tok/s over ``--repeat`` generations, printing one JSON line per
impl plus a verdict.  This is the measurement behind bench.py's default
attention pin — rerun it whenever the kernel set or jax version changes.

The engines are built sequentially in ONE process (the chip allows a
single claimant); DLLM_ATTENTION is read at trace time, so each engine is
constructed after the env var is set and dropped before the next.
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import time


def measure(impl: str, tier_name: str, prompt_tokens: int, max_new: int,
            repeat: int) -> dict:
    os.environ["DLLM_ATTENTION"] = impl
    import dataclasses

    import jax

    from ..config import bench_cluster, tiny_cluster
    from ..engine.inference import InferenceEngine

    cluster = (tiny_cluster() if jax.default_backend() == "cpu"
               else bench_cluster())
    # Prefix reuse OFF: this harness measures the cold prefill kernels
    # (PrefixCache.take matches even a diverging entry's shared prefix, so
    # any repeat would otherwise prefill a ~1-bucket suffix, not the
    # prompt).  Belt and braces, the prompt HEAD varies per iteration too.
    tier = dataclasses.replace(getattr(cluster, tier_name),
                               enable_prefix_cache=False)
    engine = InferenceEngine(tier, seed=0)
    engine.warmup()

    filler = "user: " + ("benchmark the attention kernels now. " * 400)
    ttfts, tokps = [], []
    for i in range(repeat):
        # Head-varied per iteration, sliced AFTER prepending so the total
        # stays at the requested token count (byte-level tokenizer:
        # chars ≈ tokens) and lands in the intended prefill bucket.
        prompt = (f"variant {i} " + filler)[:prompt_tokens]
        res = engine.generate(prompt, max_new_tokens=max_new)
        ttfts.append(res.ttft_ms)
        if res.tokens_per_s:
            tokps.append(res.tokens_per_s)
    del engine
    return {
        "impl": impl,
        "backend": jax.default_backend(),
        "tier": tier.name,
        "model": tier.model_preset,
        "prompt_tokens": prompt_tokens,
        "p50_ttft_ms": round(statistics.median(ttfts), 2),
        "p50_decode_tok_per_s": round(statistics.median(tokps), 1)
        if tokps else None,
        "repeat": repeat,
    }


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--tier", default="nano", choices=("nano", "orin"))
    ap.add_argument("--prompt-tokens", type=int, default=512)
    ap.add_argument("--max-new", type=int, default=64)
    ap.add_argument("--repeat", type=int, default=5)
    ap.add_argument("--platform", default=None,
                    help="pin jax_platforms (e.g. cpu) — the env var alone "
                         "is snapshotted too early under this image's "
                         "sitecustomize")
    args = ap.parse_args(argv)

    if args.platform:
        import jax
        jax.config.update("jax_platforms", args.platform)

    results = {}
    prior = os.environ.get("DLLM_ATTENTION")
    try:
        for impl in ("xla", "pallas"):
            t0 = time.perf_counter()
            results[impl] = measure(impl, args.tier, args.prompt_tokens,
                                    args.max_new, args.repeat)
            results[impl]["wall_s"] = round(time.perf_counter() - t0, 1)
            print(json.dumps(results[impl]), flush=True)
    finally:
        # Don't leak the kill switch into the calling process (in-process
        # callers like the test suite share os.environ).
        if prior is None:
            os.environ.pop("DLLM_ATTENTION", None)
        else:
            os.environ["DLLM_ATTENTION"] = prior

    x, p = results["xla"], results["pallas"]
    verdict = {
        "ttft_ratio_pallas_over_xla": round(
            p["p50_ttft_ms"] / max(x["p50_ttft_ms"], 1e-9), 3),
        "decode_ratio_pallas_over_xla": round(
            (p["p50_decode_tok_per_s"] or 0)
            / max(x["p50_decode_tok_per_s"] or 1e-9, 1e-9), 3),
    }
    print(json.dumps({"verdict": verdict}))


if __name__ == "__main__":
    main()
