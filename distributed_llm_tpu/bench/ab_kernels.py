"""A/B the attention kernel implementations on the current backend.

Two modes::

    # End-to-end: full engine, TTFT + decode tok/s per DLLM_ATTENTION
    python -m distributed_llm_tpu.bench.ab_kernels [--tier nano|orin]
        [--prompt-tokens N] [--max-new N] [--repeat K]

    # Per-kernel micro A/B at serving shapes; optionally write the
    # measured dispatch table ops/attention.py consults (VERDICT r1 #3 —
    # per-shape dispatch instead of a blanket env pin)
    python -m distributed_llm_tpu.bench.ab_kernels micro
        [--tier nano|orin] [--repeat K] [--write-dispatch]

``micro`` times each kernel kind (prefill / decode / chunk / paged_decode)
directly — xla vs pallas, jitted, median of K — across the cache-length
ladder and serving batch sizes, at worst-case positions (full-length
frontier) so a pallas win is robust.  ``--write-dispatch`` publishes
``bench/ab_dispatch.json``: per kind, per length, the faster impl.

The engines are built sequentially in ONE process (the chip allows a
single claimant); DLLM_ATTENTION is read at trace time, so each engine is
constructed after the env var is set and dropped before the next.
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import time

DISPATCH_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "ab_dispatch.json")

# Every case class micro_ab can measure (--kinds validates against it) —
# derived from the serving ops' own dispatch-kind registry so the A/B
# grid and the dispatching wrappers can never cover different kernel
# sets (tests/test_kernel_dispatch.py pins the equality).
from ..ops.attention import DISPATCH_KINDS

ALL_KINDS = frozenset(DISPATCH_KINDS)


def _time_fn(fn, args, repeat: int):
    """(median wall ms, output) of a jitted call (2 warmup calls compile
    + settle).  The output feeds the numerics gate — timing alone would
    let a kernel that miscompiles on real Mosaic (interpreter-mode tests
    can't see that) win the table and serve wrong results."""
    import jax
    out = None
    for _ in range(2):
        out = fn(*args)
        jax.block_until_ready(out)
    times = []
    for _ in range(repeat):
        t0 = time.perf_counter()
        jax.block_until_ready(fn(*args))
        times.append((time.perf_counter() - t0) * 1000.0)
    return statistics.median(times), out


def micro_ab(tier_name: str = "orin", repeat: int = 20,
             write_dispatch: bool = False, fast: bool = False,
             beat=None, kinds=None) -> dict:
    """Direct kernel A/B at serving shapes; returns (and optionally
    publishes) the per-(kind, length) winner table.

    ``fast`` trims the grid to the shapes the headline bench actually
    serves (one mid-ladder length + the model max, batches 1/8) so the
    A/B fits inside the bench run itself — the driver's round-end bench
    can measure its own dispatch table on a freshly healthy chip instead
    of serving un-dispatched.  ``beat`` is called after every case
    (bench.py's wedge watchdog counts it as liveness).  ``kinds`` (an
    iterable of kind names) restricts the grid — used to isolate or
    exclude a case class after a mid-A/B chip wedge (r3: the chip
    wedged on the decode_q8@1024 case mid-grid)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..config import bench_cluster, tiny_cluster
    from ..ops import attention as A
    from ..ops import pallas_attention as PA
    from ..ops import ragged_attention as RA

    if kinds is not None:
        unknown = set(kinds) - ALL_KINDS
        if unknown:
            raise ValueError(f"unknown kinds {sorted(unknown)}; "
                             f"valid: {sorted(ALL_KINDS)}")

    cluster = (tiny_cluster() if jax.default_backend() == "cpu"
               else bench_cluster())
    cfg = getattr(cluster, tier_name).model()
    nq, nkv, d = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim
    lengths = sorted({c for c in (256, 1024) if c < cfg.max_seq_len}
                     | {cfg.max_seq_len})
    batches = (1, 4, 8)
    if fast:
        lengths = sorted({min(1024, cfg.max_seq_len), cfg.max_seq_len})
        batches = (1, 8)
    key = jax.random.PRNGKey(0)
    bf16 = jnp.bfloat16
    results: dict = {"backend": jax.default_backend(), "model": cfg.name,
                     "repeat": repeat, "cases": []}
    wins: dict = {}

    def want(kind: str) -> bool:
        return kinds is None or kind in kinds

    def record(kind, length, fn_xla, args_xla, fn_pallas, args_pallas,
               detail):
        """Time both legs; a leg that RAISES (e.g. a Mosaic compile
        failure on new hardware) loses with ms=None instead of aborting
        the whole A/B — the dispatch table must still be written."""
        import jax as _jax

        if not want(kind):
            return

        def leg(fn, args):
            try:
                ms, out = _time_fn(_jax.jit(fn), args, repeat)
                return ms, out, None
            except Exception as exc:
                return None, None, str(exc)[:160]

        ms_xla, out_x, err_x = leg(fn_xla, args_xla)
        ms_pallas, out_p, err_p = leg(fn_pallas, args_pallas)
        case = {"kind": kind, "length": length,
                "xla_ms": round(ms_xla, 3) if ms_xla is not None else None,
                "pallas_ms": (round(ms_pallas, 3)
                              if ms_pallas is not None else None), **detail}
        if err_x:
            case["xla_error"] = err_x
        if err_p:
            case["pallas_error"] = err_p
        # Numerics gate on the REAL backend: both legs ran — compare.
        # bf16 flash reorders reductions, so the bar is loose (5% of the
        # output scale); an actual Mosaic miscompile is orders beyond it.
        mismatch = False
        if out_x is not None and out_p is not None:
            ox = np.asarray(out_x, dtype=np.float32)
            op = np.asarray(out_p, dtype=np.float32)
            denom = float(np.max(np.abs(ox))) or 1.0
            rel = float(np.max(np.abs(ox - op))) / denom
            case["rel_err"] = round(rel, 5)
            if not np.isfinite(rel) or rel > 0.05:
                mismatch = True
                case["numerics_mismatch"] = True
        results["cases"].append(case)
        print(json.dumps(case), flush=True)
        if beat is not None:
            beat()
        slot = wins.setdefault(kind, {}).setdefault(str(length), [])
        # Pallas wins only if it ran, MATCHED the XLA numerics, and beat
        # a working XLA leg; a broken XLA leg with working pallas also
        # counts (something must run).
        if ms_pallas is None or mismatch:
            slot.append(False)
        elif ms_xla is None:
            slot.append(True)
        else:
            slot.append(ms_pallas <= ms_xla)

    # prefill (one sequence per call, bucket-sized).  Every block below
    # checks want() BEFORE building its inputs: excluded kinds must not
    # pay device work (the whole point of --kinds is dodging a flaky
    # case class on a wedge-prone chip).
    for s in lengths:
        if s % 128 or not want("prefill"):
            continue
        q = jax.random.normal(key, (1, s, nq, d), bf16)
        k = jax.random.normal(key, (1, s, nkv, d), bf16)
        v = jax.random.normal(key, (1, s, nkv, d), bf16)
        record("prefill", s, A.causal_attention, (q, k, v),
               PA.flash_causal_attention, (q, k, v), {})

    # decode + chunk + paged_decode across batch × cache length
    from ..ops.quant import quantize_kv_rows as _qkv
    for s in lengths:
        for b in batches:
            if not (want("decode") or want("decode_q8")):
                break
            q = jax.random.normal(key, (b, nq, d), bf16)
            kc = jax.random.normal(key, (b, s, nkv, d), bf16)
            vc = jax.random.normal(key, (b, s, nkv, d), bf16)
            pos = jnp.full((b,), s - 1, jnp.int32)     # worst-case frontier
            record("decode", s, A.decode_attention, (q, kc, vc, pos),
                   PA.flash_decode_attention, (q, kc, vc, pos),
                   {"batch": b})

            if want("decode_q8"):
                # int8 contiguous cache: XLA dequant vs in-VMEM kernel.
                kq, ksc = _qkv(kc)
                vq, vsc = _qkv(vc)
                ksc_c = ksc.astype(jnp.float32)
                vsc_c = vsc.astype(jnp.float32)
                record("decode_q8", s,
                       lambda *a: A.decode(a[0], a[1], a[2], a[5],
                                           impl="xla",
                                           k_scale=a[3], v_scale=a[4]),
                       (q, kq, vq, ksc_c, vsc_c, pos),
                       PA.flash_decode_attention_q8,
                       (q, kq, vq, ksc_c, vsc_c, pos), {"batch": b})

        if want("chunk") or want("chunk_q8"):
            # chunk prefill: one 128-token suffix against the window
            sc = min(128, s)
            q = jax.random.normal(key, (1, sc, nq, d), bf16)
            kc = jax.random.normal(key, (1, s, nkv, d), bf16)
            vc = jax.random.normal(key, (1, s, nkv, d), bf16)
            qpos = (jnp.arange(sc, dtype=jnp.int32) + (s - sc))[None]
            record("chunk", s, A.chunk_attention, (q, kc, vc, qpos),
                   PA.flash_chunk_attention, (q, kc, vc, qpos),
                   {"chunk": sc})

            if want("chunk_q8"):
                # int8-cache chunk: XLA dequant vs the in-VMEM q8 kernel.
                kq, ksc = _qkv(kc)
                vq, vsc = _qkv(vc)
                record("chunk_q8", s,
                       lambda *a: A.chunk(a[0], a[1], a[2], a[5],
                                          impl="xla",
                                          k_scale=a[3], v_scale=a[4]),
                       (q, kq, vq, ksc.astype(jnp.float32),
                        vsc.astype(jnp.float32), qpos),
                       PA.flash_chunk_attention_q8,
                       (q, kq, vq, ksc.astype(jnp.float32),
                        vsc.astype(jnp.float32), qpos), {"chunk": sc})

        # paged decode: pool sized for 8 slots of this length
        bs = 64
        for b in batches[1:]:
            if not (want("paged_decode") or want("paged_decode_q8")):
                break
            nb = b * (s // bs) + 1
            kp = jax.random.normal(key, (nkv, nb, bs, d), bf16)
            vp = jax.random.normal(key, (nkv, nb, bs, d), bf16)
            tables = jnp.asarray(
                np.arange(b * (s // bs), dtype=np.int32).reshape(b, s // bs))
            pos = jnp.full((b,), s - 1, jnp.int32)
            q = jax.random.normal(key, (b, nq, d), bf16)
            record("paged_decode", s, A.paged_decode,
                   (q, kp, vp, tables, pos),
                   PA.paged_decode_attention, (q, kp, vp, tables, pos),
                   {"batch": b})

            if want("paged_decode_q8"):
                # int8 pool variant: XLA half-byte gather+dequant vs the
                # in-VMEM dequant kernel.
                kq, ksc = _qkv(kp)
                vq, vsc = _qkv(vp)
                record("paged_decode_q8", s,
                       lambda *a: A.paged_decode(a[0], a[1], a[2], a[5],
                                                 a[6], impl="xla",
                                                 k_scale=a[3],
                                                 v_scale=a[4]),
                       (q, kq, vq, ksc, vsc, tables, pos),
                       PA.paged_decode_attention_q8,
                       (q, kq, vq, ksc, vsc, tables, pos), {"batch": b})

        # ragged paged decode: FULL tables + SKEWED per-slot lengths —
        # the mixed-length regime the ragged kernel exists for (the
        # dense paged kinds above measure at the uniform worst-case
        # frontier; measuring ragged there would hide exactly the
        # padded-window waste it removes).
        for b in batches[1:]:
            if not (want("ragged_decode") or want("ragged_decode_q8")):
                break
            nb = b * (s // bs) + 1
            kp = jax.random.normal(key, (nkv, nb, bs, d), bf16)
            vp = jax.random.normal(key, (nkv, nb, bs, d), bf16)
            tables = jnp.asarray(
                np.arange(b * (s // bs), dtype=np.int32).reshape(b, s // bs))
            # Slot i holds ~(i+1)/b of the full length: one long slot,
            # the rest progressively shorter.
            pos = jnp.asarray([max(0, s * (i + 1) // b - 1)
                               for i in range(b)], jnp.int32)
            q = jax.random.normal(key, (b, nq, d), bf16)
            if want("ragged_decode"):
                record("ragged_decode", s, A.ragged_decode,
                       (q, kp, vp, tables, pos),
                       RA.ragged_paged_decode_attention,
                       (q, kp, vp, tables, pos), {"batch": b})

            if want("ragged_decode_q8"):
                kq, ksc = _qkv(kp)
                vq, vsc = _qkv(vp)
                record("ragged_decode_q8", s,
                       lambda *a: A.ragged_decode(a[0], a[1], a[2], a[5],
                                                  a[6], impl="xla",
                                                  k_scale=a[3],
                                                  v_scale=a[4]),
                       (q, kq, vq, ksc, vsc, tables, pos),
                       RA.ragged_paged_decode_attention_q8,
                       (q, kq, vq, ksc, vsc, tables, pos), {"batch": b})

        # ragged speculative verify (ISSUE 15): the q_len=γ+1 extension
        # of the ragged decode case — same skewed per-slot lengths, a
        # γ+1 verify chunk per slot ending at the slot's frontier (the
        # chunk's own K/V already written, write-before-attend, so the
        # queries attend real content like a serving verify tick).
        for b in batches[1:]:
            if not (want("ragged_verify") or want("ragged_verify_q8")):
                break
            g = 5                                  # γ=4, the preset default
            nb = b * (s // bs) + 1
            kp = jax.random.normal(key, (nkv, nb, bs, d), bf16)
            vp = jax.random.normal(key, (nkv, nb, bs, d), bf16)
            tables = jnp.asarray(
                np.arange(b * (s // bs), dtype=np.int32).reshape(b, s // bs))
            # First-query positions: the slot's skewed frontier minus the
            # chunk (clamped non-negative) — verify masks per query row.
            pos = jnp.asarray([max(0, s * (i + 1) // b - g)
                               for i in range(b)], jnp.int32)
            q = jax.random.normal(key, (b, g, nq, d), bf16)
            if want("ragged_verify"):
                record("ragged_verify", s, A.ragged_verify,
                       (q, kp, vp, tables, pos),
                       RA.ragged_paged_verify_attention,
                       (q, kp, vp, tables, pos), {"batch": b, "g": g})

            if want("ragged_verify_q8"):
                kq, ksc = _qkv(kp)
                vq, vsc = _qkv(vp)
                record("ragged_verify_q8", s,
                       lambda *a: A.ragged_verify(a[0], a[1], a[2], a[5],
                                                  a[6], impl="xla",
                                                  k_scale=a[3],
                                                  v_scale=a[4]),
                       (q, kq, vq, ksc, vsc, tables, pos),
                       RA.ragged_paged_verify_attention_q8,
                       (q, kq, vq, ksc, vsc, tables, pos),
                       {"batch": b, "g": g})

        # paged chunk prefill (prefix-reuse admissions — engine/paged_kv.
        # chunk_prefill_paged): one 128-token suffix attending through a
        # slot's block table over a window of this length.
        if want("paged_chunk") and s >= 128 and s % bs == 0:
            sc = 128
            nb = s // bs
            kp = jax.random.normal(key, (nkv, nb + 1, bs, d), bf16)
            vp = jax.random.normal(key, (nkv, nb + 1, bs, d), bf16)
            table = jnp.arange(nb, dtype=jnp.int32)
            start = jnp.asarray([s - sc], jnp.int32)
            qpos = (jnp.arange(sc, dtype=jnp.int32) + (s - sc))[None]
            q = jax.random.normal(key, (1, sc, nq, d), bf16)
            record("paged_chunk", s,
                   lambda *a, s=s: A.paged_chunk(a[0], a[1], a[2], a[3],
                                                 a[4], a[5], s, impl="xla"),
                   (q, kp, vp, table, start, qpos),
                   lambda *a, s=s: PA.paged_chunk_attention(
                       a[0], a[1], a[2], a[3], a[4], s),
                   (q, kp, vp, table, start, qpos), {"chunk": sc})

    # Dispatch decision: pallas must win (or tie) at EVERY tested batch of
    # a (kind, length) to own it — robust beats optimal.  Each kind also
    # gets a "default" (the majority winner across its measured lengths,
    # ties to xla) so off-ladder shapes — e.g. the batched engine's
    # trimmed paged window — inherit a measured demotion instead of
    # silently staying on Pallas (ADVICE r2).
    dispatch = {}
    for kind, per in wins.items():
        owns = {length: all(v) for length, v in per.items()}
        table = {length: ("pallas" if won else "xla")
                 for length, won in owns.items()}
        table["default"] = ("pallas"
                            if sum(owns.values()) * 2 > len(owns) else "xla")
        dispatch[kind] = table
    results["dispatch"] = dispatch
    print(json.dumps({"dispatch": dispatch}), flush=True)
    if write_dispatch:
        publish_dispatch(results["backend"], results["model"], dispatch,
                         kernel_gen=PA.KERNEL_GEN)
    return results


def publish_dispatch(backend: str, model: str, dispatch: dict,
                     path: str = None, kernel_gen: int = None) -> bool:
    """Write the measured dispatch table, enforcing the artifact policy.

    A table measured on real hardware is a committed artifact; a CPU run
    must never clobber it (ops/attention.py would then ignore the file
    entirely and silently drop the TPU measurements — ADVICE r2), while
    a hardware run may always refresh, including replacing a stale cpu
    table (same policy as bench/tune.py).  A partial (--kinds / fast)
    run MERGES into a same-backend table — unmeasured kinds keep their
    prior winners — but a cross-backend refresh starts clean: mixing
    winners measured on different hardware would make the table
    meaningless.  Returns True if the table was written."""
    path = path or DISPATCH_PATH
    prior = {}
    try:
        with open(path) as f:
            prior = json.load(f)
    except (OSError, ValueError):
        pass
    prior_backend = prior.get("backend")
    if (prior_backend is not None and prior_backend != backend
            and backend == "cpu"):
        print(f"# REFUSING to overwrite {path}: it was measured on "
              f"{prior_backend!r}, this run is {backend!r} (delete the "
              "file to force)", flush=True)
        return False
    # Merge only into a same-backend, same-kernel-generation table:
    # winners measured on different hardware OR against older kernel
    # implementations must not mix with fresh ones.
    same_gen = (kernel_gen is None
                or prior.get("kernel_gen") == kernel_gen)
    merged = (dict(prior.get("dispatch") or {})
              if prior_backend == backend and same_gen else {})
    merged.update(dispatch)
    out = {"backend": backend, "model": model, "dispatch": merged}
    if kernel_gen is not None:
        out["kernel_gen"] = kernel_gen
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(f"# wrote {path} ({len(dispatch)}/{len(merged)} kinds updated)",
          flush=True)
    return True


def measure(impl: str, tier_name: str, prompt_tokens: int, max_new: int,
            repeat: int) -> dict:
    os.environ["DLLM_ATTENTION"] = impl
    import dataclasses

    import jax

    from ..config import bench_cluster, tiny_cluster
    from ..engine.inference import InferenceEngine

    cluster = (tiny_cluster() if jax.default_backend() == "cpu"
               else bench_cluster())
    # Prefix reuse OFF: this harness measures the cold prefill kernels
    # (PrefixCache.take matches even a diverging entry's shared prefix, so
    # any repeat would otherwise prefill a ~1-bucket suffix, not the
    # prompt).  Belt and braces, the prompt HEAD varies per iteration too.
    tier = dataclasses.replace(getattr(cluster, tier_name),
                               enable_prefix_cache=False)
    engine = InferenceEngine(tier, seed=0)
    engine.warmup()

    filler = "user: " + ("benchmark the attention kernels now. " * 400)
    ttfts, tokps = [], []
    for i in range(repeat):
        # Head-varied per iteration, trimmed AFTER prepending so the total
        # stays at the requested token count under the ENGINE's tokenizer
        # (subword BPE since r3) and lands in the intended prefill bucket.
        tok = engine.tokenizer
        ids = tok.encode(f"variant {i} " + filler,
                         add_bos=False)[:prompt_tokens]
        res = engine.generate(tok.decode(ids), max_new_tokens=max_new)
        ttfts.append(res.ttft_ms)
        if res.tokens_per_s:
            tokps.append(res.tokens_per_s)
    del engine
    return {
        "impl": impl,
        "backend": jax.default_backend(),
        "tier": tier.name,
        "model": tier.model_preset,
        "prompt_tokens": prompt_tokens,
        "p50_ttft_ms": round(statistics.median(ttfts), 2),
        "p50_decode_tok_per_s": round(statistics.median(tokps), 1)
        if tokps else None,
        "repeat": repeat,
    }


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("mode", nargs="?", default="engine",
                    choices=("engine", "micro"))
    ap.add_argument("--tier", default="nano", choices=("nano", "orin"))
    ap.add_argument("--prompt-tokens", type=int, default=512)
    ap.add_argument("--max-new", type=int, default=64)
    ap.add_argument("--repeat", type=int, default=5)
    ap.add_argument("--write-dispatch", action="store_true",
                    help="micro mode: publish bench/ab_dispatch.json")
    ap.add_argument("--fast", action="store_true",
                    help="micro mode: trimmed grid (headline shapes only)")
    ap.add_argument("--kinds", default=None,
                    help="micro mode: comma-separated kind subset to run "
                         "(isolate/exclude a case after a chip wedge)")
    ap.add_argument("--platform", default=None,
                    help="pin jax_platforms (e.g. cpu) — the env var alone "
                         "is snapshotted too early under this image's "
                         "sitecustomize")
    args = ap.parse_args(argv)

    from ..utils.compile_cache import enable_persistent_compile_cache
    enable_persistent_compile_cache()
    if args.platform:
        import jax
        jax.config.update("jax_platforms", args.platform)

    if args.mode == "micro":
        micro_ab(args.tier, repeat=max(args.repeat, 10),
                 write_dispatch=args.write_dispatch, fast=args.fast,
                 kinds=(set(args.kinds.split(",")) if args.kinds else None))
        return

    results = {}
    prior = os.environ.get("DLLM_ATTENTION")
    try:
        for impl in ("xla", "pallas"):
            t0 = time.perf_counter()
            results[impl] = measure(impl, args.tier, args.prompt_tokens,
                                    args.max_new, args.repeat)
            results[impl]["wall_s"] = round(time.perf_counter() - t0, 1)
            print(json.dumps(results[impl]), flush=True)
    finally:
        # Don't leak the kill switch into the calling process (in-process
        # callers like the test suite share os.environ).
        if prior is None:
            os.environ.pop("DLLM_ATTENTION", None)
        else:
            os.environ["DLLM_ATTENTION"] = prior

    x, p = results["xla"], results["pallas"]
    verdict = {
        "ttft_ratio_pallas_over_xla": round(
            p["p50_ttft_ms"] / max(x["p50_ttft_ms"], 1e-9), 3),
        "decode_ratio_pallas_over_xla": round(
            (p["p50_decode_tok_per_s"] or 0)
            / max(x["p50_decode_tok_per_s"] or 1e-9, 1e-9), 3),
    }
    print(json.dumps({"verdict": verdict}))


if __name__ == "__main__":
    main()
