"""Legacy threshold-sweep tester (v1 harness parity).

Reference parity: src/tests/chatbot_tester.py — the earlier single-strategy
harness that sweeps the context threshold over a Chatbot and writes the
``final_results.csv`` schema consumed by results_analysis.ipynb ("Query Set",
"Context Threshold", then per-device Latency / Energy / Avg Power / Tokens
Generated).  Kept because the stored baseline numbers (BASELINE.md) are in
this schema.

Documented fix vs the reference (SURVEY.md §7 quirks): v1 summed raw 1 Hz
power samples as "energy" (chatbot_tester.py:225); we integrate the sampled
telemetry properly over each query window (the v2 semantics), using the
HBM-occupancy proxy since TPUs expose no per-query power (utils/telemetry).
"""

from __future__ import annotations

import argparse
import csv
import os
from datetime import datetime
from typing import Dict, List, Optional

from ..serving.cli import Chatbot
from .query_sets import query_sets
from .tester import normalize_query_set

HEADERS = [
    "Query Set", "Context Threshold",
    "Nano Latency (ms)", "Nano Energy (mJ)", "Nano Avg Power (W)",
    "Nano Tokens Generated",
    "Orin Latency (ms)", "Orin Energy (mJ)", "Orin Avg Power (W)",
    "Orin Tokens Generated",
]


class ChatbotTester:
    def __init__(self, test_queries, context_thresholds,
                 strategy: str = "perf"):
        self.test_queries = normalize_query_set(test_queries)
        self.context_thresholds = list(context_thresholds)
        self.strategy = strategy
        from .tester import _build_telemetry
        self.telemetry = _build_telemetry()

    def run(self, query_set_name: str,
            output_file: str = "final_results.csv") -> Dict[int, Dict]:
        self.telemetry.start()
        query_log = []   # (threshold, device, start, end, tokens)
        try:
            for threshold in self.context_thresholds:
                chatbot = Chatbot(strategy=self.strategy, config={
                    "cache_enabled": False,
                    "enable_response_cache": False,
                    "enable_failover": True,
                    "token_threshold": threshold,
                })
                chatbot.router.set_threshold(threshold)
                for qi in self.test_queries:
                    start = datetime.now()
                    chatbot.add_message("user", qi.text)
                    response, tokens, device = chatbot.router.route_query(
                        chatbot.history)
                    reply = (response.get("response", "")
                             if isinstance(response, dict) else str(response))
                    chatbot.add_message("assistant", reply)
                    query_log.append((threshold, device, start,
                                      datetime.now(), int(tokens or 0)))
                chatbot.shutdown()
        finally:
            self.telemetry.stop()

        results = self.calculate_energy(query_log)
        self.save_results(results, query_set_name, output_file)
        return results

    def calculate_energy(self, query_log) -> Dict[int, Dict]:
        results: Dict[int, Dict[str, List[float]]] = {}
        for threshold, device, start, end, tokens in query_log:
            if device not in ("nano", "orin"):
                continue
            per = results.setdefault(
                threshold, {"nano": [0, 0.0, 0.0, 0], "orin": [0, 0.0, 0.0, 0]})
            latency = round((end - start).total_seconds() * 1000)
            energy = self.telemetry.energy_for_window(device, start, end)
            per[device][0] += latency
            per[device][1] += energy
            per[device][3] += tokens
        for per in results.values():
            for device in ("nano", "orin"):
                lat, energy = per[device][0], per[device][1]
                per[device][2] = round(energy / lat, 3) if lat > 0 else 0.0
        return results

    def save_results(self, results, query_set_name: str,
                     output_file: str) -> None:
        file_exists = os.path.exists(output_file)
        with open(output_file, "a", newline="") as f:
            writer = csv.writer(f)
            if not file_exists:
                writer.writerow(HEADERS)
            for threshold, per in results.items():
                writer.writerow([
                    query_set_name, threshold,
                    per["nano"][0], round(per["nano"][1], 3),
                    per["nano"][2], per["nano"][3],
                    per["orin"][0], round(per["orin"][1], 3),
                    per["orin"][2], per["orin"][3],
                ])


def main(argv: Optional[List[str]] = None) -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--query-set", default="personal_health")
    p.add_argument("--thresholds", nargs="+", type=int,
                   default=[100, 500, 1000, 2000, 4000])
    p.add_argument("--strategy", default="perf")
    p.add_argument("--output-csv", default="final_results.csv")
    p.add_argument("--platform", default=None,
                   help="pin jax_platforms (e.g. cpu); see bench/tester.py")
    args = p.parse_args(argv)
    if args.platform:
        import jax
        jax.config.update("jax_platforms", args.platform)
    tester = ChatbotTester(query_sets[args.query_set], args.thresholds,
                           strategy=args.strategy)
    tester.run(args.query_set, args.output_csv)


if __name__ == "__main__":
    main()
