"""Labeled benchmark query sets.

Same shape as the reference's evaluation data (src/tests/query_sets.py:1-51):
three named sets, each a list of ``{"query", "expected_device"}`` records,
multi-turn by design (later queries lean on earlier context, which exercises
the context-size routing signals and the ctx-hash cache keying).  The texts
here are our own; the *distribution* mirrors the reference — simple factual
one-liners labeled nano, long/compositional/code-heavy prompts labeled orin,
and technical_coding all-orin.
"""

query_sets = {
    "general_knowledge": [
        {"query": "What is the capital of Japan?", "expected_device": "nano"},
        {"query": "How many continents are there?", "expected_device": "nano"},
        {"query": "Name the largest ocean on Earth.", "expected_device": "nano"},
        {"query": "And the deepest point in it?", "expected_device": "nano"},
        {"query": "What year did the first person walk on the moon?",
         "expected_device": "nano"},
        {"query": "Who was the mission commander?", "expected_device": "nano"},
        {"query": "Explain in detail how plate tectonics drives earthquakes, "
                  "volcanic arcs, and mountain building, and compare the "
                  "mechanisms at divergent, convergent, and transform "
                  "boundaries with concrete examples of each.",
         "expected_device": "orin"},
        {"query": "Write a thorough comparison of the Roman Republic and the "
                  "Roman Empire: institutions, military organization, causes "
                  "of the transition, and the long-term consequences for "
                  "European law and governance.",
         "expected_device": "orin"},
        {"query": "What is photosynthesis?", "expected_device": "nano"},
        {"query": "Given everything we've discussed so far, synthesize a "
                  "short essay connecting lunar exploration, geology, and "
                  "the history of science, citing the earlier answers.",
         "expected_device": "orin"},
        {"query": "Define the word 'ephemeral'.", "expected_device": "nano"},
        {"query": "Why is the sky blue? Why are sunsets red? Why do clouds "
                  "look white? Walk through the scattering physics for each.",
         "expected_device": "orin"},
    ],
    "technical_coding": [
        {"query": "Write a Python function that parses an ISO-8601 timestamp "
                  "without using external libraries and handles timezone "
                  "offsets correctly.", "expected_device": "orin"},
        {"query": "Debug this: my binary search returns the wrong index when "
                  "the target equals the first element. Show the corrected "
                  "loop invariant and explain the off-by-one.",
         "expected_device": "orin"},
        {"query": "Implement an LRU cache with O(1) get and put in C++ using "
                  "a doubly linked list and a hash map; include the class "
                  "definition and eviction logic.", "expected_device": "orin"},
        {"query": "Prove that comparison-based sorting requires Omega(n log n) "
                  "comparisons in the worst case.", "expected_device": "orin"},
        {"query": "Refactor the previous C++ cache to be thread-safe; discuss "
                  "lock granularity and the trade-offs of a sharded design.",
         "expected_device": "orin"},
        {"query": "Write a SQL query that finds the top 3 customers by "
                  "rolling 90-day revenue per region, using window functions.",
         "expected_device": "orin"},
        {"query": "Explain how a B-tree differs from an LSM tree for write-"
                  "heavy workloads and when each wins; include complexity "
                  "analysis and real database examples.",
         "expected_device": "orin"},
        {"query": "Design a rate limiter for a distributed API gateway: token "
                  "bucket vs sliding window, clock skew, and hot-key "
                  "mitigation. Provide pseudocode.", "expected_device": "orin"},
        {"query": "Given a stream of integers, maintain the running median "
                  "with two heaps. Implement it and analyze the complexity.",
         "expected_device": "orin"},
        {"query": "Build a regex that validates RFC-like email addresses and "
                  "explain each component of the pattern.",
         "expected_device": "orin"},
    ],
    "personal_health": [
        {"query": "How much water should I drink per day?",
         "expected_device": "nano"},
        {"query": "Give me one tip to sleep better.", "expected_device": "nano"},
        {"query": "What is a normal resting heart rate?",
         "expected_device": "nano"},
        {"query": "Is mine of 58 bpm okay for an adult who runs regularly?",
         "expected_device": "nano"},
        {"query": "Design a complete 12-week half-marathon training plan for "
                  "a beginner: weekly mileage progression, interval sessions, "
                  "strength work, nutrition guidance, and taper strategy, "
                  "with rationale for each phase.", "expected_device": "orin"},
        {"query": "What does BMI stand for?", "expected_device": "nano"},
        {"query": "Explain in depth how chronic stress affects the immune, "
                  "cardiovascular, and digestive systems, and evaluate the "
                  "evidence behind common interventions like meditation, "
                  "exercise, and therapy.", "expected_device": "orin"},
        {"query": "Suggest a quick healthy snack.", "expected_device": "nano"},
        {"query": "Considering the training plan you outlined earlier, how "
                  "should I adjust the remaining weeks if I miss ten days "
                  "with a cold? Rebuild the schedule and explain the "
                  "physiological reasoning.", "expected_device": "orin"},
        {"query": "What vitamin does sunlight help produce?",
         "expected_device": "nano"},
    ],
}


def _report(title: str, sections: int, opener: str) -> str:
    """Deterministic multi-section pseudo-report used by the long_context
    set.  Sentence material cycles with section-dependent figures so the
    text never literally repeats; size is controlled by ``sections``
    (each ≈ 55 words ≈ 75 BPE tokens under the serving tokenizer)."""
    bodies = [
        ("Throughput reached {n} requests per second during the {i} "
         "window, while the on-call rotation logged {m} pages and the "
         "error budget burned {p} percent."),
        ("The migration moved {n} tables across {m} shards in week {i}; "
         "replication lag peaked at {p} seconds before the backfill "
         "workers caught up."),
        ("Customer interviews in cohort {i} surfaced {n} recurring "
         "complaints, of which {m} trace back to the onboarding flow and "
         "{p} to billing edge cases."),
        ("Cache hit rate settled at {p} percent after the {i} rollout, "
         "cutting origin traffic by {n} gigabytes per day across {m} "
         "regions."),
        ("The audit flagged {n} dependencies with known advisories; {m} "
         "were patched in sprint {i} and the remaining {p} are gated "
         "behind a feature flag."),
        ("Latency at the ninety-ninth percentile improved from {n} to {m} "
         "milliseconds once batch {i} enabled connection pooling, a {p} "
         "percent reduction."),
    ]
    parts = [opener, f"DOCUMENT: {title}."]
    for s in range(sections):
        b = bodies[s % len(bodies)]
        parts.append(
            f"Section {s + 1}. "
            + b.format(n=137 + 7 * s, m=12 + 3 * s, p=5 + (s * 11) % 67,
                       i=f"Q{1 + s % 4}")
            + f" Follow-up item {s + 1} remains owned by team "
            f"{'ABCDEFGH'[s % 8]} pending review.")
    return " ".join(parts)


# The long-context set (round 5): document sizes are chosen so the
# query+context token counts genuinely straddle the reference's
# 100→4000 threshold sweep (src/tests/routing_chatbot_tester.py:352-367
# sweeps token_threshold and BASELINE.md shows load shifting
# continuously across it).  The r4 sweep was degenerate above 500
# because every query was tiny (VERDICT r4 weak #5); these pasted
# documents put successive queries at roughly 0.3k/0.7k/1.2k/2k/3k
# tokens (serving BPE), with short follow-ups riding the accumulated
# context in between.  Serving tiers tail-truncate long prompts to
# max_seq_len exactly like the reference's Ollama window (SURVEY §5.7);
# the ROUTING layer always sees the full text, which is what the sweep
# measures.
query_sets["long_context"] = [
    {"query": "I'm going to paste several status reports; help me work "
              "through them one by one.", "expected_device": "nano"},
    {"query": _report("Edge gateway quarterly review", 4,
                      "Summarize the key risks in this report in three "
                      "bullet points."), "expected_device": "orin"},
    {"query": "Thanks. Which team owns the first follow-up item?",
     "expected_device": "nano"},
    {"query": _report("Payments platform migration postmortem", 9,
                      "Identify the root causes described below and rank "
                      "them by blast radius."), "expected_device": "orin"},
    {"query": "Give me a one-line TL;DR of that last document.",
     "expected_device": "nano"},
    {"query": _report("Search relevance annual audit", 16,
                      "Contrast this audit's findings with the previous "
                      "two documents and flag contradictions."),
     "expected_device": "orin"},
    {"query": "Was replication lag mentioned anywhere? Just yes or no.",
     "expected_device": "nano"},
    {"query": _report("Data warehouse cost retrospective", 27,
                      "Write an executive brief reconciling the spend "
                      "figures below with the earlier reports."),
     "expected_device": "orin"},
    {"query": "Which quarter shows up most often across the documents?",
     "expected_device": "nano"},
    {"query": _report("Mobile release train health check", 40,
                      "Produce a consolidated remediation plan covering "
                      "every document so far, sequenced by dependency."),
     "expected_device": "orin"},
    {"query": "How many documents have I shared with you in total?",
     "expected_device": "nano"},
    {"query": "Now synthesize everything above into a single year-end "
              "narrative for leadership: themes, metrics trajectory, open "
              "risks, and a first-quarter plan, citing specific sections.",
     "expected_device": "orin"},
]
