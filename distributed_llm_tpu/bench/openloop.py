"""Open-loop traffic harness: Poisson arrivals against the real HTTP
edge, sweeping arrival rate to the knee of the latency-throughput curve.

The closed-loop N-client harness (bench.py headline) cannot see queueing
collapse: a closed-loop client submits its next request only after the
previous answer lands, so offered load self-throttles to whatever the
system serves and the queue never grows.  Production traffic does not
wait its turn — arrivals are ASYNCHRONOUS, and the number that matters
is GOODPUT UNDER SLO: the rate of requests that completed ok with TTFT
and token cadence inside target (APEX frames online serving exactly this
way; PAPERS.md).  This module:

- generates Poisson arrivals (``random.expovariate``) at a configured
  rate, each arrival an independent thread POSTing ``/chat`` through the
  in-process HTTP edge (serving/app.py via ``test_client`` — the same
  dispatch path a deployed server runs, minus the socket), with a
  multi-turn session mix drawn from the ``general_knowledge`` set;
- sweeps the arrival rate over multiples of a calibrated base service
  rate and reads goodput from the router's own SLO monitor (obs/slo.py
  — the measurement instrument IS the production instrument);
- reports the KNEE: the highest swept rate whose SLO attainment is
  still ≥ ``KNEE_ATTAINMENT`` (0.9), with ``goodput_at_knee`` as the
  headline — past the knee goodput plateaus while latency grows without
  bound, which is precisely the regime the closed-loop harness cannot
  produce;
- runs an OVERLOAD epilogue at ≥2× the knee and verifies graceful
  degradation: every arrival gets an answer (availability 1.0, no hung
  clients — admission shedding and failover doing their job) and the
  collapse shows up as flight-recorded overload incidents carrying a
  system-state timeline slice (obs/sampler.py), not as silence.

Pinned tiny-batched config like the trend/chaos/pressure legs: the leg
measures the serving machinery under load it did not choose, not model
speed.  Budget-aware via the ``budget_s`` parameter (bench.py passes its
remaining DLLM_BENCH_BUDGET_S share): rate points are dropped from the
top of the sweep, never measured shorter than ``MIN_POINT_S``.
"""

from __future__ import annotations

import random
import threading
import time
import zlib
from typing import Any, Dict, List, Optional

from ..obs.metrics import nearest_rank

# Adaptive rate sweep: start below the calibrated sequential base rate
# and DOUBLE until SLO attainment collapses below KNEE_ATTAINMENT (the
# point past the knee) or a cap is hit.  A fixed multiplier ladder
# cannot work here: the sequential base rate understates the batched
# tiers' capacity by an order of magnitude (closed-loop calibration is
# exactly the blindness this harness exists to fix), so the sweep must
# chase the knee instead of assuming where it is.
SWEEP_START_MULTIPLIER = 0.75   # first point, × the sequential base rate
MAX_SWEEP_POINTS = 9            # ≤ base × 0.75 × 2^8 before giving up
MAX_RATE_REQ_PER_S = 800.0      # past this the spawn loop itself lies
MAX_ARRIVALS_PER_POINT = 600    # bounds threads/memory at high rates
# A point "holds" its offered load when this fraction of completions met
# the SLO; the knee is the highest such point (BENCHMARKS.md r11).
KNEE_ATTAINMENT = 0.9
OVERLOAD_FACTOR = 2.5           # epilogue rate = knee × this (≥2× pinned)
MIN_POINT_S = 1.0               # never measure a rate point shorter
MAX_POINT_S = 4.0
SESSION_POOL = 8                # concurrent multi-turn sessions in the mix
JOIN_GRACE_S = 90.0             # drain window before a client counts hung


def _pct(values: List[float], q: float) -> Optional[float]:
    v = nearest_rank(values, q)
    return None if v is None else round(v, 2)


def _run_rate_point(client, router, queries, strategy: str,
                    rate_req_per_s: float, duration_s: float,
                    label: str, beat=lambda: None,
                    deadline: Optional[float] = None,
                    carry: Optional[List[threading.Thread]] = None
                    ) -> Dict[str, Any]:
    """One open-loop measurement window: Poisson arrivals at
    ``rate_req_per_s`` for ``duration_s``, goodput read from the
    router's SLO monitor deltas.  The master loop sleeps out each
    exponential gap and fires an independent daemon thread per arrival —
    an arrival NEVER waits for an earlier request (that would re-create
    the closed loop this harness exists to replace).

    ``deadline`` (``time.monotonic()``) clamps the straggler join grace
    so a wedged point cannot overrun the leg's budget share by the full
    JOIN_GRACE_S — bench.py reserves only ~30 s after this leg.
    ``carry`` threads are stragglers a PREVIOUS point left running:
    they are absorbed (briefly joined) before the SLO baseline snapshot,
    because a stale completion landing mid-window would bleed into this
    point's good/observed deltas and skew its attainment; any that
    remain alive are counted in ``prior_stragglers`` so a contaminated
    point is marked, not silently trusted.  Still-alive threads are
    pushed back onto ``carry`` for the next point."""
    # Stable seed: str hash() is PYTHONHASHSEED-randomized per process,
    # which would draw a fresh arrival schedule every run and add
    # schedule-level variance to a leg pinned for cross-round comparison.
    rng = random.Random(zlib.crc32(label.encode())
                        ^ int(rate_req_per_s * 1000))
    lock = threading.Lock()
    latencies: List[float] = []
    completed = [0]
    http_errors = [0]

    def fire(i: int) -> None:
        t0 = time.perf_counter()
        try:
            resp = client.post("/chat", json={
                "message": queries[i % len(queries)]["query"],
                "strategy": strategy,
                "session_id": f"ol-{label}-{i % SESSION_POOL}",
            })
            status = resp.status_code
        except Exception:
            status = None
        dt = (time.perf_counter() - t0) * 1000.0
        with lock:
            if status is not None:
                completed[0] += 1
                latencies.append(dt)
                if status != 200:
                    http_errors[0] += 1

    # Bound the thread/memory cost of a very fast point: shrink the
    # window rather than the rate (the offered rate IS the experiment).
    duration_s = max(0.5, min(duration_s,
                              MAX_ARRIVALS_PER_POINT / rate_req_per_s))
    prior_stragglers = 0
    if carry:
        absorb_by = time.monotonic() + 5.0
        if deadline is not None:
            absorb_by = min(absorb_by, deadline)
        for t in carry:
            t.join(timeout=max(0.0, absorb_by - time.monotonic()))
            beat()
        prior_stragglers = sum(1 for t in carry if t.is_alive())
        carry[:] = [t for t in carry if t.is_alive()]
    slo = router.slo
    g0, o0 = slo.good_total, slo.observed_total
    threads: List[threading.Thread] = []
    t_start = time.perf_counter()
    deadline = t_start + duration_s
    # ABSOLUTE arrival schedule: each exponential gap advances a target
    # timestamp and the loop sleeps only the remaining distance to it —
    # per-iteration sleep/spawn overhead turns into a brief catch-up
    # burst (arrivals that "fell behind" fire back-to-back) instead of
    # silently deflating the offered rate at high λ, which would report
    # a spawn-loop ceiling as the system's knee.
    t_next = t_start
    i = 0
    while True:
        t_next += rng.expovariate(rate_req_per_s)
        if t_next >= deadline:
            break
        lag = t_next - time.perf_counter()
        if lag > 0:
            time.sleep(lag)
        t = threading.Thread(target=fire, args=(i,), daemon=True,
                             name=f"openloop-{label}-{i}")
        threads.append(t)
        t.start()
        i += 1
        beat()
    arrivals = len(threads)
    # Clamp the drain grace by the leg's budget deadline (floor 5 s so
    # hung-client detection still gets a real chance): without the
    # clamp, one wedged point spends up to JOIN_GRACE_S past its budget
    # share and eats the reserve bench.py keeps for the phases after.
    grace = JOIN_GRACE_S
    if deadline is not None:
        grace = max(5.0, min(grace, deadline - time.monotonic()))
    join_deadline = time.monotonic() + grace
    for t in threads:
        t.join(timeout=max(0.0, join_deadline - time.monotonic()))
        beat()
    alive = [t for t in threads if t.is_alive()]
    hung = len(alive)
    if carry is not None:
        carry.extend(alive)
    wall_s = time.perf_counter() - t_start
    good = slo.good_total - g0
    observed = slo.observed_total - o0
    out: Dict[str, Any] = {}
    if prior_stragglers:
        # Stragglers from the previous point may have completed inside
        # this window and fed the SLO deltas — the attainment below is
        # contaminated and a knee read from it must be interpretable.
        out["prior_stragglers"] = prior_stragglers
    return {
        **out,
        "offered_req_per_s": round(arrivals / max(duration_s, 1e-9), 3),
        "arrivals": arrivals,
        "completed": completed[0],
        "http_errors": http_errors[0],
        "hung_clients": hung,
        "availability": (round(completed[0] / arrivals, 4)
                         if arrivals else None),
        "goodput_req_per_s": round(good / max(wall_s, 1e-9), 3),
        "slo_attainment": (round(good / observed, 4) if observed
                           else None),
        "p50_ms": _pct(latencies, 0.50),
        "p95_ms": _pct(latencies, 0.95),
        "wall_s": round(wall_s, 2),
    }


def _find_knee(sweep: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Knee = the highest swept offered rate whose SLO attainment is
    still ≥ KNEE_ATTAINMENT; ``goodput_at_knee`` is the goodput measured
    THERE.  When no point attains (the system is past its knee even at
    the lowest rate — or the SLO is simply too tight for the hardware),
    the max-goodput point is reported with a flag instead of silence."""
    holding = [p for p in sweep
               if (p.get("slo_attainment") or 0.0) >= KNEE_ATTAINMENT]
    if holding:
        knee = max(holding, key=lambda p: p["offered_req_per_s"])
        below = False
    elif sweep:
        knee = max(sweep, key=lambda p: p.get("goodput_req_per_s") or 0.0)
        below = True
    else:
        return {"knee_req_per_s": None, "goodput_at_knee": None,
                "slo_attainment_at_knee": None}
    out = {
        "knee_req_per_s": knee["offered_req_per_s"],
        "goodput_at_knee": knee["goodput_req_per_s"],
        "slo_attainment_at_knee": knee["slo_attainment"],
    }
    if below:
        out["slo_attainment_below_target_at_all_rates"] = True
    return out


def openloop_phase(strategies=("heuristic", "perf"),
                   budget_s: Optional[float] = None,
                   point_s: Optional[float] = None,
                   beat=lambda: None) -> Dict[str, Any]:
    """The bench leg (bench.py wires it after the skew leg): per-strategy
    open-loop rate sweep → knee + goodput-at-knee, then the overload
    epilogue on the first strategy.  Returns the artifact dict under the
    bench's ``openloop`` key; ``knee_req_per_s`` / ``goodput_at_knee`` /
    per-strategy ``slo_attainment`` are the acceptance columns."""
    import sys

    from ..config import tiny_batched_cluster
    from ..obs import Observability
    from ..serving.app import create_app
    from ..serving.router import Router
    from .query_sets import query_sets

    print("[bench] open-loop SLO goodput leg", file=sys.stderr, flush=True)
    queries = query_sets["general_knowledge"]
    obs = Observability(slow_ms=None)
    router = Router(strategy=strategies[0], benchmark_mode=True,
                    cluster=tiny_batched_cluster(), observability=obs)
    app = create_app(router=router)
    client = app.test_client()
    targets = router.slo.targets
    out: Dict[str, Any] = {
        "config": "tiny_batched(nano=4,orin=2) random-init, open-loop "
                  "Poisson via the in-process HTTP edge",
        "slo": {t: {"ttft_ms": tt, "tbt_ms": tb}
                for t, (tt, tb) in sorted(targets.items())},
        "session_pool": SESSION_POOL,
        "knee_rule": f"highest rate with attainment >= {KNEE_ATTAINMENT}",
    }
    deadline = (time.monotonic() + budget_s) if budget_s else None
    try:
        for tier in router.tiers.values():
            tier.server_manager.start_server(beat=beat)
            beat()
        # Calibrate the base service rate on warm engines: 3 sequential
        # edge round trips (the first also pays any remaining prefill
        # compile, so warm one untimed first).
        client.post("/chat", json={"message": queries[0]["query"],
                                   "strategy": strategies[0],
                                   "session_id": "ol-warm"})
        beat()
        t0 = time.perf_counter()
        n_cal = 3
        for i in range(n_cal):
            client.post("/chat", json={"message": queries[i]["query"],
                                       "strategy": strategies[0],
                                       "session_id": "ol-warm"})
            beat()
        per_req_s = max((time.perf_counter() - t0) / n_cal, 1e-3)
        base_rate = 1.0 / per_req_s
        out["base_seq_req_per_s"] = round(base_rate, 3)

        # Point duration: fit strategies × (sweep + epilogue) into the
        # budget share, clamped to [MIN_POINT_S, MAX_POINT_S].  The
        # adaptive sweep usually stops well short of MAX_SWEEP_POINTS.
        n_points = len(strategies) * MAX_SWEEP_POINTS + 1
        if point_s is None:
            share = (budget_s if budget_s else 60.0)
            point_s = max(MIN_POINT_S,
                          min(MAX_POINT_S, 0.6 * share / n_points))
        out["point_s"] = round(point_s, 2)

        per_strategy: Dict[str, Any] = {}
        attainment: Dict[str, Any] = {}
        # One straggler carry for the WHOLE phase: threads a point left
        # running are absorbed before the next point's SLO baseline —
        # across strategies and into the epilogue too.
        carry: List[threading.Thread] = []
        for strategy in strategies:
            sweep: List[Dict[str, Any]] = []
            rate = max(0.2, base_rate * SWEEP_START_MULTIPLIER)
            crossed = False
            for _n in range(MAX_SWEEP_POINTS):
                if deadline is not None and (time.monotonic() + point_s
                                             > deadline):
                    sweep.append({"skipped": "budget exhausted before "
                                             f"the {rate:.0f}/s point"})
                    break
                point = _run_rate_point(
                    client, router, queries, strategy, rate, point_s,
                    label=f"{strategy}-{_n}", beat=beat,
                    deadline=deadline, carry=carry)
                sweep.append(point)
                beat()
                att = point.get("slo_attainment")
                if att is not None and att < KNEE_ATTAINMENT:
                    crossed = True       # past the knee — sweep done
                    break
                if rate >= MAX_RATE_REQ_PER_S:
                    break
                rate = min(MAX_RATE_REQ_PER_S, rate * 2.0)
            measured = [p for p in sweep if "offered_req_per_s" in p]
            knee = _find_knee(measured)
            if not crossed and measured:
                # Every swept rate held its SLO: the reported knee is a
                # LOWER BOUND on the real one, and the artifact must say
                # so rather than let a cross-round comparison read a
                # spawn-loop ceiling as a regression.
                knee["knee_is_lower_bound"] = True
            per_strategy[strategy] = {"sweep": sweep, **knee}
            attainment[strategy] = knee.get("slo_attainment_at_knee")
        out["per_strategy"] = per_strategy
        out["slo_attainment"] = attainment
        first = per_strategy.get(strategies[0], {})
        out["knee_req_per_s"] = first.get("knee_req_per_s")
        out["goodput_at_knee"] = first.get("goodput_at_knee")

        # -- overload epilogue: ≥2× the knee, graceful degradation -------
        knee_rate = out["knee_req_per_s"]
        if knee_rate and (deadline is None
                          or time.monotonic() + point_s <= deadline):
            incidents_before = router.slo.incidents_total
            point = _run_rate_point(
                client, router, queries, strategies[0],
                knee_rate * OVERLOAD_FACTOR, point_s,
                label="overload", beat=beat,
                deadline=deadline, carry=carry)
            incidents = router.slo.incidents_total - incidents_before
            recorded = [e for e in obs.recorder.snapshot()
                        if e.get("reason") == "overload"]
            with_timeline = sum(
                1 for e in recorded
                if (e.get("incident") or {}).get("timeline"))
            out["overload"] = {
                "offered_over_knee": OVERLOAD_FACTOR,
                **point,
                "incidents": incidents,
                "incidents_recorded": len(recorded),
                "incidents_with_timeline": with_timeline,
            }
        elif knee_rate:
            out["overload"] = {"skipped": "budget exhausted"}
    finally:
        try:
            router.drain(timeout_s=10.0)
        except Exception:
            for tier in router.tiers.values():
                tier.server_manager.stop_server()
    return out
