"""Scenario traffic suite — shaped, deterministic open-loop schedules.

``openloop.py`` sweeps FLAT Poisson rates to find the knee; production
traffic is not flat.  Capacity economics — the goodput-per-replica-
second question the elastic leg (bench.py ``elastic_phase``) asks —
only shows up under traffic with SHAPE: diurnal ramps where demand
doubles and halves over a "day", flash crowds that spike an order of
magnitude for seconds, session-heavy stretches where multi-turn
affinity dominates vs one-shot sprays where it is worthless, and
long-context waves interleaved with chat.  This module generates those
shapes as piecewise-constant rate profiles (``Segment``), expands them
into ONE absolute seeded arrival schedule (``schedule``), and replays
them against a fire callback (``run_schedule``).

Two properties are inherited from the openloop harness on purpose:

- **Determinism**: the schedule is drawn from
  ``random.Random(zlib.crc32(label) ^ seed)`` — str ``hash()`` is
  PYTHONHASHSEED-randomized per process, which would add
  schedule-level variance to legs pinned for cross-round comparison.
  Same (segments, label, seed) → byte-identical arrival times, kinds,
  and session ids, across processes.
- **Absolute-schedule catch-up**: every arrival has an absolute target
  timestamp computed at generation time; the replay loop sleeps only
  the remaining distance to it, so per-iteration spawn overhead turns
  into a brief catch-up burst (arrivals that "fell behind" fire
  back-to-back) instead of silently deflating the offered rate — a
  spawn-loop ceiling must never masquerade as the system's knee.
"""

from __future__ import annotations

import random
import threading
import time
import zlib
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

SESSION_POOL = 8          # bounded multi-turn session pool (openloop's)
JOIN_GRACE_S = 90.0       # drain window before a client counts hung
MAX_ARRIVALS = 2000       # bounds threads/memory for a whole scenario

# Workload kinds a Segment's mix can draw: the bench leg maps them to
# prompt classes (chat = short multi-turn, oneshot = fresh session per
# request, long = long-context prompt).  The generator itself is
# agnostic — kinds are labels the fire callback interprets.
KIND_CHAT = "chat"
KIND_ONESHOT = "oneshot"
KIND_LONG = "long"


@dataclass(frozen=True)
class Segment:
    """One piecewise-constant traffic segment: ``duration_s`` of
    Poisson arrivals at ``rate_req_per_s``, each arrival's kind drawn
    from ``mix`` (kind → weight).  ``one_shot_fraction`` of arrivals
    mint a UNIQUE session id (no affinity to exploit); the rest draw
    from the bounded pool (multi-turn — prefix affinity and KV reuse
    exist)."""

    duration_s: float
    rate_req_per_s: float
    mix: Tuple[Tuple[str, float], ...] = ((KIND_CHAT, 1.0),)
    one_shot_fraction: float = 0.0


@dataclass(frozen=True)
class Arrival:
    """One scheduled request: absolute offset from scenario start,
    workload kind, session identity, and its global index."""

    t_s: float
    kind: str
    session: str
    index: int


def total_duration_s(segments: Sequence[Segment]) -> float:
    return sum(s.duration_s for s in segments)


def peak_rate(segments: Sequence[Segment]) -> float:
    return max((s.rate_req_per_s for s in segments), default=0.0)


# -- shape generators ---------------------------------------------------------

def diurnal_ramp(base_rate: float, peak_rate: float, period_s: float,
                 steps: int = 8,
                 mix: Tuple[Tuple[str, float], ...] = ((KIND_CHAT, 1.0),)
                 ) -> List[Segment]:
    """One traffic "day" compressed into ``period_s``: piecewise-linear
    ramp base → peak → base over ``steps`` equal segments (triangular
    profile — monotone rise to the midpoint, monotone fall after).
    The elastic leg's canonical shape: the rise forces scale-up, the
    fall forces idle scale-down, and the symmetry makes
    replica-seconds comparable across policies."""
    steps = max(2, int(steps))
    seg_s = float(period_s) / steps
    # 0 → 1 → 0 triangle over the step index; normalized so the PEAK
    # rate is actually reached (an even step count never samples the
    # apex — its two middle segments both sit at peak instead).
    fracs = [1.0 - abs(2.0 * (i / (steps - 1)) - 1.0)
             for i in range(steps)]
    top = max(fracs)
    return [Segment(seg_s, base_rate + (peak_rate - base_rate) * f / top,
                    mix=mix)
            for f in fracs]


def flash_crowd(base_rate: float, spike_rate: float, total_s: float,
                spike_start_s: float, spike_s: float,
                mix: Tuple[Tuple[str, float], ...] = ((KIND_CHAT, 1.0),)
                ) -> List[Segment]:
    """Steady base load with one hard step to ``spike_rate`` — the
    thundering-herd shape (a link goes viral): no ramp warning, the
    spike IS the first sample.  Tests the breach-window/cooldown
    tradeoff: react inside the spike, don't flap after it."""
    spike_start_s = max(0.0, min(spike_start_s, total_s))
    spike_s = max(0.0, min(spike_s, total_s - spike_start_s))
    out = []
    if spike_start_s > 0:
        out.append(Segment(spike_start_s, base_rate, mix=mix))
    if spike_s > 0:
        out.append(Segment(spike_s, spike_rate, mix=mix))
    rest = total_s - spike_start_s - spike_s
    if rest > 0:
        out.append(Segment(rest, base_rate, mix=mix))
    return out


def session_mix(rate: float, total_s: float,
                one_shot_fraction: float) -> List[Segment]:
    """Session-heavy vs one-shot composition at a flat rate:
    ``one_shot_fraction`` of arrivals mint unique sessions (replica
    affinity has nothing to bind), the rest are multi-turn pool
    sessions (affinity and shared-prefix KV pay).  Sweeping the
    fraction separates capacity wins that come from cache locality
    from ones that come from raw slots."""
    f = max(0.0, min(1.0, float(one_shot_fraction)))
    return [Segment(total_s, rate,
                    mix=((KIND_CHAT, 1.0 - f), (KIND_ONESHOT, f))
                    if 0.0 < f < 1.0
                    else (((KIND_ONESHOT, 1.0),) if f >= 1.0
                          else ((KIND_CHAT, 1.0),)),
                    one_shot_fraction=f)]


def long_context_wave(chat_rate: float, wave_rate: float, total_s: float,
                      wave_every_s: float, wave_s: float) -> List[Segment]:
    """Chat traffic with periodic long-context waves riding on top:
    every ``wave_every_s`` a ``wave_s`` window adds ``wave_rate`` of
    ``long``-kind arrivals (prefill-heavy — the KV-pressure shape that
    exercises the spill tier under elasticity).  Off-wave segments are
    pure chat."""
    wave_every_s = max(wave_s, float(wave_every_s))
    out: List[Segment] = []
    t = 0.0
    while t < total_s:
        calm = min(wave_every_s - wave_s, total_s - t)
        if calm > 0:
            out.append(Segment(calm, chat_rate))
            t += calm
        if t >= total_s:
            break
        burst = min(wave_s, total_s - t)
        total = chat_rate + wave_rate
        out.append(Segment(burst, total,
                           mix=((KIND_CHAT, chat_rate / total),
                                (KIND_LONG, wave_rate / total))))
        t += burst
    return out


# -- schedule materialization -------------------------------------------------

def _draw_kind(rng: random.Random,
               mix: Tuple[Tuple[str, float], ...]) -> str:
    total = sum(w for _, w in mix) or 1.0
    x = rng.random() * total
    acc = 0.0
    for kind, w in mix:
        acc += w
        if x < acc:
            return kind
    return mix[-1][0]


def schedule(segments: Sequence[Segment], label: str = "scenario",
             seed: int = 0,
             max_arrivals: int = MAX_ARRIVALS) -> List[Arrival]:
    """Expand a segment profile into one ABSOLUTE arrival schedule:
    exponential gaps at each segment's rate (a piecewise-constant
    Poisson process — the gap in flight when a boundary passes is
    redrawn at the new rate), each arrival stamped with a kind from
    the segment's mix and a session id.  Deterministic per
    (segments, label, seed) — see the module docstring."""
    rng = random.Random(zlib.crc32(label.encode())
                        ^ (int(seed) & 0xFFFFFFFF))
    out: List[Arrival] = []
    t = 0.0
    t0 = 0.0
    i = 0
    for seg in segments:
        end = t0 + float(seg.duration_s)
        rate = float(seg.rate_req_per_s)
        if rate > 0:
            t = max(t, t0)
            while len(out) < max_arrivals:
                t += rng.expovariate(rate)
                if t >= end:
                    break
                kind = _draw_kind(rng, seg.mix)
                one_shot = (kind == KIND_ONESHOT
                            or rng.random() < seg.one_shot_fraction)
                session = (f"{label}-one-{i}" if one_shot
                           else f"{label}-s{rng.randrange(SESSION_POOL)}")
                out.append(Arrival(t_s=t, kind=kind, session=session,
                                   index=i))
                i += 1
        t0 = end
        if len(out) >= max_arrivals:
            break
    return out


# -- replay -------------------------------------------------------------------

def run_schedule(fire: Callable[[Arrival], None],
                 arrivals: Sequence[Arrival],
                 beat: Callable[[], None] = lambda: None,
                 deadline: Optional[float] = None,
                 time_scale: float = 1.0,
                 join_grace_s: float = JOIN_GRACE_S,
                 label: str = "scenario") -> Dict[str, Any]:
    """Replay an arrival schedule against ``fire`` (one daemon thread
    per arrival — an arrival NEVER waits for an earlier request).
    Openloop's absolute-schedule semantics: each arrival's target
    wall-clock instant is ``start + t_s × time_scale`` and the loop
    sleeps only the remaining distance, so falling behind produces a
    catch-up burst, never a deflated offered rate.  ``deadline``
    (``time.monotonic()``) truncates the replay and clamps the
    straggler join grace (floor 5 s) like the openloop points."""
    threads: List[threading.Thread] = []
    t_start = time.perf_counter()
    truncated = False
    for a in arrivals:
        target = t_start + a.t_s * time_scale
        lag = target - time.perf_counter()
        # Truncate BEFORE sleeping toward an arrival whose target lies
        # past the deadline — sleeping first would blow the budget by
        # up to one full inter-arrival gap.
        if (deadline is not None
                and time.monotonic() + max(lag, 0.0) >= deadline):
            truncated = True
            break
        if lag > 0:
            time.sleep(lag)
        t = threading.Thread(target=fire, args=(a,), daemon=True,
                             name=f"scenario-{label}-{a.index}")
        threads.append(t)
        t.start()
        beat()
    grace = join_grace_s
    if deadline is not None:
        grace = max(5.0, min(grace, deadline - time.monotonic()))
    join_deadline = time.monotonic() + grace
    for t in threads:
        t.join(timeout=max(0.0, join_deadline - time.monotonic()))
        beat()
    hung = sum(1 for t in threads if t.is_alive())
    return {
        "arrivals": len(threads),
        "hung_clients": hung,
        "truncated": truncated,
        "wall_s": round(time.perf_counter() - t_start, 2),
    }
