"""Benchmark results analysis — results_analysis.ipynb as a module.

Reference parity: the notebook loads final_results.csv, derives per-token
and human-unit metrics, and plots latency / energy / avg power /
latency-per-token / energy-per-token against the context threshold
(results_analysis.ipynb cells 4-22).  Here the same derivations run over
the v2 harness CSVs (bench/tester.py schemas) plus the TPU-native columns
(req/s, p50 TTFT, decode tok/s), emit a markdown report, and optionally
write the notebook's plot set as PNGs:

  python -m distributed_llm_tpu.bench.analysis \
      --summary-csv results.csv --per-query-csv per_query.csv \
      --output-md report.md --plots-dir plots/
"""

from __future__ import annotations

import argparse
from typing import List, Optional

import pandas as pd


def derive_metrics(df: pd.DataFrame) -> pd.DataFrame:
    """Add the notebook's derived columns in human units (s, J, W)."""
    out = df.copy()
    for dev in ("nano", "orin", "overall"):
        lat = f"{dev}_total_latency_ms"
        en = f"{dev}_total_energy_mJ"
        tok = f"{dev}_total_tokens"
        if lat in out:
            out[f"{dev}_latency_s"] = out[lat].astype(float) / 1000.0
        if en in out:
            out[f"{dev}_energy_J"] = out[en].astype(float) / 1000.0
        if lat in out and tok in out:
            toks = out[tok].astype(float)
            out[f"{dev}_s_per_token"] = (
                out[lat].astype(float) / 1000.0 / toks.where(toks > 0))
        if en in out and tok in out:
            toks = out[tok].astype(float)
            out[f"{dev}_J_per_token"] = (
                out[en].astype(float) / 1000.0 / toks.where(toks > 0))
    return out


def _fmt(v) -> str:
    if pd.isna(v) or v == "":
        return "—"
    if isinstance(v, float):
        return f"{v:.3f}"
    return str(v)


def markdown_report(summary: pd.DataFrame,
                    per_query: Optional[pd.DataFrame] = None) -> str:
    """Markdown tables: one per query set, ordered by strategy/threshold."""
    df = derive_metrics(summary)
    lines: List[str] = ["# Benchmark report", ""]

    cols = ["strategy", "cache_mode", "token_threshold", "routing_accuracy",
            "req_per_s", "p50_ttft_ms", "p50_latency_ms", "decode_tok_per_s",
            "nano_latency_s", "orin_latency_s", "overall_total_tokens"]
    cols = [c for c in cols if c in df.columns]

    for qset, group in df.groupby("query_set"):
        lines.append(f"## {qset}")
        lines.append("")
        lines.append("| " + " | ".join(cols) + " |")
        lines.append("|" + "---|" * len(cols))
        group = group.sort_values(["strategy", "cache_mode",
                                   "token_threshold"])
        for _, row in group.iterrows():
            lines.append("| " + " | ".join(_fmt(row[c]) for c in cols) + " |")
        lines.append("")

    if per_query is not None and len(per_query):
        lines.append("## Device split per strategy")
        lines.append("")
        pivot = (per_query.groupby(["strategy", "device_used"])
                 .size().unstack(fill_value=0))
        lines.append("| strategy | " +
                     " | ".join(map(str, pivot.columns)) + " |")
        lines.append("|" + "---|" * (len(pivot.columns) + 1))
        for strategy, row in pivot.iterrows():
            lines.append(f"| {strategy} | " +
                         " | ".join(str(int(v)) for v in row) + " |")
        lines.append("")

        hot = (per_query.assign(lat=per_query["latency_ms"].astype(float))
               .nlargest(5, "lat")[["strategy", "query_text", "device_used",
                                    "lat"]])
        lines.append("## Slowest queries")
        lines.append("")
        for _, r in hot.iterrows():
            lines.append(f"- **{r['lat']:.0f} ms** [{r['device_used']}/"
                         f"{r['strategy']}] {str(r['query_text'])[:90]}")
        lines.append("")
    return "\n".join(lines)


def write_plots(summary: pd.DataFrame, plots_dir: str) -> List[str]:
    """The notebook's plot set vs token threshold + a strategy overview."""
    import os

    import matplotlib
    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    os.makedirs(plots_dir, exist_ok=True)
    df = derive_metrics(summary)
    written: List[str] = []

    sweep = df[df["strategy"] == "token"]
    metrics = [("latency_s", "total latency (s)"),
               ("energy_J", "energy (J·proxy)"),
               ("s_per_token", "latency per token (s)"),
               ("J_per_token", "energy per token (J·proxy)")]
    if len(sweep) > 1:
        for key, label in metrics:
            fig, ax = plt.subplots(figsize=(6, 4))
            # One sorted line per (query set, cache mode) per device —
            # mixing them would zigzag back across thresholds.
            for (qset, cmode), grp in sweep.groupby(
                    ["query_set", "cache_mode"]):
                grp = grp.sort_values("token_threshold")
                for dev in ("nano", "orin"):
                    col = f"{dev}_{key}"
                    if col in grp:
                        ax.plot(grp["token_threshold"], grp[col], marker="o",
                                label=f"{dev} ({qset}, cache {cmode})")
            ax.set_xlabel("token threshold")
            ax.set_ylabel(label)
            ax.legend(fontsize=7)
            ax.set_title(f"{label} vs threshold (token strategy)")
            path = os.path.join(plots_dir, f"threshold_{key}.png")
            fig.savefig(path, dpi=120, bbox_inches="tight")
            plt.close(fig)
            written.append(path)

    if "req_per_s" in df.columns:
        per_strategy = (df.assign(req_per_s=pd.to_numeric(
            df["req_per_s"], errors="coerce"))
            .dropna(subset=["req_per_s"])
            .groupby("strategy").agg(req_per_s=("req_per_s", "max")))
        if len(per_strategy) == 0:
            return written          # header-only / failed-run CSV
        fig, ax = plt.subplots(figsize=(6, 4))
        per_strategy["req_per_s"].plot.bar(ax=ax)
        ax.set_ylabel("req/s")
        ax.set_title("throughput per routing strategy")
        path = os.path.join(plots_dir, "req_per_s.png")
        fig.savefig(path, dpi=120, bbox_inches="tight")
        plt.close(fig)
        written.append(path)
    return written


def main(argv: Optional[List[str]] = None) -> None:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--summary-csv", required=True)
    p.add_argument("--per-query-csv", default=None)
    p.add_argument("--output-md", default="benchmark_report.md")
    p.add_argument("--plots-dir", default=None)
    args = p.parse_args(argv)

    summary = pd.read_csv(args.summary_csv)
    per_query = (pd.read_csv(args.per_query_csv)
                 if args.per_query_csv else None)
    report = markdown_report(summary, per_query)
    with open(args.output_md, "w") as f:
        f.write(report)
    print(f"[done] report -> {args.output_md}")
    if args.plots_dir:
        for path in write_plots(summary, args.plots_dir):
            print(f"[done] plot -> {path}")


if __name__ == "__main__":
    main()
