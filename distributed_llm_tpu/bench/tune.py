"""Derive measured serving defaults from bench artifacts (VERDICT r2 #5).

The repo's standard is defaults-follow-measurement: attention dispatch
already works that way (`bench/ab_dispatch.json`), but the quant/
speculative tier defaults were hand-set — and round 2's CPU numbers even
contradicted them.  This tool closes the loop mechanically::

    python -m distributed_llm_tpu.bench.tune \
        --headline /tmp/BENCH_tpu.json [--spec /tmp/BENCH_tpu_spec.json] \
        --write

It reads the headline bench's per-tier quant A/B (``quant.<tier>``) and
the speculative A/B (``speculative.speedup`` from the spec-enabled run),
decides each tier's ``quantize`` / ``kv_quantize`` by which leg measured
faster, and publishes ``bench/tuning.json`` tagged with the backend it
was measured on.  The speculative default is additionally behind a
CAPABILITY gate (``SPEC_ENGINE_HAS_PREFIX_REUSE``): a measured decode
win is recorded in the table's evidence, but the default only flips
once the spec engine supports session prefix reuse — the table's
``spec_note`` says so, and ``DLLM_BENCH_SPEC_ORIN=1`` serves spec
explicitly regardless.  ``config.bench_cluster`` /
``config.cpu_bench_cluster`` overlay the table when (and only when) its
backend matches the running one — a CPU-derived table can never steer
the chip, and vice versa.
"""

from __future__ import annotations

import argparse
import json
import os

TUNING_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "tuning.json")

# Capability gate for the speculative default (see derive): the spec
# engine currently serves without session KV prefix reuse, so a
# measured decode-throughput win must not silently cost the multi-turn
# TTFT capability.  Flip to True when engine/speculative.py parks KV.
SPEC_ENGINE_HAS_PREFIX_REUSE = False


def derive(headline: dict, spec: dict = None,
           min_speedup: float = 1.05) -> dict:
    """Measured defaults from bench result dicts.  A feature must WIN by
    ``min_speedup`` to be enabled (ties keep the simpler configuration).

    Guards: a watchdog-aborted headline is not a measurement (raise); a
    spec artifact that aborted or ran on a DIFFERENT backend (independent
    probe fell back) is ignored with a note; kv_quantize was measured ON
    TOP of int8 weights (bench.py's i8kv/i8 ratio), so it is only
    enabled together with them — never stamped onto an unmeasured
    bf16-weights combination."""
    if headline.get("aborted"):
        raise ValueError("headline bench artifact is a watchdog-aborted "
                         "partial — refusing to derive defaults from it")
    out: dict = {"backend": headline.get("backend"), "tiers": {}}
    quant = headline.get("quant") or {}
    for tier in ("nano", "orin"):
        q = quant.get(tier) or {}
        entry: dict = {}
        if q.get("speedup"):
            entry["quantize"] = ("int8" if q["speedup"] >= min_speedup
                                 else "none")
        if q.get("kv_int8_speedup"):
            kv_wins = q["kv_int8_speedup"] >= min_speedup
            entry["kv_quantize"] = ("int8" if kv_wins
                                    and entry.get("quantize") == "int8"
                                    else "none")
        if entry:
            entry["evidence"] = {k: q.get(k) for k in ("speedup",
                                                       "kv_int8_speedup")}
            out["tiers"][tier] = entry
    if spec is not None:
        if spec.get("aborted"):
            out["spec_note"] = "spec artifact aborted — ignored"
        elif spec.get("backend") != out["backend"]:
            out["spec_note"] = (f"spec artifact backend "
                                f"{spec.get('backend')!r} != headline "
                                f"{out['backend']!r} — ignored")
        else:
            s = spec.get("speculative") or {}
            if s.get("speedup"):
                orin = out["tiers"].setdefault("orin", {})
                wins = bool(s["speedup"] >= min_speedup)
                # Engine-capability gate: SpeculativeEngine serves
                # WITHOUT session KV prefix reuse (engine/speculative.py
                # has no prefix cache), so defaulting spec on would
                # trade the measured multi-turn TTFT win (prefix-reuse
                # verdicts) for a decode-throughput win — a different
                # workload's trade that the single-turn A/B alone
                # cannot justify.  The measured speedup is recorded;
                # the default flips only once the spec engine parks KV
                # (or explicitly via DLLM_BENCH_SPEC_ORIN=1).
                orin["speculative"] = wins and SPEC_ENGINE_HAS_PREFIX_REUSE
                if wins and not SPEC_ENGINE_HAS_PREFIX_REUSE:
                    out["spec_note"] = (
                        "spec wins on decode throughput but the "
                        "speculative engine lacks session prefix reuse "
                        "— default stays off (capability gate); serve "
                        "it explicitly with DLLM_BENCH_SPEC_ORIN=1")
                orin.setdefault("evidence", {})["spec_speedup"] = \
                    s["speedup"]
    return out


def load_tuning(backend: str) -> dict:
    """The committed tuning table's tier overlays, or {} when absent or
    measured on a different backend."""
    try:
        with open(TUNING_PATH) as f:
            data = json.load(f)
    except (OSError, ValueError):
        return {}
    if data.get("backend") != backend:
        return {}
    return data.get("tiers", {})


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--headline", required=True,
                    help="bench.py output (full first line or partial file)")
    ap.add_argument("--spec", default=None,
                    help="DLLM_BENCH_SPEC_ORIN=1 bench output")
    ap.add_argument("--min-speedup", type=float, default=1.05)
    ap.add_argument("--write", action="store_true",
                    help="publish bench/tuning.json")
    ap.add_argument("--force", action="store_true",
                    help="overwrite even a hardware-measured table")
    args = ap.parse_args(argv)

    def read(path):
        with open(path) as f:
            for line in f:
                line = line.strip()
                if line.startswith("{"):
                    return json.loads(line)
        raise ValueError(f"{path}: no JSON line found")

    headline = read(args.headline)
    spec = read(args.spec) if args.spec else None
    tuning = derive(headline, spec, args.min_speedup)
    print(json.dumps(tuning, indent=1))
    if args.write:
        prior = None
        try:
            with open(TUNING_PATH) as f:
                prior = json.load(f).get("backend")
        except (OSError, ValueError):
            pass
        # Protect HARDWARE tables from cpu-fallback rounds; a hardware
        # run may always refresh (incl. replacing a stale cpu table) —
        # the read side ignores mismatched backends anyway.
        if (prior not in (None, "cpu", tuning["backend"])
                and tuning["backend"] == "cpu" and not args.force):
            print(f"# REFUSING to overwrite {TUNING_PATH}: measured on "
                  f"{prior!r}, this run is CPU fallback (--force to "
                  "override)")
            raise SystemExit(1)
        with open(TUNING_PATH + ".tmp", "w") as f:
            json.dump(tuning, f, indent=1)
        os.replace(TUNING_PATH + ".tmp", TUNING_PATH)
        print(f"# wrote {TUNING_PATH}")


if __name__ == "__main__":
    main()
