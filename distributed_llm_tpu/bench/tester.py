"""Benchmark + routing-accuracy tester — the canonical harness.

Reference parity: src/tests/routing_chatbot_tester.py (v2, the canonical
harness).  The CLI contract, sweep semantics, and CSV schemas are preserved
so existing experiment scripts run unchanged:

  python -m distributed_llm_tpu.bench.tester \
      --query-set general_knowledge \
      --thresholds 100 1000 4000 --fixed-threshold 1000 \
      --strategies token heuristic semantic hybrid perf \
      --cache-modes off on \
      --output-csv results.csv --output-per-query-csv per_query.csv

Sweep semantics kept exactly (routing_chatbot_tester.py:352-367):
- threshold sweep applies ONLY to the token strategy; every other strategy
  runs once at --fixed-threshold (default: last value of --thresholds);
- cache off → benchmark_mode=True (BENCHMARK_CFG), on → production
  (PRODUCTION_CFG);
- fresh Router per experiment config, cache cleared, one warmup query
  ("Reply with exactly: OK"), servers started before and stopped after each
  config, multi-turn conversation history accumulated across the query set.

What changed for TPU (SURVEY.md §5.1): the Jetson power subsystem (SSH'd
jtop loggers, scp'd power.log, mW·s integration) has no Cloud-TPU
equivalent, so --nano-ip/--orin-ip are accepted-and-ignored for drop-in
compatibility, energy columns are kept in both schemas but filled from the
telemetry sampler's HBM-occupancy integral (bytes·s, clearly not mJ —
column values carry unit suffix via --energy-proxy) or zero, and the
schemas gain TPU-native columns: per-query ``ttft_ms`` and
``decode_tok_per_s``; per-summary ``req_per_s`` and p50s of both.  Those
two additions are the north-star headline metrics (BASELINE.json).
"""

from __future__ import annotations

import argparse
import csv
import os
import statistics
import time
from dataclasses import dataclass, field
from datetime import datetime
from typing import Any, Dict, List, Optional, Tuple

from ..config import BENCHMARK_CFG, PRODUCTION_CFG
from ..serving.router import Router
from ..utils.telemetry import TierTelemetry
from .query_sets import query_sets

TOKEN_SWEEP_STRATEGIES = {"token"}

SUMMARY_HEADERS = [
    "query_set", "strategy", "cache_mode", "token_threshold",
    "routing_accuracy",
    "nano_total_latency_ms", "nano_total_energy_mJ", "nano_avg_power_mW",
    "nano_total_tokens", "nano_latency_per_token_ms", "nano_energy_per_token_mJ",
    "orin_total_latency_ms", "orin_total_energy_mJ", "orin_avg_power_mW",
    "orin_total_tokens", "orin_latency_per_token_ms", "orin_energy_per_token_mJ",
    "overall_total_latency_ms", "overall_total_energy_mJ", "overall_total_tokens",
    "overall_latency_per_token_ms", "overall_energy_per_token_mJ",
    # TPU-native additions (north-star metrics)
    "req_per_s", "p50_ttft_ms", "p50_latency_ms", "decode_tok_per_s",
]

PER_QUERY_HEADERS = [
    "query_set", "strategy", "cache_mode", "token_threshold",
    "query_index", "query_text", "expected_device",
    "device_used", "cache_hit",
    "routing_method", "routing_confidence", "routing_reasoning",
    "routing_overhead_ms",
    "start_time", "end_time", "latency_ms", "response_tokens",
    "energy_mJ", "latency_per_token_ms", "energy_per_token_mJ",
    # TPU-native additions
    "ttft_ms", "decode_tok_per_s",
]


@dataclass
class QueryItem:
    text: str
    expected_device: Optional[str] = None


@dataclass
class RunConfig:
    query_set_name: str
    thresholds: List[int]
    strategies: List[str]
    cache_modes: List[str]
    fixed_threshold_for_non_token: int
    output_csv: str
    output_per_query_csv: str
    router_kwargs: Dict[str, Any] = field(default_factory=dict)
    telemetry: bool = True


def normalize_query_set(raw_items: Any) -> List[QueryItem]:
    """Accept list[str] or list[dict{query|text, expected_device|label}]
    (routing_chatbot_tester.py:75-112)."""
    if not isinstance(raw_items, list):
        raise ValueError("query_sets[<name>] must be a list")
    out: List[QueryItem] = []
    for x in raw_items:
        if isinstance(x, str):
            if x.strip():
                out.append(QueryItem(text=x.strip()))
        elif isinstance(x, dict):
            q = (x.get("query") or x.get("text") or "").strip()
            if not q:
                continue
            exp = x.get("expected_device") or x.get("label")
            if isinstance(exp, str):
                exp = exp.lower().strip()
                if exp not in ("nano", "orin"):
                    exp = None
            else:
                exp = None
            out.append(QueryItem(text=q, expected_device=exp))
    if not out:
        raise ValueError("Query set is empty after normalization")
    return out


def build_router_config(cache_enabled: bool, token_threshold: int) -> Dict[str, Any]:
    base = PRODUCTION_CFG if cache_enabled else BENCHMARK_CFG
    return {**base, "token_threshold": token_threshold}


def try_clear_cache(router: Router) -> None:
    qr = getattr(router, "query_router", None)
    if qr is not None and hasattr(qr, "clear_cache"):
        try:
            qr.clear_cache()
        except Exception:
            pass


def warmup(router: Router) -> None:
    try:
        router.route_query([{"role": "user", "content": "Reply with exactly: OK"}])
    except Exception:
        pass


def compute_accuracy(rows: List[Dict[str, Any]]) -> Optional[float]:
    labeled = [r for r in rows if r.get("expected_device") in ("nano", "orin")]
    if not labeled:
        return None
    correct = sum(1 for r in labeled
                  if r.get("device_used") == r.get("expected_device"))
    return correct / len(labeled)


def ensure_csv_headers(path: str, headers: List[str]) -> None:
    if os.path.exists(path) and os.path.getsize(path) > 0:
        return
    with open(path, "w", newline="") as f:
        csv.writer(f).writerow(headers)


def append_csv_row(path: str, headers: List[str], row: Dict[str, Any]) -> None:
    with open(path, "a", newline="") as f:
        csv.writer(f).writerow([row.get(h, "") for h in headers])


def _build_telemetry(cluster=None) -> TierTelemetry:
    """Telemetry scoped to each tier's carved submesh, so per-tier energy
    columns integrate only that tier's chips (on a shared single-chip box
    the tiers legitimately see the same device)."""
    from ..parallel.mesh import carve_tier_meshes
    from ..serving.router import default_cluster
    meshes = carve_tier_meshes(cluster or default_cluster())
    tier_devices = {name: [d.id for d in mesh.devices.flat]
                    for name, mesh in meshes.items()}
    return TierTelemetry(tier_devices.keys(), tier_devices=tier_devices)


def _experiment_grid(run_cfg: RunConfig):
    """(strategy, cache_mode, threshold) triples, reference sweep semantics."""
    for strategy in run_cfg.strategies:
        for cache_mode in run_cfg.cache_modes:
            thresholds = (run_cfg.thresholds
                          if strategy in TOKEN_SWEEP_STRATEGIES
                          else [run_cfg.fixed_threshold_for_non_token])
            for threshold in thresholds:
                yield strategy, cache_mode, threshold


def run_experiment(query_items: List[QueryItem], run_cfg: RunConfig) -> List[Dict[str, Any]]:
    ensure_csv_headers(run_cfg.output_csv, SUMMARY_HEADERS)
    ensure_csv_headers(run_cfg.output_per_query_csv, PER_QUERY_HEADERS)

    telemetry = (_build_telemetry(run_cfg.router_kwargs.get("cluster"))
                 if run_cfg.telemetry else None)
    if telemetry:
        telemetry.start()

    all_rows: List[Dict[str, Any]] = []
    experiment_wall: Dict[Tuple[str, str, int], float] = {}

    for strategy, cache_mode, threshold in _experiment_grid(run_cfg):
        cache_enabled = cache_mode.lower() == "on"
        benchmark_mode = not cache_enabled
        config = build_router_config(cache_enabled, threshold)

        try:
            router = Router(strategy=strategy, config=config,
                            threshold_fallback=threshold,
                            benchmark_mode=benchmark_mode,
                            **run_cfg.router_kwargs)
        except Exception as exc:
            print(f"[skip] strategy={strategy} cache={cache_mode} "
                  f"thr={threshold} -> {exc}")
            continue

        print(f"[run] strategy={strategy} cache={cache_mode} "
              f"benchmark_mode={benchmark_mode} threshold={threshold}",
              flush=True)

        for tier in (router.nano, router.orin):
            try:
                tier.server_manager.start_server()
            except Exception:
                pass
        try_clear_cache(router)
        warmup(router)

        conversation_history: List[Dict[str, str]] = []
        per_rows: List[Dict[str, Any]] = []
        t_experiment = time.perf_counter()

        for i, qi in enumerate(query_items):
            conversation_history.append({"role": "user", "content": qi.text})
            base = {
                "query_set": run_cfg.query_set_name,
                "strategy": strategy,
                "cache_mode": cache_mode,
                "token_threshold": threshold,
                "query_index": i,
                "query_text": qi.text,
                "expected_device": qi.expected_device,
            }
            start_time = datetime.now()
            t0 = time.perf_counter()
            try:
                response, response_tokens, device_used = \
                    router.route_query(conversation_history)
            except Exception as exc:
                latency_ms = int((time.perf_counter() - t0) * 1000)
                per_rows.append({**base, "device_used": "error",
                                 "start_time": start_time,
                                 "end_time": datetime.now(),
                                 "latency_ms": latency_ms,
                                 "response_tokens": 0, "energy_mJ": 0.0})
                print(f"[err] strategy={strategy} i={i}: {exc}")
                continue

            end_time = datetime.now()
            latency_ms = int((time.perf_counter() - t0) * 1000)

            if isinstance(response, dict):
                assistant_text = str(response.get("response", ""))
                meta = {k: response.get(k, "") for k in
                        ("cache_hit", "routing_method", "routing_confidence",
                         "routing_reasoning", "routing_overhead_ms")}
            else:
                assistant_text = str(response)
                meta = {}
            conversation_history.append(
                {"role": "assistant", "content": assistant_text})

            # last_result is only fresh when this query actually ran the
            # engine: cache hits and double-tier failures leave it stale.
            tier = router.tiers.get(device_used)
            result = tier.last_result if tier else None
            fresh = (result is not None and not meta.get("cache_hit")
                     and (not isinstance(response, dict)
                          or response.get("ok", True)))
            ttft_ms = round(result.ttft_ms, 2) if fresh else ""
            tok_per_s = round(result.tokens_per_s, 2) if fresh else ""

            per_rows.append({
                **base,
                "device_used": device_used,
                "cache_hit": meta.get("cache_hit", ""),
                "routing_method": meta.get("routing_method", ""),
                "routing_confidence": meta.get("routing_confidence", ""),
                "routing_reasoning": meta.get("routing_reasoning", ""),
                "routing_overhead_ms": meta.get("routing_overhead_ms", ""),
                "start_time": start_time,
                "end_time": end_time,
                "latency_ms": latency_ms,
                "response_tokens": int(response_tokens or 0),
                "ttft_ms": ttft_ms,
                "decode_tok_per_s": tok_per_s,
            })

        experiment_wall[(strategy, cache_mode, threshold)] = (
            time.perf_counter() - t_experiment)
        all_rows.extend(per_rows)

        # Stop tiers between configs to reduce state carryover
        # (routing_chatbot_tester.py:491-498).
        for tier in (router.nano, router.orin):
            try:
                tier.server_manager.stop_server()
            except Exception:
                pass

    if telemetry:
        telemetry.stop()

    # Fill energy + derived per-token metrics, write per-query CSV.
    for row in all_rows:
        dev = row.get("device_used")
        if dev not in ("nano", "orin"):
            row["energy_mJ"] = 0.0
            row["latency_per_token_ms"] = ""
            row["energy_per_token_mJ"] = ""
        else:
            e = (telemetry.energy_for_window(dev, row["start_time"],
                                             row["end_time"])
                 if telemetry else 0.0)
            row["energy_mJ"] = round(e, 3)
            toks = int(row.get("response_tokens") or 0)
            lat = int(row.get("latency_ms") or 0)
            row["latency_per_token_ms"] = (lat / toks) if toks > 0 else ""
            row["energy_per_token_mJ"] = (e / toks) if toks > 0 else ""
        row["start_time"] = row["start_time"].isoformat(sep=" ")
        row["end_time"] = row["end_time"].isoformat(sep=" ")
        append_csv_row(run_cfg.output_per_query_csv, PER_QUERY_HEADERS, row)

    # Per-experiment summary rows.
    grouped: Dict[Tuple[str, str, int], List[Dict[str, Any]]] = {}
    for r in all_rows:
        key = (r["strategy"], r["cache_mode"], int(r["token_threshold"]))
        grouped.setdefault(key, []).append(r)

    for key, rows in grouped.items():
        strategy, cache_mode, threshold = key
        acc = compute_accuracy(rows)

        def agg(dev: str) -> Tuple[int, float, int]:
            sel = [x for x in rows if x.get("device_used") == dev]
            return (sum(int(x.get("latency_ms") or 0) for x in sel),
                    sum(float(x.get("energy_mJ") or 0.0) for x in sel),
                    sum(int(x.get("response_tokens") or 0) for x in sel))

        nano_lat, nano_e, nano_t = agg("nano")
        orin_lat, orin_e, orin_t = agg("orin")
        overall_lat = nano_lat + orin_lat
        overall_e = nano_e + orin_e
        overall_t = nano_t + orin_t

        def per(num, den):
            return round(num / den, 6) if den > 0 else ""

        wall = experiment_wall.get(key, 0.0)
        ttfts = [float(x["ttft_ms"]) for x in rows
                 if x.get("ttft_ms") not in ("", None)]
        lats = [int(x.get("latency_ms") or 0) for x in rows]
        tps = [float(x["decode_tok_per_s"]) for x in rows
               if x.get("decode_tok_per_s") not in ("", None)]

        append_csv_row(run_cfg.output_csv, SUMMARY_HEADERS, {
            "query_set": run_cfg.query_set_name,
            "strategy": strategy,
            "cache_mode": cache_mode,
            "token_threshold": threshold,
            "routing_accuracy": "" if acc is None else round(acc, 4),
            "nano_total_latency_ms": nano_lat,
            "nano_total_energy_mJ": round(nano_e, 3),
            "nano_avg_power_mW": per(nano_e, nano_lat / 1000) or 0.0,
            "nano_total_tokens": nano_t,
            "nano_latency_per_token_ms": per(nano_lat, nano_t),
            "nano_energy_per_token_mJ": per(nano_e, nano_t),
            "orin_total_latency_ms": orin_lat,
            "orin_total_energy_mJ": round(orin_e, 3),
            "orin_avg_power_mW": per(orin_e, orin_lat / 1000) or 0.0,
            "orin_total_tokens": orin_t,
            "orin_latency_per_token_ms": per(orin_lat, orin_t),
            "orin_energy_per_token_mJ": per(orin_e, orin_t),
            "overall_total_latency_ms": overall_lat,
            "overall_total_energy_mJ": round(overall_e, 3),
            "overall_total_tokens": overall_t,
            "overall_latency_per_token_ms": per(overall_lat, overall_t),
            "overall_energy_per_token_mJ": per(overall_e, overall_t),
            "req_per_s": round(len(rows) / wall, 4) if wall > 0 else "",
            "p50_ttft_ms": round(statistics.median(ttfts), 2) if ttfts else "",
            "p50_latency_ms": round(statistics.median(lats), 2) if lats else "",
            "decode_tok_per_s": round(statistics.median(tps), 2) if tps else "",
        })

    print(f"[done] wrote summary -> {run_cfg.output_csv}")
    print(f"[done] wrote per-query -> {run_cfg.output_per_query_csv}")
    return all_rows


def parse_args(argv: Optional[List[str]] = None) -> argparse.Namespace:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--query-set", required=True,
                   help="Key in query_sets (e.g., general_knowledge)")
    p.add_argument("--thresholds", nargs="+", type=int, default=[4000],
                   help="Thresholds swept ONLY for the token strategy")
    p.add_argument("--fixed-threshold", type=int, default=None,
                   help="Threshold for non-token strategies "
                        "(default: last of --thresholds)")
    p.add_argument("--strategies", nargs="+",
                   default=["token", "heuristic", "semantic", "hybrid"])
    p.add_argument("--cache-modes", nargs="+", default=["off"],
                   choices=["off", "on"])
    p.add_argument("--output-csv", default="benchmark_results.csv")
    p.add_argument("--output-per-query-csv", default="benchmark_per_query.csv")
    p.add_argument("--append", action="store_true",
                   help="Append to existing output CSVs instead of "
                        "starting fresh (multi-invocation sweeps "
                        "accumulating one artifact)")
    p.add_argument("--no-telemetry", action="store_true",
                   help="Disable the HBM telemetry sampler")
    p.add_argument("--platform", default=None,
                   help="pin jax_platforms (e.g. cpu) — the env var alone "
                        "loses to this image's PJRT sitecustomize, and an "
                        "unpinned run on a wedged chip blocks in the claim "
                        "loop")
    # Accepted-and-ignored: the reference required SSH endpoints for its
    # Jetson power loggers; TPU tiers are in-process.
    for flag, default in (("--nano-ip", None), ("--orin-ip", None),
                          ("--nano-ssh-user", "nano"),
                          ("--orin-ssh-user", "orin")):
        p.add_argument(flag, default=default, help=argparse.SUPPRESS)
    for flag in ("--nano-ssh-port", "--orin-ssh-port"):
        p.add_argument(flag, type=int, default=22, help=argparse.SUPPRESS)
    return p.parse_args(argv)


def main(argv: Optional[List[str]] = None) -> None:
    args = parse_args(argv)
    # Persistent compile cache: the sweep builds a FRESH Router (fresh
    # jit closures) per config — on chip, without the cache, every
    # config re-pays the full warmup compile bill.
    from ..utils.compile_cache import enable_persistent_compile_cache
    enable_persistent_compile_cache()
    if args.platform:
        import jax
        jax.config.update("jax_platforms", args.platform)
    if args.query_set not in query_sets:
        raise ValueError(f"Unknown query set: {args.query_set}. "
                         f"Available: {list(query_sets)}")
    query_items = normalize_query_set(query_sets[args.query_set])
    fixed = (args.fixed_threshold if args.fixed_threshold is not None
             else args.thresholds[-1])
    run_cfg = RunConfig(
        query_set_name=args.query_set,
        thresholds=args.thresholds,
        strategies=args.strategies,
        cache_modes=args.cache_modes,
        fixed_threshold_for_non_token=fixed,
        output_csv=args.output_csv,
        output_per_query_csv=args.output_per_query_csv,
        telemetry=not args.no_telemetry,
    )
    # Fresh files each run to avoid header drift across versions;
    # --append keeps them (ensure_csv_headers only writes headers into
    # empty/new files, so rows accumulate under one header).
    if not args.append:
        for path in (run_cfg.output_csv, run_cfg.output_per_query_csv):
            if os.path.exists(path):
                os.remove(path)
    run_experiment(query_items, run_cfg)


if __name__ == "__main__":
    main()
