"""distributed_llm_tpu — a TPU-native distributed LLM serving framework.

A ground-up JAX/XLA/Pallas re-design of the capabilities of the reference
system ``clumpygum/distributed-llm`` (a query-routing chatbot dispatching
prompts across heterogeneous LLM serving devices).  Where the reference
outsources model execution to Ollama (llama.cpp) on LAN-separated Jetson
boards, this framework owns the entire inference stack natively on TPU:

- ``engine/``   tokenizer, XLA-compiled prefill + autoregressive decode with a
                KV cache resident in HBM, sampling, lifecycle management.
- ``models/``   pure-JAX (functional) LLaMA-style transformer definitions and
                size presets for the two serving tiers ("nano" 1-chip,
                "orin" multi-chip tensor-parallel).
- ``ops/``      attention + sampling ops; Pallas TPU kernels for the hot paths.
- ``parallel/`` device mesh / submesh utilities, tensor-parallel sharding
                rules, ICI collectives (health allgather), ring attention for
                sequence parallelism.
- ``routing/``  the query-routing engine: five strategies, the predictive
                routing cache, and token counting (reference parity:
                src/query_router_engine.py, src/cache.py, src/token_counter.py).
- ``serving/``  Router orchestration, the Flask ``/chat`` app, and the
                per-tier ``/query`` + ``/health`` device API (reference parity:
                src/router.py, src/app.py, src/devices/*_api.py,
                src/models/{nano,orin}.py, src/models/server_manager.py).
- ``bench/``    labeled query sets and the benchmark harness, CLI-compatible
                with the reference's src/tests/routing_chatbot_tester.py.
- ``training/`` sharded train step (dp x tp) for fine-tuning tier models.
"""

__version__ = "0.1.0"
