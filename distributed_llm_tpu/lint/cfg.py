"""Per-function control-flow graphs with exception edges — the dataflow
substrate for the v3 ownership checker (lint/checkers/ownership.py).

The graph is statement-granular: one node per simple statement, one node
per atomic branch condition (``and``/``or`` chains are decomposed into
their short-circuit conjuncts so a guard like ``blocks is None and
pop_oldest() is not None`` refines ``blocks`` before the second conjunct
can run), plus three synthetic nodes — ENTRY, EXIT (normal completion:
``return`` or falling off the end) and RAISES (an exception escaping the
function).

Exception edges — what can raise
--------------------------------

Only statements that *contain a call* (plus ``raise``, ``assert`` and
``for``-iteration headers) get an exception edge.  Attribute reads,
subscripts and arithmetic can raise in principle, but modelling them
would hang an exceptional exit off nearly every line, and every such
edge is a potential leak report; a dataflow client that must not
manufacture findings needs the edge set to under-approximate, never
over-approximate (a missing edge hides a real leak — acceptable; an
impossible edge invents one — not).  Calls inside ``lambda``/nested
``def`` bodies do not count: building a closure raises nothing.

Where an exception lands:

- inside ``try`` with handlers: at the handler-dispatch node, which
  fans out to every handler head.  A handler set is *catch-all* when it
  includes a bare ``except``, ``except BaseException`` or ``except
  Exception`` — otherwise the dispatch keeps an extra edge outward
  (a non-matching exception keeps propagating).  Treating ``Exception``
  as catch-all is a deliberate approximation: the only traffic it
  misses is KeyboardInterrupt/SystemExit, and charging every
  ``except Exception: cleanup`` block with a phantom escape path would
  drown real findings in un-actionable ones.
- ``finally`` bodies are CLONED per completion class (normal /
  exceptional / return / break / continue), each clone wired to that
  class's continuation — precise routing, not a merged
  over-approximation.  Bodies are tiny in this repo; at most a handful
  of clones each.
- ``with`` blocks add no special routing: the context expression's
  calls can raise, body exceptions propagate outward.  A context
  manager that *suppresses* exceptions in ``__exit__`` is not modelled
  (none in this repo do).

Not built: ``match`` statements (none in the repo; the builder raises
:class:`UnsupportedFlow` so clients can skip the function rather than
analyze a graph with holes).  Generator and ``async`` bodies build
fine but callers should skip them — a suspended frame's lifetime is
not path-shaped (see the ownership checker's scope rules).
"""

from __future__ import annotations

import ast
from typing import Callable, Dict, List, Optional, Tuple

__all__ = [
    "CFG", "Node", "Edge", "UnsupportedFlow", "build_cfg", "stmt_raises",
    "contains_call",
]

# Node kinds.  "stmt" carries a simple statement; "test" an atomic branch
# condition; "for-iter" evaluates the iterable; "for-bind" rebinds the
# loop target each iteration; "with" evaluates context expressions and
# binds ``as`` targets; "except" binds a handler's ``as`` name; "join"
# is an empty wiring point (includes ENTRY); "exit"/"raises" terminate.
STMT, TEST, JOIN, EXIT, RAISES = "stmt", "test", "join", "exit", "raises"


class UnsupportedFlow(Exception):
    """Raised for control flow the builder does not model (``match``)."""


class Edge:
    """One successor edge.  ``exc`` marks exceptional flow.  ``refine``
    is ``(test_expr, branch_is_true)`` on the two out-edges of a test
    node so dataflow clients can narrow optional-acquire states."""

    __slots__ = ("dst", "exc", "refine")

    def __init__(self, dst: int, exc: bool = False,
                 refine: Optional[Tuple[ast.expr, bool]] = None):
        self.dst = dst
        self.exc = exc
        self.refine = refine

    def __repr__(self):  # pragma: no cover - debugging aid
        tag = "!" if self.exc else ""
        return f"->{tag}{self.dst}"


class Node:
    __slots__ = ("ix", "kind", "stmt", "expr", "succ")

    def __init__(self, ix: int, kind: str, stmt: Optional[ast.AST] = None,
                 expr: Optional[ast.expr] = None):
        self.ix = ix
        self.kind = kind
        self.stmt = stmt          # payload statement (STMT / for-* / with)
        self.expr = expr          # payload expression (TEST)
        self.succ: List[Edge] = []

    def __repr__(self):  # pragma: no cover - debugging aid
        return f"<{self.kind}@{self.ix} {self.succ}>"


class CFG:
    """nodes[entry] is a JOIN; EXIT/RAISES have no successors."""

    def __init__(self):
        self.nodes: List[Node] = []
        self.entry = 0
        self.exit = 0
        self.raises = 0


def contains_call(node: ast.AST) -> bool:
    """True if evaluating ``node`` runs a call — calls under
    ``lambda``/nested ``def`` are building closures, not running them."""
    stack = [node]
    while stack:
        cur = stack.pop()
        if isinstance(cur, ast.Call):
            return True
        if isinstance(cur, (ast.Lambda, ast.FunctionDef,
                            ast.AsyncFunctionDef)) and cur is not node:
            continue                      # closure body: not executed now
        stack.extend(ast.iter_child_nodes(cur))
    return False


def stmt_raises(stmt: ast.stmt) -> bool:
    """Can executing this *simple* statement raise (see module doc)?"""
    if isinstance(stmt, (ast.Raise, ast.Assert)):
        return True
    return contains_call(stmt)


_SIMPLE = (ast.Assign, ast.AugAssign, ast.AnnAssign, ast.Expr, ast.Delete,
           ast.Pass, ast.Import, ast.ImportFrom, ast.Global, ast.Nonlocal,
           ast.Assert)


class _Builder:
    """Backward block builder: each statement is wired knowing the node
    that follows it.  Abrupt-completion targets (where ``raise``,
    ``return``, ``break``, ``continue`` land) are *thunks* so that
    entering a ``try/finally`` can wrap them with a freshly cloned
    ``finally`` body, memoized per (scope, continuation) pair."""

    def __init__(self):
        self.cfg = CFG()
        entry = self._node(JOIN)
        self.cfg.entry = entry.ix
        self.cfg.exit = self._node(EXIT).ix
        self.cfg.raises = self._node(RAISES).ix
        # Routing thunks: call → node index to jump to.
        self._exc: Callable[[], int] = lambda: self.cfg.raises
        self._ret: Callable[[], int] = lambda: self.cfg.exit
        self._loops: List[Tuple[Callable[[], int], Callable[[], int]]] = []

    # -- graph primitives --------------------------------------------------

    def _node(self, kind: str, stmt: Optional[ast.AST] = None,
              expr: Optional[ast.expr] = None) -> Node:
        n = Node(len(self.cfg.nodes), kind, stmt, expr)
        self.cfg.nodes.append(n)
        return n

    def _edge(self, src: int, dst: int, exc: bool = False,
              refine=None) -> None:
        self.cfg.nodes[src].succ.append(Edge(dst, exc, refine))

    # -- blocks ------------------------------------------------------------

    def build(self, func) -> CFG:
        body_entry = self._block(func.body, self.cfg.exit)
        self._edge(self.cfg.entry, body_entry)
        return self.cfg

    def _block(self, stmts: List[ast.stmt], follow: int) -> int:
        for st in reversed(stmts):
            follow = self._stmt(st, follow)
        return follow

    # -- statements --------------------------------------------------------

    def _stmt(self, st: ast.stmt, follow: int) -> int:
        if isinstance(st, _SIMPLE):
            n = self._node(STMT, stmt=st)
            self._edge(n.ix, follow)
            if stmt_raises(st):
                self._edge(n.ix, self._exc(), exc=True)
            return n.ix
        if isinstance(st, ast.Return):
            n = self._node(STMT, stmt=st)
            self._edge(n.ix, self._ret())
            if st.value is not None and contains_call(st.value):
                self._edge(n.ix, self._exc(), exc=True)
            return n.ix
        if isinstance(st, ast.Raise):
            n = self._node(STMT, stmt=st)
            self._edge(n.ix, self._exc(), exc=True)
            return n.ix
        if isinstance(st, ast.Break):
            n = self._node(STMT, stmt=st)
            self._edge(n.ix, self._loops[-1][0]())
            return n.ix
        if isinstance(st, ast.Continue):
            n = self._node(STMT, stmt=st)
            self._edge(n.ix, self._loops[-1][1]())
            return n.ix
        if isinstance(st, ast.If):
            true_ix = self._block(st.body, follow)
            false_ix = self._block(st.orelse, follow) if st.orelse else follow
            return self._test(st.test, true_ix, false_ix)
        if isinstance(st, ast.While):
            return self._while(st, follow)
        if isinstance(st, (ast.For, ast.AsyncFor)):
            return self._for(st, follow)
        if isinstance(st, (ast.With, ast.AsyncWith)):
            return self._with(st, follow)
        if isinstance(st, ast.Try):
            return self._try(st, follow)
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef)):
            # Nested definition: the body is not executed here; the
            # decorators/defaults are.  One opaque node suffices —
            # escape analysis of closed-over names is the checker's job.
            n = self._node(STMT, stmt=st)
            self._edge(n.ix, follow)
            if any(contains_call(d) for d in getattr(st, "decorator_list",
                                                     ())):
                self._edge(n.ix, self._exc(), exc=True)
            return n.ix
        # match (3.10+) and anything newer: refuse rather than build a
        # graph with invisible inner flow.
        raise UnsupportedFlow(type(st).__name__)

    def _while(self, st: ast.While, follow: int) -> int:
        head = self._node(JOIN)
        # ``else`` runs on normal loop exhaustion, not on break.
        after_else = self._block(st.orelse, follow) if st.orelse else follow
        self._loops.append((lambda: follow, lambda: head.ix))
        try:
            body_entry = self._block(st.body, head.ix)
        finally:
            self._loops.pop()
        test_entry = self._test(st.test, body_entry, after_else)
        self._edge(head.ix, test_entry)
        return head.ix

    def _for(self, st, follow: int) -> int:
        # iter-node (evaluate the iterable) → dispatch ⇄ bind → body.
        dispatch = self._node(JOIN)
        after_else = self._block(st.orelse, follow) if st.orelse else follow
        self._loops.append((lambda: follow, lambda: dispatch.ix))
        try:
            body_entry = self._block(st.body, dispatch.ix)
        finally:
            self._loops.pop()
        bind = self._node("for-bind", stmt=st)
        self._edge(bind.ix, body_entry)
        self._edge(dispatch.ix, bind.ix)
        self._edge(dispatch.ix, after_else)
        it = self._node("for-iter", stmt=st)
        self._edge(it.ix, dispatch.ix)
        if contains_call(st.iter):
            self._edge(it.ix, self._exc(), exc=True)
        return it.ix

    def _with(self, st, follow: int) -> int:
        body_entry = self._block(st.body, follow)
        n = self._node("with", stmt=st)
        self._edge(n.ix, body_entry)
        if any(contains_call(item.context_expr) for item in st.items):
            self._edge(n.ix, self._exc(), exc=True)
        return n.ix

    # -- branch conditions -------------------------------------------------

    def _test(self, expr: ast.expr, true_ix: int, false_ix: int) -> int:
        """Short-circuit decomposition: one TEST node per atomic
        conjunct, refinement labels on its true/false edges."""
        if isinstance(expr, ast.BoolOp) and isinstance(expr.op, ast.And):
            for v in reversed(expr.values):
                true_ix = self._test(v, true_ix, false_ix)
            return true_ix
        if isinstance(expr, ast.BoolOp) and isinstance(expr.op, ast.Or):
            for v in reversed(expr.values):
                false_ix = self._test(v, true_ix, false_ix)
            return false_ix
        if isinstance(expr, ast.UnaryOp) and isinstance(expr.op, ast.Not):
            return self._test(expr.operand, false_ix, true_ix)
        const: Optional[bool] = None
        if isinstance(expr, ast.Constant):
            const = bool(expr.value)
        n = self._node(TEST, expr=expr)
        if const is None or const:
            self._edge(n.ix, true_ix, refine=(expr, True))
        if const is None or not const:
            self._edge(n.ix, false_ix, refine=(expr, False))
        if contains_call(expr):
            self._edge(n.ix, self._exc(), exc=True)
        return n.ix

    # -- try / except / finally --------------------------------------------

    def _try(self, st: ast.Try, follow: int) -> int:
        outer_exc, outer_ret = self._exc, self._ret
        outer_loops = list(self._loops)

        if st.finalbody:
            # Wrap every routing thunk with a clone of the finally body
            # whose continuation is that thunk's target; memoize per
            # continuation so e.g. fifty calls in the body share one
            # exceptional clone.
            cache: Dict[int, int] = {}

            def through_finally(target_thunk):
                def thunk():
                    target = target_thunk()
                    if target not in cache:
                        cache[target] = self._block(st.finalbody, target)
                    return cache[target]
                return thunk

            follow = through_finally(lambda: follow)()
            self._exc = through_finally(outer_exc)
            self._ret = through_finally(outer_ret)
            self._loops = [(through_finally(b), through_finally(c))
                           for (b, c) in self._loops]

        # From here the thunks are the finally-wrapped outer targets —
        # what handler bodies and the dispatch escape edge use.  Handlers
        # are built BEFORE the body override below, so an exception
        # raised inside a handler routes outward, never back to itself.
        if st.handlers:
            dispatch = self._node(JOIN)
            for h in st.handlers:
                head = self._node("except", stmt=h)
                self._edge(head.ix, self._block(h.body, follow))
                self._edge(dispatch.ix, head.ix)
            if not _catches_all(st.handlers):
                self._edge(dispatch.ix, self._exc(), exc=True)
            body_exc: Callable[[], int] = lambda: dispatch.ix
        else:
            body_exc = self._exc

        body_follow = self._block(st.orelse, follow) if st.orelse else follow
        self._exc = body_exc
        try:
            body_entry = self._block(st.body, body_follow)
        finally:
            self._exc, self._ret = outer_exc, outer_ret
            self._loops = outer_loops
        return body_entry


def _catches_all(handlers: List[ast.ExceptHandler]) -> bool:
    for h in handlers:
        if h.type is None:
            return True
        t = h.type
        names = ([_leaf(e) for e in t.elts] if isinstance(t, ast.Tuple)
                 else [_leaf(t)])
        if "BaseException" in names or "Exception" in names:
            return True
    return False


def _leaf(expr: ast.expr) -> Optional[str]:
    if isinstance(expr, ast.Name):
        return expr.id
    if isinstance(expr, ast.Attribute):
        return expr.attr
    return None


def build_cfg(func) -> CFG:
    """CFG for one ``FunctionDef``/``AsyncFunctionDef``.  Raises
    :class:`UnsupportedFlow` on ``match`` statements."""
    return _Builder().build(func)
