"""JIT-purity lint: host-side impurity inside traced computations.

A function is a JIT ROOT when it is decorated with ``jax.jit`` /
``pjit`` / ``shard_map`` (directly or through ``partial``), or passed
to one of those as the function argument (``jax.jit(run)``,
``shard_map(step, mesh=...)``, ``jax.jit(partial(init, cfg))``,
``jax.jit(lambda: ...)``).  ``pl.pallas_call`` counts as a wrapper too:
a Pallas KERNEL body is traced exactly like a jitted function (and a
blocking host call inside one wedges the whole device program), so the
kernels in ops/pallas_attention.py and ops/ragged_attention.py are
roots — including the repo idiom ``kernel = partial(_kernel, ...)``
followed by ``pl.pallas_call(kernel, ...)``, resolved through the
module-local assignment.  The checker walks roots plus every
module-local function they transitively call (cross-module callees are
out of static reach and skipped — keep traced helpers in the module
that jits them, or lint them where they live).

Rules:

- ``jit-host-impurity``: ``time.*``, ``print``, Python/NumPy RNG
  (``random.*`` / ``np.random.*`` — host randomness baked in at trace
  time; use ``jax.random`` with explicit keys), ``open(...)`` and
  ``.block_until_ready()`` (a host sync point has no meaning inside a
  traced function) anywhere in a jit-reachable body.  ``jax.debug.*``
  and ``jax.random.*`` are exempt by construction (matched by module
  root).
- ``jit-traced-concretization``: on the root function itself,
  ``bool()`` / ``int()`` / ``float()`` / ``len()`` over an expression
  mentioning a traced parameter, or ``.item()`` / ``.tolist()`` on one
  — Python branching/iteration on traced values, the
  compile-time-explosion / ConcretizationError class (HybridGen's
  mixed host/accelerator pitfall: the bug hides until compile).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from ..core import Checker, Finding, Project
from ..symbols import (JIT_WRAPPERS, attr_chain, call_name, jit_roots_for,
                       symbols_for, unwrap_partial as _unwrap_partial,
                       wrapper_leaf as _wrapper_leaf)

CONCRETIZERS = {"bool", "int", "float", "len"}
CONCRETIZE_METHODS = {"item", "tolist"}


class _ImportMap(ast.NodeVisitor):
    """name -> source module for top-level imports, to tell stdlib
    ``random`` apart from ``jax.random`` and ``np`` from anything
    else."""

    def __init__(self, tree: ast.Module):
        self.modules: Dict[str, str] = {}
        self.visit(tree)

    def visit_Import(self, node: ast.Import) -> None:
        for alias in node.names:
            self.modules[alias.asname or alias.name.split(".")[0]] = \
                alias.name

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        for alias in node.names:
            base = node.module or ""
            self.modules[alias.asname or alias.name] = \
                f"{base}.{alias.name}".lstrip(".")


class JitPurityChecker(Checker):
    name = "jit_purity"
    rules = ("jit-host-impurity", "jit-traced-concretization")
    scope = ("distributed_llm_tpu",)

    def check(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        for mod in project.in_dirs(self.scope):
            syms = symbols_for(mod)
            if syms is None:
                continue
            findings.extend(self._check_module(mod, syms))
        return findings

    def _check_module(self, mod, syms) -> List[Finding]:
        imports = _ImportMap(mod.tree)
        # Root discovery (decorator forms, call-site forms including the
        # ``kernel = partial(_f, ...)`` then ``pl.pallas_call(kernel,
        # ...)`` idiom, scoped variable resolution) lives in
        # symbols.jit_roots_for — one cached pass shared with the
        # retrace checker's traced-reachability analysis.
        roots, lambda_roots = jit_roots_for(mod, syms)

        if not roots and not lambda_roots:
            return []

        reachable = syms.local_closure(roots)
        findings: List[Finding] = []
        for qual in sorted(reachable):
            info = syms.functions[qual]
            findings.extend(self._scan_body(
                mod, imports, info.node, is_root=(qual in roots)))
        for lam in lambda_roots:
            # A lambda passed to jit IS a root: its params are traced,
            # so the concretization rules apply to it too.
            findings.extend(self._scan_body(mod, imports, lam,
                                            is_root=True))
        return findings

    # -- body scanning -----------------------------------------------------

    def _scan_body(self, mod, imports: _ImportMap, func_node,
                   is_root: bool) -> List[Finding]:
        findings: List[Finding] = []
        params: Set[str] = set()
        if is_root and hasattr(func_node, "args"):
            a = func_node.args
            params = {p.arg for p in
                      list(a.posonlyargs) + list(a.args)
                      + list(a.kwonlyargs)}

        body = (func_node.body if isinstance(func_node.body, list)
                else [func_node.body])
        # Skip nested def/lambda subtrees: they are their own entries in
        # the reachable set when actually called from traced code.  The
        # exception is Pallas's ``@pl.when(...)`` idiom — the decorator
        # RUNS the nested body at trace time right where it is defined,
        # so its statements belong to the enclosing kernel's scan.
        stack = list(body)
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if any(isinstance(deco, ast.Call)
                       and (attr_chain(deco.func) or "").rsplit(
                           ".", 1)[-1] == "when"
                       for deco in node.decorator_list):
                    stack.extend(node.body)
                continue
            if isinstance(node, ast.Lambda):
                continue
            if isinstance(node, ast.Call):
                findings.extend(self._check_call(mod, imports, node,
                                                 params))
            stack.extend(ast.iter_child_nodes(node))
        return findings

    def _check_call(self, mod, imports: _ImportMap, node: ast.Call,
                    params: Set[str]) -> List[Finding]:
        out: List[Finding] = []
        chain = attr_chain(node.func) or ""
        root = chain.split(".", 1)[0]
        root_module = imports.modules.get(root, "")
        name = call_name(node)

        def flag(rule: str, msg: str) -> None:
            out.append(Finding(rule, mod.relpath, node.lineno, msg))

        # time.* inside a traced function.
        if root_module == "time" or chain.startswith("time."):
            flag("jit-host-impurity",
                 f"`{chain}(...)` inside a jit-traced function runs at "
                 f"TRACE time only — the compiled program never sees it")
        # print() (jax.debug.print is an Attribute call, unaffected).
        elif isinstance(node.func, ast.Name) and name == "print":
            flag("jit-host-impurity",
                 "`print(...)` inside a jit-traced function fires at "
                 "trace time only — use jax.debug.print for runtime "
                 "values")
        # Host RNG: stdlib random (but not `from jax import random`)
        # and numpy.random under any alias.
        elif (chain.startswith("random.")
              and imports.modules.get("random", "random") == "random"):
            flag("jit-host-impurity",
                 f"host RNG `{chain}(...)` is baked in at trace time — "
                 f"use jax.random with an explicit key")
        elif (".random." in f"{chain}." and root_module == "numpy"):
            flag("jit-host-impurity",
                 f"host RNG `{chain}(...)` is baked in at trace "
                 f"time — use jax.random with an explicit key")
        # File I/O.
        elif isinstance(node.func, ast.Name) and name == "open":
            flag("jit-host-impurity",
                 "`open(...)` inside a jit-traced function is host I/O "
                 "at trace time")
        # Device sync inside traced code.
        elif name == "block_until_ready":
            flag("jit-host-impurity",
                 "`.block_until_ready()` has no meaning inside a traced "
                 "function — sync on the host after the jitted call")

        # Concretization of traced parameters (root functions only:
        # only there do we know which names are traced).
        if params:
            mentions = {n.id for n in ast.walk(node)
                        if isinstance(n, ast.Name)} & params
            if mentions:
                if (isinstance(node.func, ast.Name)
                        and name in CONCRETIZERS):
                    flag("jit-traced-concretization",
                         f"`{name}(...)` over traced parameter(s) "
                         f"{sorted(mentions)} forces concretization at "
                         f"trace time (Python branching on traced "
                         f"values)")
                elif (name in CONCRETIZE_METHODS
                      and isinstance(node.func, ast.Attribute)):
                    flag("jit-traced-concretization",
                         f"`.{name}()` on traced parameter(s) "
                         f"{sorted(mentions)} pulls the value to host "
                         f"at trace time")
        return out
