"""Host↔device transfer discipline on annotated hot-path roots.

APEX-style host/accelerator overlap (PAPERS.md) dies silently when a
host sync creeps into the decode tick or the scheduler thread: one
stray ``.item()`` serializes the device queue against Python, and
nothing errors — throughput just sags.  This checker makes the
discipline structural: a function annotated ``# dllm-lint: hot-path``
(the decode tick / scheduler loop, the sampler collect, stream pumps)
and EVERYTHING it transitively calls — project-wide, through the
import-resolved call graph — must not sync or round-trip through the
host, except at sites that carry an inline suppression naming why that
specific sync is the sanctioned one.

Rules:

- ``transfer-host-sync``: ``jax.block_until_ready(...)``,
  ``jax.device_get(...)`` or ``.item()`` in the hot-path closure.  The
  batched tick keeps exactly ONE — the tick-boundary sync that makes
  the tokens observable — and that site's suppression justification
  says so; prefill's first-token syncs are likewise sanctioned by name
  (TTFT is the SLO).  Anything else is a new stall.
- ``transfer-host-round-trip``: ``np.asarray(...)`` / ``np.array(...)``
  / ``float()`` / ``int()`` / ``bool()`` directly over a ``jnp.`` /
  ``jax.`` expression in the closure — an implicit device→host pull
  (and often a fresh host copy) on every tick.  Expressions that
  contain an explicit sync are reported once, as the sync.
- ``transfer-sync-spill``: the hierarchical-KV specialization (ISSUE
  14) — a synchronous host copy (``jax.device_get`` /
  ``block_until_ready`` / ``np.asarray``-style pull) whose argument
  touches POOL DATA (a name matching ``pool``/``cache``/``kv*``/
  ``buffer``), in the hot-path closure.  The spill copier worker is the
  ONLY sanctioned device→host crossing for pool blocks: the scheduler
  demotes by issuing an async gather snapshot and hands the drain to
  the copier thread (engine/kv_spill.py), so a sync pool pull reachable
  from the scheduler ``_loop`` is a reintroduced stall by definition.
  Classified before the generic rules — the specific finding names the
  sanctioned alternative.
- ``transfer-undonated-buffer``: a ``jax.jit``/``pjit`` wrap whose
  function threads a KV/cache/pool buffer (a parameter named ``pool``
  / ``cache`` / ``kv*`` that the function also returns) with no
  ``donate_argnums`` — the update double-buffers the pool on every
  call.  This rule is project-wide (not hot-path-gated): the wrap site
  is where donation is declared, wherever it is.

Functions named ``*warmup*``/``*bench*`` are exempt from the closure
rules: warmup syncs to force compiles, benches sync to measure.

Adding a new hot-path root is one comment: put ``# dllm-lint:
hot-path`` on (or directly above) the ``def`` line — see DESIGN.md
"Static analysis".
"""

from __future__ import annotations

import ast
import re
from typing import List, Optional, Set

from ..core import Checker, Finding, Project
from ..symbols import (attr_chain, call_name, hot_path_roots,
                       project_symbols, symbols_for, wrapper_leaf)

EXEMPT_RE = re.compile(r"warmup|prewarm|bench|micro", re.IGNORECASE)

SYNC_NAMES = {"block_until_ready", "device_get"}
PULL_WRAPPERS = {"float", "int", "bool"}
BUFFER_PARAM_RE = re.compile(r"^(pool|cache|kv\w*|buffer)$")


def _touches_pool(expr: ast.expr) -> bool:
    """Whether the expression references a KV-pool-shaped value (a bare
    or attribute name matching the buffer pattern: ``pool`` /
    ``self.pool`` / ``kv*`` / ``cache`` / ``buffer``) — the
    transfer-sync-spill heuristic for 'this sync pulls pool data'."""
    for node in ast.walk(expr):
        if isinstance(node, ast.Name) and BUFFER_PARAM_RE.match(node.id):
            return True
        if isinstance(node, ast.Attribute) \
                and BUFFER_PARAM_RE.match(node.attr):
            return True
    return False


def _contains_sync(expr: ast.expr) -> bool:
    for node in ast.walk(expr):
        if isinstance(node, ast.Call) and call_name(node) in SYNC_NAMES:
            return True
    return False


def _contains_device_expr(expr: ast.expr) -> bool:
    """A call rooted at jnp./jax. anywhere inside — the device-value
    heuristic for round-trip detection."""
    for node in ast.walk(expr):
        if isinstance(node, ast.Call):
            chain = attr_chain(node.func) or ""
            root = chain.split(".", 1)[0]
            if root in ("jnp", "jax"):
                return True
    return False


class TransferChecker(Checker):
    name = "transfer"
    rules = ("transfer-host-sync", "transfer-host-round-trip",
             "transfer-sync-spill", "transfer-undonated-buffer")
    scope = ("distributed_llm_tpu/engine", "distributed_llm_tpu/serving",
             "distributed_llm_tpu/obs", "distributed_llm_tpu/ops",
             "distributed_llm_tpu/models", "distributed_llm_tpu/parallel")
    whole_project = True     # the hot-path closure crosses modules

    def check(self, project: Project) -> List[Finding]:
        ps = project_symbols(project)
        closure = ps.closure(hot_path_roots(ps))
        findings: List[Finding] = []

        # Closure rules fire wherever the callee LIVES (a hot tick
        # calling a syncing helper in utils/ is still a hot-path sync).
        for gid in sorted(closure):
            gf = ps.functions.get(gid)
            if gf is None or EXEMPT_RE.search(gf.qualname):
                continue
            mod = project.get(gf.relpath)
            if mod is None:
                continue
            findings.extend(self._scan_hot_body(mod, gf))

        # Donation rule: every wrap site in scope, hot or not.
        for mod in project.in_dirs(self.scope):
            syms = symbols_for(mod)
            if syms is None:
                continue
            findings.extend(self._scan_donation(mod, syms))
        return findings

    # -- closure rules -----------------------------------------------------

    def _scan_hot_body(self, mod, gf) -> List[Finding]:
        findings: List[Finding] = []
        node = gf.info.node
        body = (node.body if isinstance(getattr(node, "body", None), list)
                else [node.body] if hasattr(node, "body") else [])
        stack = list(body)
        while stack:
            n = stack.pop()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue          # own graph entries when actually called
            # Lambdas are NOT graph entries and cannot carry their own
            # hot-path annotation: scan their bodies as part of the
            # enclosing function, or a per-tick sync hides in one.
            stack.extend(ast.iter_child_nodes(n))
            if not isinstance(n, ast.Call):
                continue
            name = call_name(n)
            if name in SYNC_NAMES:
                if n.args and _touches_pool(n.args[0]):
                    # Pool data crossing the host boundary
                    # synchronously on the hot path: the spill copier
                    # worker is the only sanctioned crossing.
                    findings.append(Finding(
                        "transfer-sync-spill", mod.relpath, n.lineno,
                        f"`{name}(...)` pulls POOL data to host on the "
                        f"hot path (via `{gf.qualname}`) — the spill "
                        f"copier worker (engine/kv_spill.py) is the "
                        f"only sanctioned device→host crossing for "
                        f"pool blocks; demote by issuing the async "
                        f"gather snapshot and let the copier drain it"))
                    continue
                findings.append(Finding(
                    "transfer-host-sync", mod.relpath, n.lineno,
                    f"`{name}(...)` on the hot path (reachable from a "
                    f"`# dllm-lint: hot-path` root via `{gf.qualname}`) "
                    f"— a device sync serializes the tick against the "
                    f"host; if this is the sanctioned sync, say so in a "
                    f"suppression justification"))
                continue
            if name == "item" and isinstance(n.func, ast.Attribute) \
                    and not n.args and not n.keywords:
                findings.append(Finding(
                    "transfer-host-sync", mod.relpath, n.lineno,
                    f"`.item()` on the hot path (via `{gf.qualname}`) "
                    f"pulls a device value to host per call — batch the "
                    f"pull at the tick boundary instead"))
                continue
            is_np_pull = False
            chain = attr_chain(n.func) or ""
            if chain in ("np.asarray", "np.array", "numpy.asarray",
                         "numpy.array"):
                is_np_pull = True
                if n.args and _touches_pool(n.args[0]) \
                        and not _contains_sync(n.args[0]):
                    # An np pull DIRECTLY over pool data needs no jnp
                    # call to be a device→host copy — the pool is
                    # device-resident by construction.
                    findings.append(Finding(
                        "transfer-sync-spill", mod.relpath, n.lineno,
                        f"`{name}(...)` over POOL data on the hot path "
                        f"(via `{gf.qualname}`) — an implicit sync "
                        f"device→host copy of pool blocks; the spill "
                        f"copier worker (engine/kv_spill.py) is the "
                        f"only sanctioned crossing"))
                    continue
            elif isinstance(n.func, ast.Name) and name in PULL_WRAPPERS:
                is_np_pull = True
            if is_np_pull and n.args \
                    and _contains_device_expr(n.args[0]) \
                    and not _contains_sync(n.args[0]):
                findings.append(Finding(
                    "transfer-host-round-trip", mod.relpath, n.lineno,
                    f"`{name}(...)` over a device expression on the hot "
                    f"path (via `{gf.qualname}`) — an implicit "
                    f"device→host transfer every call; keep the value "
                    f"on device or move the pull to the tick boundary"))
        return findings

    # -- donation rule -----------------------------------------------------

    def _scan_donation(self, mod, syms) -> List[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call) \
                    or wrapper_leaf(node.func) not in ("jit", "pjit") \
                    or not node.args:
                continue
            if any(kw.arg == "donate_argnums" for kw in node.keywords):
                continue
            target = node.args[0]
            if not isinstance(target, ast.Name):
                continue
            fn = self._local_def(syms, target.id)
            if fn is None:
                continue
            params = [p.arg for p in fn.args.args]
            buffered = [p for p in params if BUFFER_PARAM_RE.match(p)]
            if not buffered:
                continue
            returned = self._returned_names(fn)
            threaded = sorted(set(buffered) & returned)
            if threaded:
                findings.append(Finding(
                    "transfer-undonated-buffer", mod.relpath, node.lineno,
                    f"jit wrap threads buffer parameter(s) "
                    f"{threaded} through without donate_argnums — the "
                    f"functional update double-buffers the pool on "
                    f"every call; donate it (device backends) or "
                    f"justify why not"))
        return findings

    @staticmethod
    def _local_def(syms, name: str) -> Optional[ast.FunctionDef]:
        for qual, info in syms.functions.items():
            if (qual == name or qual.endswith(f"<locals>.{name}")) \
                    and isinstance(info.node, ast.FunctionDef):
                return info.node
        return None

    @staticmethod
    def _returned_names(fn: ast.FunctionDef) -> Set[str]:
        out: Set[str] = set()
        stack = list(fn.body)
        while stack:
            n = stack.pop()
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
                continue
            if isinstance(n, ast.Return) and n.value is not None:
                for sub in ast.walk(n.value):
                    if isinstance(sub, ast.Name):
                        out.add(sub.id)
            stack.extend(ast.iter_child_nodes(n))
        return out
