"""Thread/lock lifecycle leak checker.

The serving stack spawns threads in six modules and is about to spawn
more (ROADMAP item 2: per-replica schedulers).  The failure modes are
quiet: a non-daemon worker no shutdown path joins keeps the process
alive after SIGTERM; a manual ``acquire()`` without an exception-safe
release deadlocks the NEXT request, not this one; a module-scope
recorder that owns a thread but has no stop hook outlives every drain.
This checker makes all three structural, over the project-wide call
graph.

Rules:

- ``thread-no-reclaim``: every ``threading.Thread(...)`` must be
  ``daemon=True`` or have a ``.join`` reachable from a reclaim path:
  either in the spawning function itself (the bench fan-out idiom —
  spawn, start, join in one scope; the join must name THIS thread's
  binding or an alias/loop variable no spawn is bound to, so joining
  worker A never silences a never-joined worker B in the same scope),
  or — for threads parked on ``self.X`` — a ``self.X.join(...)`` in a
  method of the same class that is itself a stop/close/drain/shutdown-
  family function or project-reachable from one.  A join in a random
  method that no shutdown path calls does not count: nothing runs it
  when the process is asked to die.
- ``thread-acquire-leak``: a manual ``lock.acquire()`` whose enclosing
  function has no ``lock.release()`` inside a ``finally`` block — on an
  exception between acquire and release the lock is held forever (the
  next request deadlocks, not this one).  The sanctioned shapes are
  ``with lock:`` and acquire-then-``try/finally``-release; anything
  else carries a suppression whose justification names the release
  owner (e.g. a stream object that releases on close).
- ``thread-ring-no-stop``: a module-scope singleton of a class that
  starts threads must define a stop/close/shutdown hook AND that hook
  must be called from somewhere a drain/stop path reaches — otherwise
  a drained process keeps sampling/recording forever.

Stop-family = a function whose name starts with stop/close/drain/
shutdown/terminate/__exit__ (``stop_server`` counts), plus everything
those functions transitively call, project-wide.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from ..core import Checker, Finding, Project
from ..symbols import (ProjectSymbols, attr_chain, call_name,
                       project_symbols, symbols_for)

STOP_NAME_RE = re.compile(
    r"^(stop|close|drain|shutdown|terminate|__exit__|__del__|atexit)")


def _stop_reachable(ps: ProjectSymbols) -> Set[str]:
    roots = {gid for gid, gf in ps.functions.items()
             if STOP_NAME_RE.match(gf.qualname.split(".")[-1])}
    return ps.closure(roots)


def _daemon_true(call: ast.Call) -> bool:
    for kw in call.keywords:
        if kw.arg == "daemon":
            return not (isinstance(kw.value, ast.Constant)
                        and kw.value.value in (False, None))
    return False


def _thread_like_join(call: ast.Call) -> bool:
    """Only thread-shaped joins count as reclamation: no args, or a
    timeout (keyword, or one positional that isn't an iterable literal/
    comprehension).  ``", ".join(names)`` — a string receiver or an
    iterable-literal argument — is the formatting idiom and must NOT
    silence thread-no-reclaim for an unrelated Thread in the same
    function."""
    recv = call.func.value
    if isinstance(recv, ast.Constant):          # ", ".join(...)
        return False
    if len(call.args) > 1:
        return False
    if call.args:
        arg = call.args[0]
        if isinstance(arg, (ast.List, ast.Tuple, ast.Set, ast.ListComp,
                            ast.SetComp, ast.GeneratorExp)):
            return False
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            return False
    return True


_POOL_WRAPPERS = {"list", "tuple", "sorted", "reversed", "enumerate"}


def _pool_iter_chain(it: ast.expr) -> Optional[str]:
    """The attr chain of a for-loop iterable that is a thread POOL
    container: ``self.X`` directly, ``list(self.X)``-style wrappers, or
    ``self.X.values()``."""
    if isinstance(it, ast.Call):
        if (isinstance(it.func, ast.Name)
                and it.func.id in _POOL_WRAPPERS and len(it.args) == 1):
            it = it.args[0]
        elif (isinstance(it.func, ast.Attribute)
              and it.func.attr == "values" and not it.args):
            it = it.func.value
    chain = attr_chain(it)
    return chain if chain and chain.startswith("self.") else None


def _loop_pool_vars(mod) -> Dict[int, str]:
    """id(join-call-node) -> pool attr chain, for every ``v.join(...)``
    whose receiver ``v`` is the loop variable of an enclosing ``for v in
    self.X`` (or a list()/values() wrapper of it) — the worker-pool
    reclamation idiom the per-replica drain fan-out uses."""
    out: Dict[int, str] = {}
    for loop in ast.walk(mod.tree):
        if not isinstance(loop, ast.For):
            continue
        target = loop.target
        var = None
        if isinstance(target, ast.Name):
            var = target.id
        elif (isinstance(target, ast.Tuple) and target.elts
              and isinstance(target.elts[-1], ast.Name)):
            var = target.elts[-1].id          # `for i, t in enumerate(...)`
        if var is None:
            continue
        pool = _pool_iter_chain(loop.iter)
        if pool is None:
            continue
        for n in ast.walk(loop):
            if (isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Attribute)
                    and n.func.attr == "join"
                    and isinstance(n.func.value, ast.Name)
                    and n.func.value.id == var):
                out[id(n)] = pool
    return out


def _parents_of(mod) -> Dict[int, ast.AST]:
    cached = getattr(mod, "_dllm_parents", None)
    if cached is None:
        cached = {}
        for parent in ast.walk(mod.tree):
            for child in ast.iter_child_nodes(parent):
                cached[id(child)] = parent
        mod._dllm_parents = cached
    return cached


class ThreadLifecycleChecker(Checker):
    name = "thread_lifecycle"
    rules = ("thread-no-reclaim", "thread-acquire-leak",
             "thread-ring-no-stop")
    # The whole default project: bench.py and scripts spawn threads too.
    scope = ("distributed_llm_tpu", "scripts", "bench.py",
             "tests/conftest.py")
    whole_project = True

    def check(self, project: Project) -> List[Finding]:
        ps = project_symbols(project)
        stop_set = _stop_reachable(ps)
        findings: List[Finding] = []
        for mod in project.in_dirs(self.scope):
            syms = symbols_for(mod)
            if syms is None:
                continue
            findings.extend(self._check_threads(mod, syms, ps, stop_set))
            findings.extend(self._check_acquires(mod, syms))
            findings.extend(self._check_rings(mod, syms, ps, stop_set))
        return findings

    # -- rule: thread-no-reclaim -------------------------------------------

    def _check_threads(self, mod, syms, ps: ProjectSymbols,
                       stop_set: Set[str]) -> List[Finding]:
        findings: List[Finding] = []
        rel = mod.relpath
        parents = _parents_of(mod)

        # function qual -> set of attr-chain receivers joined there.  A
        # join on a FOR-loop variable iterating a self attribute (`for t
        # in self._workers: t.join()` — the per-replica worker-pool
        # idiom, ISSUE 12) records the POOL's chain too, so a pool
        # drained by a stop-family loop counts as reclaimed.
        joins: Dict[str, Set[str]] = {}
        loop_pools = _loop_pool_vars(mod)
        for qual, edges in syms.calls.items():
            for _callee, bare, node in edges:
                if bare == "join" and isinstance(node.func, ast.Attribute) \
                        and _thread_like_join(node):
                    chain = attr_chain(node.func.value)
                    joins.setdefault(qual, set()).add(chain or "<dyn>")
                    pool = loop_pools.get(id(node))
                    if pool is not None:
                        joins[qual].add(pool)

        # Worker-pool appends (`t = Thread(...); self.X.append(t)` or
        # `self.X.append(Thread(...))`): the local binding resolves to
        # the POOL attr, so rule (b) — joined from a stop-family method
        # — applies to pooled per-replica workers exactly as to a
        # single `self.worker = Thread(...)`.
        pool_appends: Dict[str, Dict[str, str]] = {}
        for qual, edges in syms.calls.items():
            for _callee, bare, node in edges:
                if (bare != "append"
                        or not isinstance(node.func, ast.Attribute)
                        or len(node.args) != 1):
                    continue
                pool = attr_chain(node.func.value)
                if not (pool and pool.startswith("self.")):
                    continue
                arg = node.args[0]
                if isinstance(arg, ast.Name):
                    pool_appends.setdefault(qual, {})[arg.id] = pool

        # Assignment targets of every Thread(...) per function: a join
        # must name ITS thread (or an alias/loop variable no thread is
        # bound to) to reclaim it — "any join in the function" let a
        # second, never-joined worker in the same scope pass silently.
        thread_targets: Dict[str, Set[str]] = {}
        for qual, edges in syms.calls.items():
            for _callee, bare, node in edges:
                if bare != "Thread":
                    continue
                parent = parents.get(id(node))
                if (isinstance(parent, ast.Assign)
                        and len(parent.targets) == 1):
                    chain = attr_chain(parent.targets[0])
                    if chain:
                        thread_targets.setdefault(qual, set()).add(chain)

        for qual, edges in syms.calls.items():
            for _callee, bare, node in edges:
                if bare != "Thread":
                    continue
                if _daemon_true(node):
                    continue
                info = syms.functions.get(qual)
                parent = parents.get(id(node))
                target = None
                if (isinstance(parent, ast.Assign)
                        and len(parent.targets) == 1):
                    target = attr_chain(parent.targets[0])
                # (a) joined in the spawning function itself — on the
                # thread's own name, or on a receiver that is not any
                # spawned thread's target (the `for t in threads:
                # t.join()` loop-variable idiom).  Untargeted spawns
                # (list appends, inline starts) accept any
                # thread-shaped join: the binding is untraceable.
                fn_joins = joins.get(qual, set())
                if target is not None:
                    alias_joins = fn_joins - thread_targets.get(qual,
                                                                set())
                    if target in fn_joins or alias_joins:
                        continue
                elif fn_joins:
                    continue
                # (b) parked on self.X — directly, or pooled via
                # `self.X.append(t)` / `self.X.append(Thread(...))` —
                # and joined from a stop-family method of the same
                # class (a `for t in self.X: t.join()` loop there
                # reclaims the whole pool).
                attr = target if target and target.startswith("self.") \
                    else None
                if attr is None:
                    if target is not None:
                        attr = pool_appends.get(qual, {}).get(target)
                    else:
                        parent_call = parents.get(id(node))
                        if (isinstance(parent_call, ast.Call)
                                and isinstance(parent_call.func,
                                               ast.Attribute)
                                and parent_call.func.attr == "append"):
                            chain = attr_chain(parent_call.func.value)
                            if chain and chain.startswith("self."):
                                attr = chain
                reclaimed = False
                if attr is not None and info is not None \
                        and info.class_name:
                    for jqual, chains in joins.items():
                        jinfo = syms.functions.get(jqual)
                        if jinfo is None \
                                or jinfo.class_name != info.class_name:
                            continue
                        if attr not in chains:
                            continue
                        jgid = f"{rel}:{jqual}"
                        leaf = jqual.split(".")[-1]
                        if STOP_NAME_RE.match(leaf) or jgid in stop_set:
                            reclaimed = True
                            break
                if reclaimed:
                    continue
                findings.append(Finding(
                    "thread-no-reclaim", rel, node.lineno,
                    "non-daemon Thread is neither joined in its "
                    "spawning function nor joined from any "
                    "stop/close/drain path — it outlives shutdown and "
                    "blocks process exit; set daemon=True or wire the "
                    "join into the stop path"))
        return findings

    # -- rule: thread-acquire-leak -----------------------------------------

    def _check_acquires(self, mod, syms) -> List[Finding]:
        findings: List[Finding] = []
        rel = mod.relpath
        for qual, info in syms.functions.items():
            if isinstance(info.node, ast.Lambda):
                continue
            acquires: List[Tuple[ast.Call, str]] = []
            releases_in_finally: Set[str] = set()
            releases_anywhere: Set[str] = set()

            def scan(nodes, in_finally: bool) -> None:
                stack = [(n, in_finally) for n in nodes]
                while stack:
                    n, fin = stack.pop()
                    if isinstance(n, (ast.FunctionDef,
                                      ast.AsyncFunctionDef, ast.Lambda)):
                        continue
                    if isinstance(n, ast.Try):
                        scan(n.body, fin)
                        for h in n.handlers:
                            scan(h.body, fin)
                        scan(n.orelse, fin)
                        scan(n.finalbody, True)
                        continue
                    if isinstance(n, ast.Call) \
                            and isinstance(n.func, ast.Attribute) \
                            and n.func.attr in ("acquire", "release"):
                        lock = syms.resolve_lock(n.func.value, qual,
                                                 info.class_name)
                        if lock is not None:
                            if n.func.attr == "acquire":
                                acquires.append((n, lock))
                            else:
                                releases_anywhere.add(lock)
                                if fin:
                                    releases_in_finally.add(lock)
                    stack.extend((c, fin)
                                 for c in ast.iter_child_nodes(n))

            scan(info.node.body, False)
            for node, lock in acquires:
                if lock in releases_in_finally:
                    continue
                where = ("released only outside any `finally`"
                         if lock in releases_anywhere
                         else "never released in this function")
                findings.append(Finding(
                    "thread-acquire-leak", rel, node.lineno,
                    f"manual `{lock}.acquire()` is {where} — an "
                    f"exception between acquire and release holds the "
                    f"lock forever (the NEXT caller deadlocks); use "
                    f"`with` or try/finally, or justify who owns the "
                    f"release"))
        return findings

    # -- rule: thread-ring-no-stop -----------------------------------------

    def _check_rings(self, mod, syms, ps: ProjectSymbols,
                     stop_set: Set[str]) -> List[Finding]:
        findings: List[Finding] = []
        rel = mod.relpath

        # Local classes that start threads, and their stop-family
        # method names.
        owners: Dict[str, Tuple[ast.ClassDef, Set[str]]] = {}
        for node in mod.tree.body:
            if not isinstance(node, ast.ClassDef):
                continue
            starts_thread = any(
                isinstance(n, ast.Call) and call_name(n) == "Thread"
                for n in ast.walk(node))
            if not starts_thread:
                continue
            hooks = {m.name for m in node.body
                     if isinstance(m, (ast.FunctionDef,
                                       ast.AsyncFunctionDef))
                     and STOP_NAME_RE.match(m.name)}
            owners[node.name] = (node, hooks)
        if not owners:
            return findings

        # Module-scope instantiations of those classes.
        for node in mod.tree.body:
            value = None
            targets: List[ast.expr] = []
            if isinstance(node, ast.Assign):
                value, targets = node.value, node.targets
            elif isinstance(node, ast.AnnAssign):
                value, targets = node.value, [node.target]
            if not isinstance(value, ast.Call):
                continue
            cls_name = call_name(value)
            if cls_name not in owners:
                continue
            inst_names = {c.rsplit(".", 1)[-1] for c in
                          (attr_chain(t) for t in targets) if c}
            _cls, hooks = owners[cls_name]
            if not hooks:
                findings.append(Finding(
                    "thread-ring-no-stop", rel, node.lineno,
                    f"module-scope `{cls_name}` instance owns a thread "
                    f"but the class defines no stop/close/shutdown "
                    f"hook — a drained process cannot reclaim it"))
                continue
            # The hook must be CALLED, on THIS instance, from somewhere
            # a stop path reaches: hook-name match inside the stop
            # closure with the receiver's leaf naming the singleton
            # (receivers are untypeable statically — but a bare
            # name-only match let an unrelated `fh.close()` anywhere in
            # a drain path mark a never-stopped recorder reclaimed).
            called = False
            for gid in stop_set:
                for _c, bare, n in ps.calls.get(gid, ()):
                    if bare not in hooks \
                            or not isinstance(n.func, ast.Attribute):
                        continue
                    recv = attr_chain(n.func.value)
                    if recv and recv.rsplit(".", 1)[-1] in inst_names:
                        called = True
                        break
                if called:
                    break
            if not called:
                findings.append(Finding(
                    "thread-ring-no-stop", rel, node.lineno,
                    f"module-scope `{cls_name}` instance owns a thread; "
                    f"its {sorted(hooks)} hook is never called from any "
                    f"drain/stop path — a drained process keeps the "
                    f"thread alive"))
        return findings
