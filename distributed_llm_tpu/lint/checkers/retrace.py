"""Retrace-hazard lint: compile-churn at jit/pjit/shard_map/pallas_call
roots, enforcing PR 6's headline invariant STATICALLY — exactly one
compiled decode program per engine life — instead of only observing it
at runtime through ``_note_compile``.

A retrace hazard is any shape that mints a NEW compiled program on a
path that runs more than once per engine life.  The runtime cost is
invisible until a bench round pays for it (a mid-serve compile stalls
every active slot for seconds-to-minutes on chip), which is why the
rule family exists: the hazard must fail CI, not a later bench.

Rules:

- ``retrace-wrap-in-loop``: ``jax.jit(...)`` / ``pjit`` / ``shard_map``
  / ``pl.pallas_call`` invoked inside a ``for``/``while`` body — a
  fresh wrapper (and a fresh trace) per iteration.  Calling an
  ALREADY-wrapped function in a loop is the normal warm path and stays
  silent, and so does a loop inside TRACED code (it unrolls at trace
  time — one outer compile, the per-layer ops idiom).
- ``retrace-per-call-wrap``: a wrap immediately invoked
  (``jax.jit(f)(x)``, ``pl.pallas_call(partial(k, ...), ...)(...)``)
  inside a function reachable from an annotated hot-path root
  (``# dllm-lint: hot-path`` — decode tick, scheduler loop, request
  handlers) but NOT reachable from any jit root: every request/tick
  re-traces.  Inside traced code the same shape is fine — it traces
  once per outer compile — so traced-reachable functions (project-wide
  closure, ``lax.scan`` bodies included) are exempt.
- ``retrace-dynamic-shape``: a device upload whose SHAPE varies per
  call — ``jnp.asarray(x[:, :w])`` with a non-constant slice bound —
  or a shape-derived Python scalar (``len(x)``, ``x.shape[i]``) passed
  to a known-jitted callable that declares no ``static_argnums`` /
  ``static_argnames``.  Each distinct width is a distinct compiled
  program; bucket it, pad it, or make it static and accept the
  per-value retrace knowingly.  Deliberately-bounded families (the
  dense rung ladder, prefill buckets) carry inline suppressions whose
  justification states the bound.
- ``retrace-shape-cache-key``: a mapping key built from an array's
  ``.shape`` (directly, in a tuple, or through an f-string) — keying a
  cache by shape is declaring "one entry per shape", i.e. institutional
  churn.  Slicing TO a shape bound (``x[: q.shape[1]]``) and indexing
  by a shape-derived SCALAR (``tables[q.shape[0]]`` — a shape indexed
  down to an int is ordinary array code, not a mapping key) stay
  silent: mappings and arrays are statically indistinguishable, so
  only the unambiguously-mapping-shaped keys fire.

Scope: the serving stack (engine/ops/serving/models/parallel/obs) —
bench and training mint programs per measurement case by design.
Functions named ``*warmup*`` are exempt: minting every program the
engine can touch is warmup's JOB.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from ..core import Checker, Finding, Project
from ..symbols import (attr_chain, call_name, hot_path_roots,
                       project_symbols, symbols_for, unwrap_partial,
                       wrapper_leaf)

EXEMPT_RE = re.compile(r"warmup|prewarm", re.IGNORECASE)

# Wrap-site static-argument keywords that sanction per-value retraces.
STATIC_KWARGS = {"static_argnums", "static_argnames"}


def _is_exempt(qual: Optional[str]) -> bool:
    return bool(qual and EXEMPT_RE.search(qual))


def _nonconstant_slice(sub: ast.Subscript) -> bool:
    """True when the subscript contains a Slice with a non-constant
    bound (``x[:, :wb]``): the result's shape varies with the bound."""
    def dynamic(bound: Optional[ast.expr]) -> bool:
        return bound is not None and not isinstance(bound, ast.Constant)

    for node in ast.walk(sub.slice):
        if isinstance(node, ast.Slice):
            if dynamic(node.lower) or dynamic(node.upper):
                return True
    return False


def _shape_in_key(sub: ast.Subscript) -> bool:
    """True when the subscript KEY (not a slice bound) uses an
    ``.shape`` attribute AS A VALUE — the whole tuple, directly or
    inside a tuple/f-string key (``cache[x.shape]``,
    ``cache[(x.shape, dtype)]``, ``cache[f"prog-{x.shape}"]``).  A
    shape INDEXED down to a scalar (``tables[q.shape[0]]``) is ordinary
    array indexing, not a mapping key, and stays silent — the checker
    cannot tell mappings from arrays statically, so only the
    unambiguously-mapping-shaped keys fire."""
    indexed: Set[int] = set()
    hits: List[ast.Attribute] = []
    stack = [sub.slice]
    while stack:
        node = stack.pop()
        if isinstance(node, ast.Slice):
            continue                  # slicing to a shape bound is fine
        if isinstance(node, ast.Subscript) \
                and isinstance(node.value, ast.Attribute) \
                and node.value.attr == "shape":
            indexed.add(id(node.value))
        if isinstance(node, ast.Attribute) and node.attr == "shape":
            hits.append(node)
        stack.extend(ast.iter_child_nodes(node))
    return any(id(h) not in indexed for h in hits)


def _shape_derived(expr: ast.expr) -> Optional[str]:
    """'len(...)' / 'x.shape[i]' when the expression is (or contains at
    the top arithmetic level) a shape-derived Python scalar."""
    stack = [expr]
    while stack:
        node = stack.pop()
        if (isinstance(node, ast.Call) and isinstance(node.func, ast.Name)
                and node.func.id == "len"):
            return "len(...)"
        if (isinstance(node, ast.Subscript)
                and isinstance(node.value, ast.Attribute)
                and node.value.attr == "shape"):
            return ".shape[...]"
        if isinstance(node, (ast.BinOp, ast.UnaryOp)):
            stack.extend(ast.iter_child_nodes(node))
    return None


class _JitWrapIndex:
    """``f = jax.jit(g, ...)`` assignments, scoped to the function (or
    module body) that binds them: a call site resolves against its OWN
    scope first, then module scope — never against a sibling function's
    local binding (a module-wide flat map conflated same-named locals
    across functions, both ways: a host-only local shadowed by another
    function's jit wrap false-positived, and a sanctioned wrap masked an
    unsanctioned same-named one)."""

    def __init__(self, scopes):
        # scope qual (None = module body) -> {name -> wrap Call}
        self.by_scope: Dict[Optional[str], Dict[str, ast.Call]] = {}
        for qual, body in scopes:
            table: Dict[str, ast.Call] = {}
            stack = list(body)
            while stack:
                n = stack.pop()
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                    continue          # their own scope entries
                stack.extend(ast.iter_child_nodes(n))
                if (isinstance(n, ast.Assign) and len(n.targets) == 1
                        and isinstance(n.targets[0], ast.Name)
                        and isinstance(n.value, ast.Call)
                        and wrapper_leaf(n.value.func) in ("jit", "pjit")):
                    table[n.targets[0].id] = n.value
            if table:
                self.by_scope[qual] = table

    def unsanctioned(self, qual: Optional[str], name: str) -> bool:
        for scope in (qual, None):
            wrap = self.by_scope.get(scope, {}).get(name)
            if wrap is not None:
                return not any(kw.arg in STATIC_KWARGS
                               for kw in wrap.keywords)
        return False


class RetraceChecker(Checker):
    name = "retrace"
    rules = ("retrace-wrap-in-loop", "retrace-per-call-wrap",
             "retrace-dynamic-shape", "retrace-shape-cache-key")
    scope = ("distributed_llm_tpu/engine", "distributed_llm_tpu/ops",
             "distributed_llm_tpu/serving", "distributed_llm_tpu/models",
             "distributed_llm_tpu/parallel", "distributed_llm_tpu/obs")
    whole_project = True     # traced/hot reachability crosses modules

    def check(self, project: Project) -> List[Finding]:
        ps = project_symbols(project)
        traced = ps.traced_closure()
        hot = ps.closure(hot_path_roots(ps))
        findings: List[Finding] = []
        for mod in project.in_dirs(self.scope):
            syms = symbols_for(mod)
            if syms is None:
                continue
            findings.extend(self._check_module(mod, syms, ps, traced, hot))
        return findings

    def _check_module(self, mod, syms, ps, traced: Set[str],
                      hot: Set[str]) -> List[Finding]:
        findings: List[Finding] = []
        rel = mod.relpath

        # Walk each function body (module scope included) with loop
        # depth, attributing nodes to their enclosing function's gid.
        scopes: List[Tuple[Optional[str], list]] = [(None, mod.tree.body)]
        scopes += [(qual, info.node.body)
                   for qual, info in syms.functions.items()
                   if isinstance(info.node.body, list)]
        jit_index = _JitWrapIndex(scopes)

        for qual, body in scopes:
            gid = f"{rel}:{qual}" if qual else None
            if _is_exempt(qual):
                continue
            stack: List[Tuple[ast.AST, int]] = [(n, 0) for n in body]
            while stack:
                node, loops = stack.pop()
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    continue           # separate scope entry
                depth = loops + (1 if isinstance(node, (ast.For,
                                                        ast.While))
                                 else 0)
                stack.extend((c, depth)
                             for c in ast.iter_child_nodes(node))
                if isinstance(node, ast.Subscript) and _shape_in_key(node):
                    findings.append(Finding(
                        "retrace-shape-cache-key", rel, node.lineno,
                        "mapping key built from an array's `.shape` — a "
                        "shape-keyed cache institutionalizes one "
                        "compiled program per shape; bucket or pad the "
                        "shape instead"))
                if not isinstance(node, ast.Call):
                    continue
                findings.extend(self._check_call(
                    mod, node, qual, gid, loops, traced, hot, jit_index))
        return findings

    def _check_call(self, mod, node: ast.Call, qual: Optional[str],
                    gid: Optional[str], loops: int, traced: Set[str],
                    hot: Set[str],
                    jit_index: _JitWrapIndex) -> List[Finding]:
        rel = mod.relpath
        out: List[Finding] = []
        leaf = wrapper_leaf(node.func)
        if leaf is not None:
            # Inside TRACED code a wrap-in-loop unrolls at trace time —
            # one outer compile, the per-layer ops-module idiom — same
            # exemption the per-call-wrap rule grants below.
            if loops > 0 and (gid is None or gid not in traced):
                out.append(Finding(
                    "retrace-wrap-in-loop", rel, node.lineno,
                    f"`{leaf}(...)` inside a loop mints a fresh wrapper "
                    f"(and a fresh trace) every iteration — hoist the "
                    f"wrap out of the loop and call the wrapped "
                    f"function instead"))
            return out

        # Immediate invoke of a wrap: Call whose func is itself a
        # wrapper Call — jax.jit(f)(x) / pl.pallas_call(k, ...)(...).
        inner = node.func
        if isinstance(inner, ast.Call):
            ileaf = wrapper_leaf(inner.func)
            if ileaf is not None and gid is not None \
                    and gid in hot and gid not in traced:
                target = unwrap_partial(inner.args[0]) if inner.args \
                    else None
                what = ("a freshly-built partial/lambda kernel"
                        if isinstance(target, (ast.Lambda, ast.Call))
                        else "its function argument")
                out.append(Finding(
                    "retrace-per-call-wrap", rel, node.lineno,
                    f"`{ileaf}(...)` wrapped and invoked in one "
                    f"expression on a hot path: every call re-traces "
                    f"{what} — build the wrapper once (module scope or "
                    f"a keyed cache) and reuse it"))
            return out

        chain = attr_chain(node.func) or ""
        name = call_name(node)
        # Dynamic-shape device upload: jnp.asarray(x[:, :w]) & friends.
        if chain.startswith("jnp.") and name in ("asarray", "array") \
                and node.args:
            arg = node.args[0]
            if isinstance(arg, ast.Subscript) and _nonconstant_slice(arg):
                out.append(Finding(
                    "retrace-dynamic-shape", rel, node.lineno,
                    "device upload of a variably-sliced array: every "
                    "distinct width is a distinct operand shape — one "
                    "compiled program per width downstream; bucket or "
                    "pad the slice, or justify the bound in a "
                    "suppression"))
        # Shape-derived Python scalar into a jitted callable that
        # declared no static_argnums/static_argnames.
        if isinstance(node.func, ast.Name) \
                and jit_index.unsanctioned(qual, node.func.id):
            for arg in node.args:
                derived = _shape_derived(arg)
                if derived is not None:
                    out.append(Finding(
                        "retrace-dynamic-shape", rel, node.lineno,
                        f"shape-derived scalar {derived} flows into "
                        f"jitted `{node.func.id}(...)` with no "
                        f"static_argnums/static_argnames — the value "
                        f"becomes a traced 0-d array (silent intent "
                        f"mismatch) or, marked static later, a "
                        f"per-value retrace; declare it static "
                        f"explicitly or bucket it"))
                    break
        return out
