"""Checker registry: add a checker by importing and listing it here.

Each checker is a ``core.Checker`` subclass with a unique ``name``,
a ``rules`` tuple (the ids suppression comments reference), and a
``scope`` of path prefixes.  See DESIGN.md "Static analysis" for the
how-to-add walkthrough.
"""

from __future__ import annotations

from typing import List

from ..core import Checker
from .config_drift import ConfigDriftChecker
from .error_shape import ErrorShapeChecker
from .jit_purity import JitPurityChecker
from .locks import LockChecker
from .metrics_discipline import MetricsDisciplineChecker
from .obs_discipline import (ObsDisciplineChecker,
                             ProfilerDisciplineChecker)
from .ownership import OwnershipChecker
from .retrace import RetraceChecker
from .span_discipline import SpanDisciplineChecker
from .thread_lifecycle import ThreadLifecycleChecker
from .transfer import TransferChecker


def all_checkers() -> List[Checker]:
    return [
        LockChecker(),
        JitPurityChecker(),
        ErrorShapeChecker(),
        ConfigDriftChecker(),
        SpanDisciplineChecker(),
        ObsDisciplineChecker(),
        ProfilerDisciplineChecker(),
        RetraceChecker(),
        TransferChecker(),
        ThreadLifecycleChecker(),
        OwnershipChecker(),
        MetricsDisciplineChecker(),
    ]
