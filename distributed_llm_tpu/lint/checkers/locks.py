"""Lock-discipline / race detector (PR 2's bug class, made structural).

Three rules over ``serving/`` + ``engine/`` + ``obs/``:

- ``lock-blocking-call``: a blocking operation is reachable while a
  ``threading`` lock is held.  Blocking = the repo's known long calls by
  NAME (engine ``generate``/``generate_stream``/``warmup``,
  ``start_server``/``stop_server``, checkpoint loads, ``time.sleep``,
  socket/HTTP reads) plus the unbounded wait forms ``.join()`` /
  ``.wait()`` / ``.get()`` / ``.acquire()`` called with no
  timeout/arguments — propagated transitively through the WHOLE-PROJECT
  call graph (symbols.ProjectSymbols), so ``with self._lock:
  self.start_server()`` is flagged even when the compile lives two
  calls down IN ANOTHER FILE (import-resolved: ``from m import fn``,
  ``module.fn``, ``self.method``; bare-name coincidences never edge).
  This is exactly the PR 2 shape: a health probe blocking on the
  lifecycle lock through a multi-minute warmup compile reads as a dead
  tier — and the upcoming multi-replica refactor splits exactly these
  paths across modules, where the old module-local graph was blind.
- ``lock-order-inversion``: lock B acquired while A is held in one
  place and A while B is held in another (static deadlock pair).
  Acquisition-under-lock is collected transitively through resolvable
  module-local calls.
- ``lock-mixed-guard``: an instance attribute that is (a) written from
  code reachable by a worker thread (``threading.Thread(target=...)``
  entries and their module-local closure) and (b) guarded by a lock at
  SOME access sites, but read or written bare at others — the
  inconsistent-discipline race (the checker stays silent on attributes
  never guarded anywhere: those are presumed single-writer by design,
  e.g. a scheduler thread's private state with GIL-safe snapshot reads).
  Container mutation through the attribute (``self._refs[b] = ...``,
  ``del self._refs[b]``) counts as a write: the refcounted allocator's
  table (ISSUE 10) races exactly this way — a bare incref against a
  locked reaper — while the attribute binding itself never changes.
  The rule fires only when the WORKER holds a lock at some write site
  (a discipline exists but missed a site); a worker whose writes are
  ALL bare is presumed single-writer even if another site locks, since
  that shape is statically indistinguishable from the scheduler's
  owned-state pattern (bare `_slots` everywhere + a post-join read
  under the unrelated lifecycle lock) — the deliberate-limit fixture
  in tests/test_lint.py pins this tradeoff.

Heuristics are deliberately name-based where cross-module types are
unknowable statically; intended violations carry inline suppressions
with justifications (see DESIGN.md "Static analysis").
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ..core import Checker, Finding, Project
from ..symbols import (ModuleSymbols, ProjectSymbols, attr_chain,
                       call_name, module_dotted_name, project_symbols,
                       symbols_for)

# Long-running by name, wherever they are called (receiver-insensitive:
# cross-module receivers cannot be typed statically).
BLOCKING_NAMES = {
    "sleep",                    # time.sleep
    "generate", "generate_stream",   # engine device calls (minutes on a
    "warmup",                        # wedged chip)
    "start_server", "stop_server",   # lifecycle: build + compile + warm
    "drain",                         # graceful drain: waits out in-flight
                                     # work, then calls stop_server — under
                                     # the lifecycle lock it deadlocks
    "load_params_for_tier",          # checkpoint restore
    "urlopen", "getresponse", "recv", "accept",   # socket/HTTP
}

# Zero-argument forms of these are unbounded waits.
UNBOUNDED_WAIT_NAMES = {"join", "wait", "get", "acquire"}


def _is_blocking_call(node: ast.Call) -> Optional[str]:
    name = call_name(node)
    if name in BLOCKING_NAMES:
        return f"`{name}(...)`"
    if (name in UNBOUNDED_WAIT_NAMES and not node.args
            and not node.keywords):
        return f"unbounded `{name}()` (no timeout)"
    return None


class _FuncScan(ast.NodeVisitor):
    """One function body: blocking calls, lock events with held context,
    and plain self-attribute accesses.  Nested defs are skipped (they
    are separate functions that run later, on their own thread/stack)."""

    def __init__(self, syms: ModuleSymbols, func_qual: str,
                 class_name: Optional[str]):
        self.syms = syms
        self.func_qual = func_qual
        self.class_name = class_name
        self.acquires: Set[str] = set()          # locks this func takes
        # (held_lock, acquired_lock, node) ordered pairs seen directly
        self.order_pairs: List[Tuple[str, str, ast.AST]] = []
        # blocking candidates under a held lock:
        #   (node, reason, held_lock, resolved_callee | None)
        self.held_calls: List[Tuple[ast.Call, Optional[str], str,
                                    Optional[str]]] = []
        # plain self.X accesses: (attr, node, is_write, held_locks)
        self.attr_accesses: List[Tuple[str, ast.AST, bool,
                                       Tuple[str, ...]]] = []
        self._held: List[str] = []
        self._rest_held: Set[str] = set()   # .acquire() → rest of function
        self._skip_root = None

    def run(self, node) -> "_FuncScan":
        self._skip_root = node
        for stmt in node.body:
            self.visit(stmt)
        return self

    # -- scope fences ------------------------------------------------------

    def visit_FunctionDef(self, node):            # nested def: don't descend
        pass

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_Lambda = visit_FunctionDef

    def _held_now(self) -> Tuple[str, ...]:
        return tuple(self._held) + tuple(self._rest_held)

    # -- with-blocks -------------------------------------------------------

    def visit_With(self, node: ast.With) -> None:
        pushed = 0
        for item in node.items:
            expr = item.context_expr
            # ``with lock:`` and ``with lock.acquire_timeout(...)``-style
            # wrappers: resolve the lock receiver.
            target = expr
            if isinstance(expr, ast.Call):
                self.visit(expr)
                continue
            lock = self.syms.resolve_lock(target, self.func_qual,
                                          self.class_name)
            if lock is not None:
                self._note_acquire(lock, node)
                self._held.append(lock)
                pushed += 1
        for stmt in node.body:
            self.visit(stmt)
        for _ in range(pushed):
            self._held.pop()

    visit_AsyncWith = visit_With

    def _note_acquire(self, lock: str, node: ast.AST) -> None:
        self.acquires.add(lock)
        for held in self._held_now():
            if held != lock:
                self.order_pairs.append((held, lock, node))

    # -- calls -------------------------------------------------------------

    def visit_Call(self, node: ast.Call) -> None:
        name = call_name(node)
        if name == "release" and isinstance(node.func, ast.Attribute):
            # Manual release ends the rest-of-function held region a
            # manual acquire opened (source order — conservative both
            # ways, and exact for the acquire/try/finally idiom).
            lock = self.syms.resolve_lock(node.func.value, self.func_qual,
                                          self.class_name)
            if lock is not None:
                self._rest_held.discard(lock)
            self.generic_visit(node)
            return
        if (name in ("acquire",) and isinstance(node.func, ast.Attribute)):
            lock = self.syms.resolve_lock(node.func.value, self.func_qual,
                                          self.class_name)
            if lock is not None:
                self._note_acquire(lock, node)
                bounded = bool(node.args or node.keywords)
                held = self._held_now()
                if held and not bounded and lock not in held:
                    self.held_calls.append(
                        (node, f"unbounded `{lock}.acquire()`",
                         held[0], None))
                # Held for the remainder of the function: a manual
                # acquire has no structural exit.
                self._rest_held.add(lock)
                self.generic_visit(node)
                return
        reason = _is_blocking_call(node)
        held = self._held_now()
        if held:
            resolved = None
            for callee, cname, cnode in self.syms.calls.get(
                    self.func_qual, ()):
                if cnode is node:
                    resolved = callee
                    break
            # EVERY call under a held lock is recorded: module-locally
            # unresolvable callees may still resolve cross-module
            # through the project graph at check time.
            self.held_calls.append((node, reason, held[0], resolved))
        self.generic_visit(node)

    # -- attribute accesses ------------------------------------------------

    def visit_Attribute(self, node: ast.Attribute) -> None:
        if (isinstance(node.value, ast.Name) and node.value.id == "self"):
            is_write = isinstance(node.ctx, (ast.Store, ast.Del))
            self.attr_accesses.append(
                (node.attr, node, is_write, self._held_now()))
        self.generic_visit(node)

    def visit_Subscript(self, node: ast.Subscript) -> None:
        # Mutating a container THROUGH a self attribute
        # (``self._refs[b] = ...``, ``del self._refs[b]``,
        # ``self._refs[b] += 1``) is a WRITE to the shared state the
        # attribute names, even though the attribute itself is only
        # loaded — the refcount-table shape (ISSUE 10): a bare incref
        # racing a locked reaper tears the count.  Rebinding-only
        # tracking missed this class entirely.
        if (isinstance(node.ctx, (ast.Store, ast.Del))
                and isinstance(node.value, ast.Attribute)
                and isinstance(node.value.value, ast.Name)
                and node.value.value.id == "self"):
            self.attr_accesses.append(
                (node.value.attr, node, True, self._held_now()))
        self.generic_visit(node)


def _plain_accesses(scan: _FuncScan, tree_parents: Dict[int, ast.AST]
                    ) -> List[Tuple[str, ast.AST, bool, Tuple[str, ...]]]:
    """Filter out method-call receivers (``self.x.m()``): calling a
    method on a shared object is that object's own thread-safety story,
    not a rebinding race on the attribute."""
    out = []
    for attr, node, is_write, held in scan.attr_accesses:
        parent = tree_parents.get(id(node))
        if (isinstance(parent, ast.Attribute)
                and isinstance(tree_parents.get(id(parent)), ast.Call)
                and tree_parents[id(parent)].func is parent):
            continue
        out.append((attr, node, is_write, held))
    return out


def _first_direct_blocking(func_node) -> Optional[Tuple[ast.Call, str]]:
    """The first (by line) blocking call in a function body, nested defs
    skipped — the seed of the project-wide blocking fixpoint."""
    best: Optional[Tuple[ast.Call, str]] = None
    body = (func_node.body if isinstance(func_node.body, list)
            else [func_node.body])
    stack = list(body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        if isinstance(node, ast.Call):
            reason = _is_blocking_call(node)
            if reason is not None and (best is None
                                       or node.lineno < best[0].lineno):
                best = (node, reason)
        stack.extend(ast.iter_child_nodes(node))
    return best


def _display(gid: str, from_rel: str) -> str:
    """How a callee reads in a finding message: bare qualname inside the
    same module, ``dotted.module.qualname`` across modules."""
    rel, qual = gid.split(":", 1)
    if rel == from_rel:
        return qual
    return f"{module_dotted_name(rel)}.{qual}"


def _global_blocking(ps: ProjectSymbols) -> Dict[str, str]:
    """gid -> human-readable witness for every function that blocks,
    directly or transitively through the project-wide call graph."""
    blocking: Dict[str, str] = {}
    for gid, gf in ps.functions.items():
        if isinstance(gf.info.node, ast.Lambda):
            continue
        hit = _first_direct_blocking(gf.info.node)
        if hit is not None:
            blocking[gid] = f"{hit[1]} at line {hit[0].lineno}"
    changed = True
    while changed:
        changed = False
        for gid, edges in ps.calls.items():
            if gid in blocking:
                continue
            for callee, _bare, _node in edges:
                if callee is not None and callee in blocking:
                    rel = gid.split(":", 1)[0]
                    blocking[gid] = (f"calls `{_display(callee, rel)}` "
                                     f"({blocking[callee]})")
                    changed = True
                    break
    return blocking


class LockChecker(Checker):
    name = "locks"
    rules = ("lock-blocking-call", "lock-order-inversion",
             "lock-mixed-guard")
    scope = ("distributed_llm_tpu/serving", "distributed_llm_tpu/engine",
             "distributed_llm_tpu/obs")
    whole_project = True      # an edit elsewhere can make a callee block

    def check(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        ps = project_symbols(project)
        blocking = _global_blocking(ps)
        # (relpath, lockA, lockB) -> first site, for inversion detection
        pair_sites: Dict[Tuple[str, str, str], Tuple[str, int]] = {}

        for mod in project.in_dirs(self.scope):
            syms = symbols_for(mod)
            if syms is None:
                continue
            findings.extend(self._check_module(mod, syms, ps, blocking,
                                               pair_sites))

        # Lock-order inversions across all collected pairs (locks are
        # module-scoped, so pairs only collide within one module).
        reported = set()
        for (rel, a, b), (path, line) in sorted(pair_sites.items()):
            if (rel, b, a) in pair_sites and (rel, b, a) not in reported:
                other = pair_sites[(rel, b, a)]
                reported.add((rel, a, b))
                findings.append(Finding(
                    "lock-order-inversion", path, line,
                    f"lock order inversion: {b} acquired while holding "
                    f"{a} here, but {a} acquired while holding {b} at "
                    f"{other[0]}:{other[1]} — static deadlock pair"))
        return findings

    # -- per-module --------------------------------------------------------

    def _check_module(self, mod, syms: ModuleSymbols, ps: ProjectSymbols,
                      blocking: Dict[str, str],
                      pair_sites) -> List[Finding]:
        findings: List[Finding] = []
        rel = mod.relpath
        scans: Dict[str, _FuncScan] = {}
        for qual, info in syms.functions.items():
            if isinstance(info.node, ast.Lambda):
                continue
            scans[qual] = _FuncScan(syms, qual,
                                    info.class_name).run(info.node)

        # Transitive lock acquisition (fixpoint over resolved
        # module-local call edges — lock identity is module-scoped, so
        # cross-module edges cannot contribute inversion pairs).
        acquires: Dict[str, Set[str]] = {q: set(s.acquires)
                                         for q, s in scans.items()}
        changed = True
        while changed:
            changed = False
            for qual in scans:
                for callee, _n, _c in syms.calls.get(qual, ()):
                    if callee is None or callee not in scans:
                        continue
                    extra = acquires[callee] - acquires[qual]
                    if extra:
                        acquires[qual] |= extra
                        changed = True

        # Rule: blocking under a held lock — direct, via a local callee,
        # or via a callee in ANOTHER module (the project graph's
        # import-resolved edge; blocking-ness came from the global
        # fixpoint).  Plus transitive order pairs through local calls.
        for qual, scan in scans.items():
            for held, acquired, node in scan.order_pairs:
                key = (rel, held, acquired)
                pair_sites.setdefault(key, (rel, node.lineno))
            for node, reason, held_lock, resolved in scan.held_calls:
                gid = (f"{rel}:{resolved}" if resolved is not None
                       else ps.callee_of(rel, node))
                if reason is not None:
                    findings.append(Finding(
                        "lock-blocking-call", rel, node.lineno,
                        f"blocking {reason} while holding {held_lock}"))
                elif gid is not None and gid in blocking:
                    findings.append(Finding(
                        "lock-blocking-call", rel, node.lineno,
                        f"call to `{_display(gid, rel)}` while holding "
                        f"{held_lock} — transitively blocking: "
                        f"{blocking[gid]}"))
                if resolved is not None:
                    held = {held_lock}
                    for lock in acquires.get(resolved, ()):
                        for h in held:
                            if h != lock:
                                key = (rel, h, lock)
                                pair_sites.setdefault(
                                    key, (rel, node.lineno))

        findings.extend(self._mixed_guard(mod, syms, scans))
        return findings

    # -- rule: mixed guard discipline --------------------------------------

    def _mixed_guard(self, mod, syms: ModuleSymbols,
                     scans: Dict[str, _FuncScan]) -> List[Finding]:
        findings: List[Finding] = []
        parents: Dict[int, ast.AST] = {}
        for parent in ast.walk(mod.tree):
            for child in ast.iter_child_nodes(parent):
                parents[id(child)] = parent

        # Worker entries: threading.Thread(target=X), resolved in the
        # SPAWNING call's own scope — a Name target binds to a local def
        # visible from the enclosing function chain, a `self.m` target
        # to the spawning class's method.  Matching by bare name across
        # the module would mark unrelated classes' same-named methods
        # worker-reachable and manufacture mixed-guard findings there.
        worker_roots: Set[str] = set()
        for caller, edges in syms.calls.items():
            info = syms.functions.get(caller)
            for _callee, name, node in edges:
                if name != "Thread":
                    continue
                for kw in node.keywords:
                    if kw.arg != "target":
                        continue
                    target = kw.value
                    if isinstance(target, ast.Name) and info is not None:
                        scope: Optional[str] = caller
                        while scope:
                            cand = f"{scope}.<locals>.{target.id}"
                            if cand in syms.functions:
                                worker_roots.add(cand)
                                break
                            parent = syms.functions.get(scope)
                            scope = parent.parent if parent else None
                    elif (isinstance(target, ast.Attribute)
                          and isinstance(target.value, ast.Name)
                          and target.value.id == "self"
                          and info is not None and info.class_name):
                        cand = f"{info.class_name}.{target.attr}"
                        if cand in syms.functions:
                            worker_roots.add(cand)
        if not worker_roots:
            return findings
        worker_funcs = syms.local_closure(worker_roots)

        # Per class: guarded attrs, worker-side writes, bare accesses.
        classes = {i.class_name for i in syms.functions.values()
                   if i.class_name}
        for cls in sorted(classes):
            guarded: Dict[str, Set[str]] = {}
            worker_guarded_writes: Set[str] = set()
            bare: List[Tuple[str, ast.AST, str]] = []
            for qual, scan in scans.items():
                info = syms.functions[qual]
                if info.class_name != cls:
                    continue
                is_init = qual.split(".")[-1] == "__init__"
                for attr, node, is_write, held in _plain_accesses(
                        scan, parents):
                    if held:
                        guarded.setdefault(attr, set()).update(held)
                    # The discipline signal: the WORKER code itself
                    # locks this attr at some write site.  A worker
                    # that never locks it anywhere is the presumed
                    # single-writer pattern (the batching scheduler's
                    # slot list with GIL-safe snapshot reads, where an
                    # unrelated lifecycle lock happens to be held at a
                    # post-join site) — mixed-guard is about a
                    # discipline that EXISTS but missed a site.
                    if (is_write and held and qual in worker_funcs
                            and not is_init):
                        worker_guarded_writes.add(attr)
                    if not held and not is_init:
                        bare.append((attr, node, qual))
            for attr, node, qual in bare:
                if attr in worker_guarded_writes:
                    locks = ", ".join(sorted(guarded[attr]))
                    findings.append(Finding(
                        "lock-mixed-guard", mod.relpath, node.lineno,
                        f"`self.{attr}` is written from worker-thread "
                        f"code under {locks}, but accessed here without "
                        f"any lock"))
        return findings
