"""Observability-feed discipline: the SLO monitor has ONE feed site.

``SLOMonitor.record_request`` (obs/slo.py) counts a finished request
into the sliding goodput windows.  Its correctness contract is
exactly-once-per-request, which the serving stack gets structurally by
feeding it ONLY from ``Router._finish_request`` — the single exit that
already runs exactly once on every path of both pipelines (sync,
stream, exception).  A second feed site anywhere in serving/ or
engine/ would double-count requests, halve every goodput reading, and
fire phantom overload incidents — and nothing at runtime would look
obviously wrong.

Rule ``slo-feed-outside-finish``: any call ``<...>.slo.record_request(...)``
(or bare ``slo.record_request(...)``) in the instrumented layers must
appear inside a function named ``_finish_request``.  Matching is
receiver-chain-based (the chain must contain a ``slo`` segment), so an
unrelated object's ``record_request`` method does not false-positive.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from ..core import Checker, Finding, Project

FEED_ATTR = "record_request"
ALLOWED_FUNC = "_finish_request"


def _chain(node: ast.expr) -> List[str]:
    """Attribute-chain segments of a receiver, innermost-last
    (``self.obs.slo`` -> ["slo", "obs", "self"])."""
    out: List[str] = []
    while isinstance(node, ast.Attribute):
        out.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        out.append(node.id)
    return out


def _is_slo_feed(call: ast.Call) -> bool:
    fn = call.func
    if not (isinstance(fn, ast.Attribute) and fn.attr == FEED_ATTR):
        return False
    return "slo" in _chain(fn.value)


class ObsDisciplineChecker(Checker):
    name = "obs_discipline"
    rules = ("slo-feed-outside-finish",)
    scope = ("distributed_llm_tpu/serving", "distributed_llm_tpu/engine")

    def check(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        for mod in project.in_dirs(self.scope):
            if mod.tree is None:
                continue
            self._visit(mod.tree, None, mod.relpath, findings)
        return findings

    def _visit(self, node: ast.AST, func: Optional[str], path: str,
               findings: List[Finding]) -> None:
        for child in ast.iter_child_nodes(node):
            child_func = func
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                child_func = child.name
            elif isinstance(child, ast.Lambda):
                # A lambda inherits its enclosing function's identity: a
                # feed hidden in a callback defined INSIDE
                # _finish_request is still the sanctioned site.
                child_func = func
            if (isinstance(child, ast.Call) and _is_slo_feed(child)
                    and func != ALLOWED_FUNC):
                findings.append(Finding(
                    "slo-feed-outside-finish", path, child.lineno,
                    f"SLO feed `slo.{FEED_ATTR}(...)` outside "
                    f"`{ALLOWED_FUNC}` — the goodput windows count "
                    f"requests exactly once, on the router's single "
                    f"completion exit; a second feed site double-counts"))
            self._visit(child, child_func, path, findings)
