"""Observability-feed discipline: the SLO monitor has ONE feed site,
and profiler stamps never run at trace time.

Rule ``slo-feed-outside-finish``: ``SLOMonitor.record_request``
(obs/slo.py) counts a finished request into the sliding goodput
windows.  Its correctness contract is exactly-once-per-request, which
the serving stack gets structurally by feeding it ONLY from
``Router._finish_request`` — the single exit that already runs exactly
once on every path of both pipelines (sync, stream, exception).  A
second feed site anywhere in serving/ or engine/ would double-count
requests, halve every goodput reading, and fire phantom overload
incidents — and nothing at runtime would look obviously wrong.
Matching is receiver-chain-based (the chain must contain a ``slo``
segment), so an unrelated object's ``record_request`` method does not
false-positive.

Rule ``profiler-hook-in-traced-code`` (ISSUE 11): the tick-phase
profiler (obs/profiler.py) stamps ``perf_counter`` around device-call
seams ON THE HOST.  A profiler call inside a jit/pjit/shard_map/
pallas-traced function runs at TRACE time: it bakes one stamp-time
constant into the compiled program, measures nothing on any subsequent
execution, and silently skews every phase table built from it.  Any
call on a receiver chain containing a ``profiler``/``prof`` segment is
flagged when the enclosing function is in the PROJECT-WIDE traced
closure (lint/symbols.py ``traced_closure`` — the same set the retrace
checker reasons over), anywhere in the repo, not just the serving
scope.  Deliberate limits, same conservatism as the call graph: a
profiler object reached through a differently-named local
(``p = self.profiler; p.phase(...)``) is not matched — the repo idiom
is always the attribute chain — and only functions the closure can
prove traced are checked, so the rule adds no false findings.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional

from ..core import Checker, Finding, Project
from ..symbols import project_symbols

FEED_ATTR = "record_request"
ALLOWED_FUNC = "_finish_request"

# Receiver-chain segments that mark a tick-profiler stamp.  "prof" is
# included for the conventional local name in helper signatures
# (obs/profiler.py's own docs use it); anything else is a deliberate
# limit documented above.
PROFILER_SEGMENTS = {"profiler", "prof"}


def _chain(node: ast.expr) -> List[str]:
    """Attribute-chain segments of a receiver, innermost-last
    (``self.obs.slo`` -> ["slo", "obs", "self"])."""
    out: List[str] = []
    while isinstance(node, ast.Attribute):
        out.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        out.append(node.id)
    return out


def _is_slo_feed(call: ast.Call) -> bool:
    fn = call.func
    if not (isinstance(fn, ast.Attribute) and fn.attr == FEED_ATTR):
        return False
    return "slo" in _chain(fn.value)


def _is_profiler_call(call: ast.Call) -> bool:
    fn = call.func
    if not isinstance(fn, ast.Attribute):
        return False
    return bool(PROFILER_SEGMENTS & set(_chain(fn.value)))


def _own_nodes(node: ast.AST) -> Iterable[ast.AST]:
    """Walk a function body WITHOUT descending into nested def/async
    def (each is its own call-graph function and, when traced, its own
    closure member — descending would double-report).  Lambdas ARE
    walked: they are not separate graph nodes, so this is their only
    chance to be seen."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        child = stack.pop()
        yield child
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        stack.extend(ast.iter_child_nodes(child))


class ObsDisciplineChecker(Checker):
    """The per-file rule (``slo-feed-outside-finish``): its verdict
    depends only on the file a finding lands in, so ``--changed`` may
    filter it to changed files.  The traced-closure profiler rule lives
    in its own whole-project checker below — folding it in here would
    widen THIS rule's reporting too and break the changed-files-only
    contract."""

    name = "obs_discipline"
    rules = ("slo-feed-outside-finish",)
    scope = ("distributed_llm_tpu/serving", "distributed_llm_tpu/engine")

    def check(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        for mod in project.in_dirs(self.scope):
            if mod.tree is None:
                continue
            self._visit(mod.tree, None, mod.relpath, findings)
        return findings

    # -- slo-feed-outside-finish -------------------------------------------

    def _visit(self, node: ast.AST, func: Optional[str], path: str,
               findings: List[Finding]) -> None:
        for child in ast.iter_child_nodes(node):
            child_func = func
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                child_func = child.name
            elif isinstance(child, ast.Lambda):
                # A lambda inherits its enclosing function's identity: a
                # feed hidden in a callback defined INSIDE
                # _finish_request is still the sanctioned site.
                child_func = func
            if (isinstance(child, ast.Call) and _is_slo_feed(child)
                    and func != ALLOWED_FUNC):
                findings.append(Finding(
                    "slo-feed-outside-finish", path, child.lineno,
                    f"SLO feed `slo.{FEED_ATTR}(...)` outside "
                    f"`{ALLOWED_FUNC}` — the goodput windows count "
                    f"requests exactly once, on the router's single "
                    f"completion exit; a second feed site double-counts"))
            self._visit(child, child_func, path, findings)


class ProfilerDisciplineChecker(Checker):
    """``profiler-hook-in-traced-code``, as its own checker: the traced
    closure crosses modules (a jit root in engine/ can reach a helper
    in ops/), so an edit in one file can create a finding in another —
    ``whole_project`` widens it under ``--changed``.  Kept separate
    from ObsDisciplineChecker so that widening does not leak onto the
    per-file slo-feed rule."""

    name = "profiler_discipline"
    rules = ("profiler-hook-in-traced-code",)
    scope = ("distributed_llm_tpu",)
    whole_project = True

    def check(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        ps = project_symbols(project)
        traced = ps.traced_closure()
        for gid in sorted(traced):
            gf = ps.functions.get(gid)
            if gf is None:
                continue
            for node in _own_nodes(gf.info.node):
                if isinstance(node, ast.Call) and _is_profiler_call(node):
                    findings.append(Finding(
                        "profiler-hook-in-traced-code", gf.relpath,
                        node.lineno,
                        f"profiler stamp inside traced code "
                        f"(`{gf.qualname}` is jit/pallas-reachable): "
                        f"perf_counter runs once at TRACE time and "
                        f"bakes a constant into the compiled program — "
                        f"stamp around the device call on the host "
                        f"side instead"))
        return findings
