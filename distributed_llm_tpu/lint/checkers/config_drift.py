"""Config/env drift: code and config_registry must agree both ways.

- Every ``DLLM_*`` env var READ in the project (``os.environ.get``,
  ``os.getenv``, ``os.environ[...]``, ``"X" in os.environ``) must be
  registered in ``config_registry.ENV_VARS`` — and every registered var
  must still have at least one reader (a registry entry with no reader
  is a stale knob nobody can discover is dead).
- Every ``TierConfig``/``ClusterConfig`` dataclass field in config.py
  must appear in ``config_registry.CONFIG_FIELDS`` with a non-empty
  one-liner, and vice versa.
- Every ``ENV_VARS`` entry must carry a doc and consumer (the registry
  IS the documentation; an empty row defeats it).

The registry module is stdlib-only, so importing it here keeps the lint
CLI jax-free.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Set, Tuple

from ..core import Checker, Finding, Project
from ...config_registry import CONFIG_FIELDS, ENV_VARS

ENV_NAME_RE = re.compile(r"^DLLM_[A-Z0-9_]+$")
REGISTRY_PATH = "distributed_llm_tpu/config_registry.py"
CONFIG_PATH = "distributed_llm_tpu/config.py"
CONFIG_CLASSES = ("TierConfig", "ClusterConfig")


def _env_chain(node: ast.expr) -> bool:
    """True for expressions ending in ``environ`` (os.environ,
    _os.environ, bare environ)."""
    if isinstance(node, ast.Attribute):
        return node.attr == "environ"
    return isinstance(node, ast.Name) and node.id == "environ"


def _const_env_name(node: ast.expr):
    if (isinstance(node, ast.Constant) and isinstance(node.value, str)
            and ENV_NAME_RE.match(node.value)):
        return node.value
    return None


class ConfigDriftChecker(Checker):
    name = "config_drift"
    rules = ("config-env-unregistered", "config-env-stale",
             "config-field-undocumented", "config-field-stale",
             "config-registry-incomplete")
    # The whole default project: bench.py, scripts, conftest included.
    scope = ("distributed_llm_tpu", "scripts", "bench.py",
             "tests/conftest.py")
    # An edit anywhere can strand a registry entry (delete the last
    # reader) — the finding then lands in the UNCHANGED registry file,
    # so --changed must not drop it.
    whole_project = True

    def check(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        reads: Dict[str, Tuple[str, int]] = {}     # var -> first site

        for mod in project.in_dirs(self.scope):
            if mod.tree is None:
                continue
            for node in ast.walk(mod.tree):
                for var, line in self._env_uses(node):
                    reads.setdefault(var, (mod.relpath, line))
                    if var not in ENV_VARS:
                        findings.append(Finding(
                            "config-env-unregistered", mod.relpath, line,
                            f"env var {var} read here but not in "
                            f"config_registry.ENV_VARS — register it "
                            f"with a docstring (or fix the typo)"))

        # No-reader detection needs the WHOLE project loaded: a narrowed
        # target run cannot prove absence, only presence.
        if getattr(project, "complete", True):
            for var in sorted(set(ENV_VARS) - set(reads)):
                findings.append(Finding(
                    "config-env-stale", REGISTRY_PATH, 1,
                    f"ENV_VARS entry {var} has no reader anywhere in "
                    f"the project — dead knob; remove it or wire it up"))

        for var, entry in sorted(ENV_VARS.items()):
            if not entry.doc.strip() or not entry.consumer.strip():
                findings.append(Finding(
                    "config-registry-incomplete", REGISTRY_PATH, 1,
                    f"ENV_VARS entry {var} is missing its doc/consumer "
                    f"— the registry IS the documentation"))

        findings.extend(self._check_fields(project))
        return findings

    # -- env read patterns -------------------------------------------------

    def _env_uses(self, node: ast.AST) -> List[Tuple[str, int]]:
        out: List[Tuple[str, int]] = []
        # os.environ.get("X", ...) / os.getenv("X", ...)
        if isinstance(node, ast.Call):
            fn = node.func
            if (isinstance(fn, ast.Attribute) and fn.attr == "get"
                    and _env_chain(fn.value) and node.args):
                name = _const_env_name(node.args[0])
                if name:
                    out.append((name, node.lineno))
            elif (isinstance(fn, ast.Attribute) and fn.attr == "getenv"
                    and node.args):
                name = _const_env_name(node.args[0])
                if name:
                    out.append((name, node.lineno))
            elif (isinstance(fn, ast.Name)
                    and fn.id in ("env_str", "env_int", "env_float",
                                  "env_flag", "getenv")
                    and node.args):
                name = _const_env_name(node.args[0])
                if name:
                    out.append((name, node.lineno))
        # os.environ["X"] (read or write — both are usage)
        elif isinstance(node, ast.Subscript) and _env_chain(node.value):
            sl = node.slice
            if isinstance(sl, ast.Index):           # py<3.9 compat
                sl = sl.value
            name = _const_env_name(sl)
            if name:
                out.append((name, node.lineno))
        # "X" in os.environ
        elif isinstance(node, ast.Compare):
            if (len(node.ops) == 1 and isinstance(node.ops[0],
                                                  (ast.In, ast.NotIn))
                    and _env_chain(node.comparators[0])):
                name = _const_env_name(node.left)
                if name:
                    out.append((name, node.lineno))
        return out

    # -- dataclass field coverage ------------------------------------------

    def _check_fields(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        mod = project.get(CONFIG_PATH)
        if mod is None or mod.tree is None:
            return findings
        declared: Set[str] = set()
        for node in ast.walk(mod.tree):
            if (not isinstance(node, ast.ClassDef)
                    or node.name not in CONFIG_CLASSES):
                continue
            for stmt in node.body:
                if (isinstance(stmt, ast.AnnAssign)
                        and isinstance(stmt.target, ast.Name)):
                    field = f"{node.name}.{stmt.target.id}"
                    declared.add(field)
                    if not CONFIG_FIELDS.get(field, "").strip():
                        findings.append(Finding(
                            "config-field-undocumented", CONFIG_PATH,
                            stmt.lineno,
                            f"{field} is not documented in "
                            f"config_registry.CONFIG_FIELDS"))
        for field in sorted(set(CONFIG_FIELDS) - declared):
            findings.append(Finding(
                "config-field-stale", REGISTRY_PATH, 1,
                f"CONFIG_FIELDS entry {field} does not exist on "
                f"{' / '.join(CONFIG_CLASSES)} any more — remove it"))
        return findings
