"""Ownership & lifecycle dataflow checker (v3).

PRs 10-17 paired every resource manually: refcounted block
``alloc``/``share``/``free``, prefix-entry ``take``/``untake``/
``share``/``unshare``/``pin``/``unpin``, spill-tier promotion
``claim``/``release``, admission permits, and warm-pool replica
handles.  Each pair is enforced only by tests on the happy path; the
failure shape that actually bites is an exception between acquire and
release — a silent pool leak, or a cleanup that runs twice and
corrupts the survivor.  This checker makes those paths structural: it
propagates an abstract ownership state for every locally-acquired
resource along a per-function CFG with exception edges (lint/cfg.py)
and reports exits where a resource is still owned, releases of
already-released resources, and uses after an ownership handoff.

Rules
-----

- ``own-leak-on-path``: an acquired resource (blocks, replica handle,
  admission permit) reaches a function exit — normal or exceptional —
  still owned, or its binding is overwritten/discarded while owned.
- ``own-pin-no-unpin``: the same, for pin-kind protocols (prefix-cache
  entry pins, spill promotion claims) whose release is a pin drop.
- ``own-double-release``: a release executes on a state that can only
  be already-released (``RELEASED`` possible, ``OWNED`` not) — the
  second ``free`` corrupts whoever reused the blocks.
- ``own-use-after-transfer``: a release or hand-off executes after
  ownership already moved (e.g. ``free`` after ``prefix_cache.put``
  parked the blocks, ``stop_server`` on a replica already published to
  the member list).

Abstract state — a MAY-set per variable over {OWNED, NONE, RELEASED,
TRANSFERRED, ESCAPED}:

- acquire sites bind ``{OWNED}`` (``{OWNED, NONE}`` for acquires that
  can return None; ``x = alloc(n) if flag else None`` works too), and
  ``x is None`` / ``x is not None`` / truthiness tests narrow the set
  per branch (an edge whose refinement empties the set is infeasible
  and not taken — that is the path sensitivity).
- anything the analysis cannot prove non-retaining ESCAPES: passing
  the variable to an unresolved call, storing it in a container or
  attribute, aliasing it, returning it, or referencing it from a
  nested ``def``/``lambda``.  Escaped resources are never reported —
  the v2 no-false-edge invariant: missing a leak is acceptable,
  inventing one is not.  A short whitelist of provably non-retaining
  callees (``len``, ``np.asarray``, …) keeps bookkeeping reads from
  killing tracking.
- interprocedural summaries ride the ProjectSymbols call graph: a
  resolved callee that releases/escapes its parameter summarizes as
  such (fixpoint over the graph); unresolved callees conservatively
  escape their arguments.
- exception edges apply a statement's effects *optimistically*
  (releases count, acquires do not bind) — again the FP-safe
  direction: a cleanup call that itself raises mid-release is treated
  as having released.

Deliberate limits (documented in DESIGN.md "Static analysis"):
may-set joins mean a double-release hidden behind ``OWNED`` on a
sibling path is not reported; resources carried in tuples past
unpacking, generator/async bodies, and ownership that begins at a
membership *removal* (``_pick_victim``) are untracked; admission
permits are checked on normal exits only (``exc_edges=False`` row) —
their release-on-error discipline is the router's ``finally`` and is
exercised dynamically.

Adding a protocol is one table row in ``PROTOCOLS`` below.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from ..cfg import UnsupportedFlow, build_cfg
from ..core import Checker, Finding, Project
from ..symbols import (FuncInfo, ProjectSymbols, attr_chain,
                       project_symbols, symbols_for)

OWN_LEAK = "own-leak-on-path"
OWN_DOUBLE = "own-double-release"
OWN_UAT = "own-use-after-transfer"
OWN_PIN = "own-pin-no-unpin"

# Abstract states (elements of a per-variable may-set).
OWNED, NONE, RELEASED, TRANSFERRED, ESCAPED = "O", "N", "R", "T", "E"

# Callee leaf names that provably do not retain their arguments —
# reads that copy values out (or mutate the container in place) and
# drop the reference.  Everything else escapes.
NON_RETAINING = frozenset({
    "len", "isinstance", "bool", "int", "float", "str", "repr", "id",
    "type", "hash", "abs", "round", "min", "max", "sum", "any", "all",
    "print", "format", "count", "index", "remove", "discard", "sorted",
    "asarray", "array", "get_event_loop", "debug", "info", "warning",
    "error", "exception",
})


@dataclass(frozen=True)
class Sig:
    """One acquire/release/transfer call signature.

    ``recv`` — receiver *leaf* names that identify the protocol object
    (``self.kv_spill.claim`` → leaf ``kv_spill``); empty = any.
    ``bind`` (acquires) — "result" binds the call result, "arg0" marks
    the first argument as acquired (``allocator.share(blocks)``).
    ``arg`` (releases/transfers) — "arg0": the resource is the first
    argument (a plain name, ``x[0]``, or ``[x]``); "any": any tracked
    argument; "recv_root": the resource is the *root* of the receiver
    chain (``victim.mgr.stop_server()``); "all": applies to every
    live resource of the protocol (``admission.release()`` names no
    handle).
    """

    method: str
    recv: Tuple[str, ...] = ()
    bind: str = "result"
    optional: bool = False
    arg: str = "arg0"


@dataclass(frozen=True)
class Protocol:
    name: str
    kind: str = "resource"            # "resource" | "pin" | "permit"
    acquires: Tuple[Sig, ...] = ()
    releases: Tuple[Sig, ...] = ()
    transfers: Tuple[Sig, ...] = ()
    exc_edges: bool = True
    none_is_acquired: bool = False    # try_admit: None result = held
    release_hint: str = ""


PROTOCOLS: Tuple[Protocol, ...] = (
    Protocol(
        name="kv-blocks",
        acquires=(Sig("alloc", recv=("allocator",), optional=True),
                  Sig("_alloc_evicting", recv=("self",), optional=True),
                  Sig("share", recv=("allocator",), bind="arg0")),
        releases=(Sig("free", recv=("allocator",)),),
        transfers=(Sig("put", recv=("prefix_cache",), arg="any"),),
        release_hint="self.allocator.free(blocks)",
    ),
    Protocol(
        name="prefix-pin", kind="pin",
        acquires=(Sig("take", recv=("prefix_cache",), optional=True),
                  Sig("share", recv=("prefix_cache",), optional=True)),
        releases=(Sig("untake", recv=("prefix_cache",)),
                  Sig("unshare", recv=("prefix_cache",)),
                  Sig("unpin", recv=("prefix_cache",)),
                  Sig("put", recv=("prefix_cache",))),
        release_hint="prefix_cache.untake/unshare/unpin(entry)",
    ),
    Protocol(
        name="spill-pin", kind="pin",
        acquires=(Sig("claim", recv=("kv_spill", "spill"),
                      optional=True),),
        releases=(Sig("release", recv=("kv_spill", "spill")),),
        release_hint="kv_spill.release(entry, promoted=...)",
    ),
    Protocol(
        name="admission-permit", kind="permit",
        acquires=(Sig("try_admit", recv=("admission",), optional=True),),
        releases=(Sig("release", recv=("admission",), arg="all"),),
        exc_edges=False, none_is_acquired=True,
        release_hint="self.admission.release(dt)",
    ),
    Protocol(
        name="replica-handle",
        acquires=(Sig("pop", recv=("_standby",)),
                  Sig("_build_replica", recv=("self",))),
        releases=(Sig("append", recv=("_standby",)),
                  Sig("stop_server", arg="recv_root"),
                  Sig("drain", arg="recv_root")),
        transfers=(Sig("append", recv=("_members",)),),
        release_hint="self._standby.append(r) or r.mgr.stop_server()",
    ),
    Protocol(
        # ISSUE 20 crash rescue: a capture_requests() result is the
        # victim replica's in-flight work — live _Request objects with
        # callers blocked on done.wait().  It must reach exactly one
        # home: adopted by a sibling/restarted engine (transfer) or
        # failed with the engine-stopped shape (release).  A path that
        # drops the list strands callers forever; adopting twice would
        # decode the same stream on two engines.
        name="rescue-capture",
        acquires=(Sig("capture_requests"),),
        releases=(Sig("fail_captured", arg="arg0"),),
        transfers=(Sig("adopt_requests", arg="arg0"),),
        release_hint="engine.adopt_requests(captured) or "
                     "fail_captured(captured, tier_name)",
    ),
)

_LEAK_RULE = {"resource": OWN_LEAK, "permit": OWN_LEAK, "pin": OWN_PIN}


# -- call-shape matching ---------------------------------------------------

def _call_parts(call: ast.Call) -> Optional[List[str]]:
    chain = attr_chain(call.func)
    if chain is None:
        if isinstance(call.func, ast.Name):
            return [call.func.id]
        return None
    return chain.split(".")


def _sig_matches_call(sig: Sig, parts: List[str]) -> bool:
    if parts[-1] != sig.method:
        return False
    if not sig.recv:
        return True
    return len(parts) >= 2 and parts[-2] in sig.recv


def match_acquire(call: ast.Call) -> Optional[Tuple[Protocol, Sig]]:
    parts = _call_parts(call)
    if parts is None:
        return None
    for proto in PROTOCOLS:
        for sig in proto.acquires:
            if _sig_matches_call(sig, parts):
                return proto, sig
    return None


def _match_in(call: ast.Call, table: str) -> List[Tuple[Protocol, Sig]]:
    parts = _call_parts(call)
    if parts is None:
        return []
    out = []
    for proto in PROTOCOLS:
        for sig in getattr(proto, table):
            if _sig_matches_call(sig, parts):
                out.append((proto, sig))
    return out


def _release_arg_names(call: ast.Call, sig: Sig) -> Set[str]:
    """Variable names a release/transfer sig designates in this call:
    args[0] as ``x``, ``x[0]`` (single index, not a slice) or ``[x]``
    for arg0 mode; every directly-named argument for "any" mode."""
    def name_of(expr: ast.expr) -> Optional[str]:
        if isinstance(expr, ast.Name):
            return expr.id
        if (isinstance(expr, ast.Subscript)
                and isinstance(expr.value, ast.Name)
                and not isinstance(expr.slice, ast.Slice)):
            return expr.value.id
        if (isinstance(expr, (ast.List, ast.Tuple)) and len(expr.elts) == 1
                and isinstance(expr.elts[0], ast.Name)):
            return expr.elts[0].id
        return None

    if sig.arg == "arg0":
        if call.args:
            n = name_of(call.args[0])
            return {n} if n else set()
        return set()
    if sig.arg == "any":
        out = set()
        for a in list(call.args) + [k.value for k in call.keywords]:
            n = name_of(a)
            if n:
                out.add(n)
        return out
    return set()


def _recv_root_release(call: ast.Call) -> List[Tuple[Protocol, Sig, str]]:
    """``victim.mgr.stop_server()`` → (replica-handle, sig, "victim")."""
    parts = _call_parts(call)
    if parts is None or len(parts) < 2:
        return []
    out = []
    for proto in PROTOCOLS:
        for sig in proto.releases:
            if sig.arg == "recv_root" and parts[-1] == sig.method:
                out.append((proto, sig, parts[0]))
    return out


# -- occurrence classification ---------------------------------------------

def _parent_map(root: ast.AST) -> Dict[int, ast.AST]:
    parents: Dict[int, ast.AST] = {}
    stack = [root]
    while stack:
        cur = stack.pop()
        for ch in ast.iter_child_nodes(cur):
            parents[id(ch)] = cur
            stack.append(ch)
    return parents


def _in_nested_def(node: ast.AST, stop: ast.AST,
                   parents: Dict[int, ast.AST]) -> bool:
    cur = parents.get(id(node))
    while cur is not None and cur is not stop:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Lambda)):
            return True
        cur = parents.get(id(cur))
    return False


# Classification tokens.
PURE, ESCAPE = "pure", "escape"


def _classify_use(name: ast.Name, stmt: ast.stmt,
                  parents: Dict[int, ast.AST]):
    """Classify one Load occurrence of a tracked name.

    Returns one of: ("pure",) · ("escape",) · ("release", proto, sig) ·
    ("transfer", proto, sig) · ("acquire_arg", proto, sig) ·
    ("call_arg", call_node, pos_or_kwname).

    The walk ascends through *transparent* wrappers (subscripts,
    starred, f-string pieces) until a decisive context; attribute
    reads are terminal PURE — ``r.name`` projects a non-resource
    value, unlike ``blocks[0]`` which projects the resource itself.
    """
    if _in_nested_def(name, stmt, parents):
        return (ESCAPE,)        # closure capture: lifetime leaves scope
    node: ast.AST = name
    while True:
        parent = parents.get(id(node))
        if parent is None:
            return (PURE,)
        if isinstance(parent, ast.Attribute):
            # x.attr — maybe the receiver of a recv_root release
            # (victim.mgr.stop_server()); else a plain projection.
            chain_top: ast.AST = parent
            up = parents.get(id(chain_top))
            while isinstance(up, ast.Attribute):
                chain_top, up = up, parents.get(id(up))
            if (isinstance(up, ast.Call) and up.func is chain_top
                    and isinstance(name, ast.Name)):
                for proto, sig, root in _recv_root_release(up):
                    if root == name.id:
                        return ("release", proto, sig)
            return (PURE,)
        if isinstance(parent, (ast.Subscript, ast.Starred)):
            node = parent
            continue
        if isinstance(parent, (ast.FormattedValue, ast.JoinedStr)):
            return (PURE,)
        if isinstance(parent, ast.Call):
            if parent.func is node:
                return (PURE,)          # calling x() — a read
            return _classify_call_arg(name, node, parent)
        if isinstance(parent, ast.keyword):
            call = parents.get(id(parent))
            if isinstance(call, ast.Call):
                return _classify_call_arg(name, node, call,
                                          kwname=parent.arg)
            return (ESCAPE,)
        if isinstance(parent, ast.Return):
            return (ESCAPE,)
        if isinstance(parent, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            return (ESCAPE,)            # alias / stored value
        if isinstance(parent, (ast.List, ast.Tuple, ast.Set, ast.Dict)):
            return (ESCAPE,)            # stored in a container
        if isinstance(parent, ast.BinOp):
            return (ESCAPE,)            # list concat aliases contents
        if isinstance(parent, (ast.Compare, ast.BoolOp, ast.UnaryOp)):
            return (PURE,)
        if isinstance(parent, ast.IfExp):
            if parent.test is node:
                return (PURE,)
            return (ESCAPE,)
        if isinstance(parent, (ast.For, ast.AsyncFor)):
            return (PURE,)              # iteration reads elements
        if isinstance(parent, (ast.comprehension, ast.Slice, ast.Expr,
                               ast.If, ast.While, ast.withitem)):
            return (PURE,)
        if isinstance(parent, ast.Raise):
            return (ESCAPE,)
        return (PURE,)


def _classify_call_arg(name: ast.Name, arg_node: ast.AST, call: ast.Call,
                       kwname: Optional[str] = None):
    """``name`` reaches ``call`` as (possibly wrapped) argument
    ``arg_node``; decide what the call does with it."""
    direct = arg_node is name
    for proto, sig in _match_in(call, "releases"):
        if sig.arg in ("arg0", "any") and \
                name.id in _release_arg_names(call, sig):
            return ("release", proto, sig)
    for proto, sig in _match_in(call, "transfers"):
        if name.id in _release_arg_names(call, sig):
            return ("transfer", proto, sig)
    acq = match_acquire(call)
    if acq is not None and acq[1].bind == "arg0" and direct \
            and call.args and call.args[0] is name:
        return ("acquire_arg",) + acq
    parts = _call_parts(call)
    leaf = parts[-1] if parts else None
    if leaf in NON_RETAINING:
        return (PURE,)
    if direct:
        # Candidate for an interprocedural summary lookup.
        if kwname is not None:
            return ("call_arg", call, kwname)
        try:
            pos = call.args.index(name)
        except ValueError:
            return (ESCAPE,)
        return ("call_arg", call, pos)
    return (ESCAPE,)


# -- interprocedural parameter summaries -----------------------------------

# Effect lattice: pure < release:<proto> < escape.
def _join_effect(a: str, b: str) -> str:
    if ESCAPE in (a, b):
        return ESCAPE
    if a.startswith("release:"):
        return a
    if b.startswith("release:"):
        return b
    return PURE


def _param_names(fi: FuncInfo) -> List[str]:
    a = fi.node.args
    names = [x.arg for x in getattr(a, "posonlyargs", [])]
    names += [x.arg for x in a.args]
    names += [x.arg for x in a.kwonlyargs]
    return names


def _param_key(callee: FuncInfo, pos_or_kw, method_call: bool):
    names = _param_names(callee)
    if callee.class_name is not None and method_call and names:
        names = names[1:]               # drop self/cls
    if isinstance(pos_or_kw, int):
        if pos_or_kw < len(names):
            return names[pos_or_kw]
        return None                     # lands in *args — give up
    return pos_or_kw if pos_or_kw in names else None


def param_summaries(project: Project) -> Dict[str, Dict[str, str]]:
    """gid → {param name → "pure" | "release:<proto>" | "escape"},
    computed to fixpoint over the resolved call graph.  Cached on the
    project object (same idiom as project_symbols)."""
    cached = getattr(project, "_dllm_own_summaries", None)
    if cached is not None:
        return cached
    ps = project_symbols(project)
    # Dependencies: (gid, param) → effects list of either literal
    # effect strings or ("dep", callee_gid, param_key).
    raw: Dict[Tuple[str, str], List] = {}
    for gid, gf in ps.functions.items():
        fi = gf.info
        pnames = set(_param_names(fi))
        if not pnames:
            continue
        parents = _parent_map(fi.node)
        for sub in ast.walk(fi.node):
            if not (isinstance(sub, ast.Name) and sub.id in pnames
                    and isinstance(sub.ctx, ast.Load)):
                continue
            if _in_nested_def(sub, fi.node, parents):
                raw.setdefault((gid, sub.id), []).append(ESCAPE)
                continue
            stmt = sub
            while not isinstance(stmt, ast.stmt):
                nxt = parents.get(id(stmt))
                if nxt is None:
                    break
                stmt = nxt
            tok = _classify_use(sub, stmt, parents)
            if tok[0] == "release":
                raw.setdefault((gid, sub.id), []).append(
                    "release:" + tok[1].name)
            elif tok[0] in ("transfer", ESCAPE):
                raw.setdefault((gid, sub.id), []).append(ESCAPE)
            elif tok[0] == "call_arg":
                call, key = tok[1], tok[2]
                callee_gid = ps.callee_of(gf.relpath, call)
                if callee_gid is None:
                    raw.setdefault((gid, sub.id), []).append(ESCAPE)
                else:
                    callee = ps.functions[callee_gid].info
                    pk = _param_key(callee, key,
                                    isinstance(call.func, ast.Attribute))
                    if pk is None:
                        raw.setdefault((gid, sub.id), []).append(ESCAPE)
                    else:
                        raw.setdefault((gid, sub.id), []).append(
                            ("dep", callee_gid, pk))
            # acquire_arg / pure contribute nothing
    effects: Dict[Tuple[str, str], str] = {k: PURE for k in raw}
    changed = True
    while changed:
        changed = False
        for key, toks in raw.items():
            cur = effects[key]
            for tok in toks:
                if isinstance(tok, tuple):
                    dep = effects.get((tok[1], tok[2]), PURE)
                    cur = _join_effect(cur, dep)
                else:
                    cur = _join_effect(cur, tok)
            if cur != effects[key]:
                effects[key] = cur
                changed = True
    out: Dict[str, Dict[str, str]] = {}
    for (gid, p), eff in effects.items():
        out.setdefault(gid, {})[p] = eff
    project._dllm_own_summaries = out  # type: ignore[attr-defined]
    return out


# -- the per-function dataflow ---------------------------------------------

@dataclass
class _VarInfo:
    proto: Protocol
    lines: Set[int] = field(default_factory=set)
    inverted: bool = False


State = Dict[str, FrozenSet[str]]


def _acquire_value(value: ast.expr):
    """(call, proto, sig, optional) if this assigned value is an
    acquire — a matching Call, or an IfExp with a matching arm."""
    if isinstance(value, ast.Call):
        m = match_acquire(value)
        if m and m[1].bind == "result":
            return value, m[0], m[1], m[1].optional
    if isinstance(value, ast.IfExp):
        for arm in (value.body, value.orelse):
            if isinstance(arm, ast.Call):
                m = match_acquire(arm)
                if m and m[1].bind == "result":
                    return arm, m[0], m[1], True
    return None


class _FuncFlow:
    def __init__(self, mod, fi: FuncInfo, ps: ProjectSymbols,
                 summaries: Dict[str, Dict[str, str]]):
        self.mod = mod
        self.fi = fi
        self.ps = ps
        self.summaries = summaries
        self.vinfo: Dict[str, _VarInfo] = {}
        self.findings: List[Finding] = []
        self._seen: Set[Tuple] = set()
        self.parents = _parent_map(fi.node)
        # leak bookkeeping: (var, line) → set of exit kinds
        self._leaks: Dict[Tuple[str, int], Set[str]] = {}

    # -- findings ---------------------------------------------------------

    def _emit(self, rule: str, line: int, msg: str, key: Tuple) -> None:
        if key in self._seen:
            return
        self._seen.add(key)
        self.findings.append(Finding(rule, self.mod.relpath, line, msg))

    def _leak(self, var: str, kind: str) -> None:
        info = self.vinfo.get(var)
        if info is None:
            return
        for line in info.lines:
            self._leaks.setdefault((var, line), set()).add(kind)

    def flush_leaks(self) -> None:
        for (var, line), kinds in sorted(self._leaks.items()):
            info = self.vinfo[var]
            rule = _LEAK_RULE[info.proto.kind]
            noun = "pin" if info.proto.kind == "pin" else "resource"
            where = {"exc": "an exception path",
                     "normal": "a normal exit path",
                     "overwrite": "every path (its binding is "
                                  "overwritten while still owned)"}
            kinds_txt = " and ".join(where[k] for k in sorted(kinds))
            self._emit(
                rule, line,
                f"{info.proto.name} {noun} '{var}' acquired here is not "
                f"released on {kinds_txt} — pair the acquire with "
                f"{info.proto.release_hint} on every path, exception "
                f"edges included, or hand ownership off explicitly",
                (rule, var, line))

    # -- state helpers ----------------------------------------------------

    def _track(self, var: str, proto: Protocol, line: int) -> None:
        info = self.vinfo.get(var)
        if info is None or info.proto is not proto:
            self.vinfo[var] = info = _VarInfo(
                proto, inverted=proto.none_is_acquired)
        info.lines.add(line)

    def _release_var(self, S: dict, var: str, line: int,
                     via_summary: bool = False) -> None:
        cur = S.get(var)
        if cur is None:
            return
        info = self.vinfo[var]
        if not via_summary and RELEASED in cur and OWNED not in cur \
                and ESCAPED not in cur:
            self._emit(
                OWN_DOUBLE, line,
                f"{info.proto.name} resource '{var}' (acquired at line "
                f"{min(info.lines)}) is already released when it is "
                f"released again here — the first release's new owner "
                f"is corrupted by the second",
                (OWN_DOUBLE, var, line))
        if not via_summary and TRANSFERRED in cur and OWNED not in cur \
                and ESCAPED not in cur:
            self._emit(
                OWN_UAT, line,
                f"ownership of '{var}' was already transferred "
                f"(acquired at line {min(info.lines)}) when it is "
                f"released here — the new owner controls its lifecycle",
                (OWN_UAT, var, line))
        new = set()
        for s in cur:
            new.add({OWNED: RELEASED, NONE: NONE, RELEASED: RELEASED,
                     TRANSFERRED: TRANSFERRED, ESCAPED: ESCAPED}[s])
        S[var] = frozenset(new)

    def _transfer_var(self, S: dict, var: str, line: int) -> None:
        cur = S.get(var)
        if cur is None:
            return
        info = self.vinfo[var]
        if (TRANSFERRED in cur or RELEASED in cur) and OWNED not in cur \
                and ESCAPED not in cur:
            self._emit(
                OWN_UAT, line,
                f"'{var}' (acquired at line {min(info.lines)}) is handed "
                f"off here but ownership already moved on every path "
                f"reaching this line",
                (OWN_UAT, var, line))
        new = {ESCAPED if s == ESCAPED else
               (NONE if s == NONE else TRANSFERRED) for s in cur}
        S[var] = frozenset(new)

    def _escape_var(self, S: dict, var: str) -> None:
        if var in S:
            S[var] = frozenset({ESCAPED if s != NONE else NONE
                                for s in S[var]})

    def _overwrite(self, S: dict, var: str) -> None:
        cur = S.get(var)
        if cur is not None and OWNED in cur:
            self._leak(var, "overwrite")
        S.pop(var, None)

    # -- statement transfer ------------------------------------------------

    def _apply_uses(self, S: dict, st: ast.AST, line: int) -> None:
        """Releases / transfers / escapes / summaries for every tracked
        name read by this statement, plus arg="all" releases and
        deferred-release closures."""
        tracked = set(S)
        if tracked:
            for sub in ast.walk(st):
                if not (isinstance(sub, ast.Name) and sub.id in tracked
                        and isinstance(sub.ctx, ast.Load)):
                    continue
                var = sub.id
                info = self.vinfo[var]
                tok = _classify_use(sub, st, self.parents)
                ln = getattr(sub, "lineno", line)
                if tok[0] == "release":
                    if tok[1] is info.proto:
                        self._release_var(S, var, ln)
                    else:
                        self._escape_var(S, var)
                elif tok[0] == "transfer":
                    if tok[1] is info.proto:
                        self._transfer_var(S, var, ln)
                    else:
                        self._escape_var(S, var)
                elif tok[0] == "acquire_arg":
                    pass                 # handled as binding below
                elif tok[0] == "call_arg":
                    self._apply_summary(S, var, tok[1], tok[2], ln)
                elif tok[0] == ESCAPE:
                    self._escape_var(S, var)
        # arg="all" releases (admission.release()) and deferred-release
        # closures: a nested def containing a protocol release means
        # the release happens later — stop tracking that protocol.
        for sub in ast.walk(st):
            if isinstance(sub, ast.Call):
                in_closure = _in_nested_def(sub, st, self.parents)
                for proto, sig in _match_in(sub, "releases"):
                    if in_closure:
                        for var, info in list(self.vinfo.items()):
                            if info.proto is proto:
                                self._escape_var(S, var)
                    elif sig.arg == "all":
                        ln = getattr(sub, "lineno", line)
                        for var, info in list(self.vinfo.items()):
                            if info.proto is proto:
                                self._release_var(S, var, ln)

    def _closure_escape(self, S: dict, st: ast.AST) -> None:
        """A nested def/class statement: referenced tracked names and
        deferred-release protocols all escape."""
        protos = set()
        for sub in ast.walk(st):
            if isinstance(sub, ast.Name) and sub.id in S \
                    and isinstance(sub.ctx, ast.Load):
                self._escape_var(S, sub.id)
            if isinstance(sub, ast.Call):
                for proto, _sig in _match_in(sub, "releases"):
                    protos.add(proto)
        for var, info in list(self.vinfo.items()):
            if info.proto in protos:
                self._escape_var(S, var)

    def _apply_summary(self, S: dict, var: str, call: ast.Call,
                       key, line: int) -> None:
        gid = self.ps.callee_of(self.mod.relpath, call)
        if gid is None:
            self._escape_var(S, var)
            return
        callee = self.ps.functions[gid].info
        pk = _param_key(callee, key, isinstance(call.func, ast.Attribute))
        eff = PURE
        if pk is None:
            eff = ESCAPE
        else:
            eff = self.summaries.get(gid, {}).get(pk, PURE)
        if eff == ESCAPE:
            self._escape_var(S, var)
        elif eff.startswith("release:"):
            if eff.split(":", 1)[1] == self.vinfo[var].proto.name:
                self._release_var(S, var, line, via_summary=True)
            else:
                self._escape_var(S, var)

    def _bind_targets(self, S: dict, targets) -> None:
        for t in targets:
            for sub in ast.walk(t):
                if isinstance(sub, ast.Name):
                    self._overwrite(S, sub.id)

    def transfer(self, node) -> Tuple[Optional[dict], Optional[dict]]:
        """(normal out-state, exceptional out-state) for one node,
        given a mutable copy of the in-state bound to self._S."""
        S = self._S
        st = node.stmt
        kind = node.kind
        if kind == "test":
            self._apply_uses(S, node.expr,
                             getattr(node.expr, "lineno", 0))
            return S, dict(S)
        if kind in ("join", "exit", "raises"):
            return S, dict(S)
        line = getattr(st, "lineno", 0)
        if kind == "for-bind":
            self._bind_targets(S, [st.target])
            return S, dict(S)
        if kind == "for-iter":
            self._apply_uses(S, st.iter, line)
            return S, dict(S)
        if kind == "with":
            for item in st.items:
                self._apply_uses(S, item.context_expr, line)
            exc = dict(S)
            for item in st.items:
                if item.optional_vars is not None:
                    self._bind_targets(S, [item.optional_vars])
            return S, exc
        if kind == "except":
            if st.name:
                self._overwrite(S, st.name)
            return S, dict(S)
        # plain statements -------------------------------------------------
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                           ast.ClassDef)):
            # The whole statement is a deferred body: every tracked
            # name it references escapes into the closure, and a
            # protocol release inside it is a deferred release — stop
            # tracking that protocol's resources too.
            self._closure_escape(S, st)
            self._overwrite(S, st.name)
            return S, dict(S)
        self._apply_uses(S, st, line)
        exc = dict(S)
        # Acquire bindings & overwrites happen only on the normal edge.
        if isinstance(st, ast.Assign) and len(st.targets) == 1 \
                and isinstance(st.targets[0], ast.Name):
            var = st.targets[0].id
            acq = _acquire_value(st.value)
            if acq is not None:
                call, proto, sig, optional = acq
                self._overwrite(S, var)
                self._track(var, proto, line)
                S[var] = frozenset({OWNED, NONE} if optional
                                   else {OWNED})
            else:
                self._overwrite(S, var)
        elif isinstance(st, (ast.Assign, ast.AnnAssign)):
            targets = st.targets if isinstance(st, ast.Assign) \
                else [st.target]
            self._bind_targets(S, targets)
        elif isinstance(st, ast.Expr) and isinstance(st.value, ast.Call):
            m = match_acquire(st.value)
            if m is not None and m[1].bind == "result":
                proto = m[0]
                rule = _LEAK_RULE[proto.kind]
                self._emit(
                    rule, line,
                    f"result of {proto.name} acquire "
                    f"'{m[1].method}()' is discarded — the resource "
                    f"can never be released "
                    f"({proto.release_hint})",
                    (rule, "<discard>", line))
            # ``allocator.share(x)`` acquires its argument in place.
            if m is not None and m[1].bind == "arg0" and st.value.args \
                    and isinstance(st.value.args[0], ast.Name):
                var = st.value.args[0].id
                self._track(var, m[0], line)
                # Normal edge only — same rule as bind="result": if the
                # acquire call itself raises, the incref may never have
                # happened and an unwind release would corrupt refcounts.
                S[var] = frozenset({OWNED})
        elif isinstance(st, ast.Delete):
            for t in st.targets:
                if isinstance(t, ast.Name):
                    self._overwrite(S, t.id)
        return S, exc

    # -- edges ------------------------------------------------------------

    def refine(self, S: dict, expr: ast.expr,
               branch: bool) -> Optional[dict]:
        """Narrow S along a test edge; None = edge infeasible."""
        var, true_means_none = _none_test(expr)
        if var is None or var not in S:
            return S
        info = self.vinfo.get(var)
        if info is None:
            return S
        none_branch = true_means_none if branch else not true_means_none
        cur = S[var]
        if info.inverted:
            # try_admit: result None ⇔ permit held (OWNED).
            keep = ({OWNED, ESCAPED} if none_branch
                    else {NONE, RELEASED, TRANSFERRED, ESCAPED})
        else:
            keep = ({NONE, ESCAPED} if none_branch
                    else {OWNED, RELEASED, TRANSFERRED, ESCAPED})
        new = cur & frozenset(keep)
        if not new:
            return None
        out = dict(S)
        out[var] = new
        return out

    # -- driver ------------------------------------------------------------

    def run(self, cfg) -> List[Finding]:
        states: List[Optional[State]] = [None] * len(cfg.nodes)
        states[cfg.entry] = {}
        work = [cfg.entry]
        while work:
            ix = work.pop()
            node = cfg.nodes[ix]
            in_state = states[ix]
            if in_state is None:
                continue
            self._S = {k: v for k, v in in_state.items()}
            normal, exc = self.transfer(node)
            for e in node.succ:
                out = exc if e.exc else normal
                if out is None:
                    continue
                out2 = dict(out)
                if e.exc:
                    for var in list(out2):
                        if not self.vinfo[var].proto.exc_edges:
                            del out2[var]
                if e.refine is not None:
                    out2 = self.refine(out2, *e.refine)
                    if out2 is None:
                        continue
                tgt = states[e.dst]
                if tgt is None:
                    states[e.dst] = out2
                    work.append(e.dst)
                else:
                    changed = False
                    for var, vals in out2.items():
                        old = tgt.get(var, frozenset())
                        if not vals <= old:
                            tgt[var] = old | vals
                            changed = True
                    if changed:
                        work.append(e.dst)
        for kind, ix in (("normal", cfg.exit), ("exc", cfg.raises)):
            st = states[ix]
            if not st:
                continue
            for var, vals in st.items():
                if OWNED in vals:
                    self._leak(var, kind)
        self.flush_leaks()
        return self.findings


def _none_test(expr: ast.expr) -> Tuple[Optional[str], bool]:
    """(varname, true_branch_means_none) for the three refinable test
    shapes — ``x`` (truthy ⇒ non-None for the tracked value shapes:
    non-empty block lists, entries, tuples), ``x is None`` and
    ``x is not None``; (None, False) for anything else."""
    if isinstance(expr, ast.Name):
        return expr.id, False
    if (isinstance(expr, ast.Compare) and len(expr.ops) == 1
            and isinstance(expr.comparators[0], ast.Constant)
            and expr.comparators[0].value is None
            and isinstance(expr.left, ast.Name)):
        if isinstance(expr.ops[0], ast.Is):
            return expr.left.id, True
        if isinstance(expr.ops[0], ast.IsNot):
            return expr.left.id, False
    return None, False


# -- per-function driver ----------------------------------------------------

def _has_acquire(func_node: ast.AST) -> bool:
    for sub in ast.walk(func_node):
        if isinstance(sub, ast.Call) and match_acquire(sub) is not None:
            return True
    return False


def _is_generator(func_node: ast.AST) -> bool:
    stack = list(ast.iter_child_nodes(func_node))
    while stack:
        cur = stack.pop()
        if isinstance(cur, (ast.Yield, ast.YieldFrom)):
            return True
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(cur))
    return False


def analyze_function(mod, fi: FuncInfo, ps: ProjectSymbols,
                     summaries: Dict[str, Dict[str, str]]
                     ) -> List[Finding]:
    """Ownership dataflow over one function; [] when out of scope
    (no acquires, generator/async body, unsupported flow)."""
    node = fi.node
    if isinstance(node, (ast.AsyncFunctionDef, ast.Lambda)):
        return []
    if not _has_acquire(node) or _is_generator(node):
        return []
    try:
        cfg = build_cfg(node)
    except (UnsupportedFlow, RecursionError):
        return []
    flow = _FuncFlow(mod, fi, ps, summaries)
    return flow.run(cfg)


class OwnershipChecker(Checker):
    """Path-sensitive resource ownership dataflow (see module doc)."""

    name = "ownership"
    rules = (OWN_LEAK, OWN_DOUBLE, OWN_UAT, OWN_PIN)
    scope = ("distributed_llm_tpu", "scripts", "bench.py",
             "tests/conftest.py")
    whole_project = True

    def check(self, project: Project) -> List[Finding]:
        ps = project_symbols(project)
        summaries = param_summaries(project)
        findings: List[Finding] = []
        for mod in project.in_dirs(self.scope):
            if mod.tree is None:
                continue
            for fi in symbols_for(mod).functions.values():
                findings.extend(analyze_function(mod, fi, ps, summaries))
        return findings
