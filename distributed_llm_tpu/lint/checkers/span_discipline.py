"""Span-discipline checker — every span enter has a structural exit.

Every span ENTER must have a matching EXIT on every return/raise path.
obs/spans.py makes that structural — spans are context managers — so the
discipline reduces to two statically checkable rules for the
instrumented layers (serving/, engine/):

- ``span-not-with``: every call to a ``span(...)`` factory (``trace.span``,
  ``parent.span``, ``spans.span``) and to the PhaseTimer's ``phase(...)``
  must appear ONLY as a ``with``-statement context item — a bare call
  would open a span whose exit depends on later code reaching it.
- ``span-manual-enter``: manual enter APIs (``start_span`` /
  ``begin_span`` / explicit ``__enter__``) are forbidden outside obs/
  itself; long-lived work that cannot be ``with``-scoped uses the token
  timeline / completion-callback pattern instead (obs/spans.py).

``check_source`` / ``check_tree`` keep the original standalone script's
string-list API (tests/test_obs.py drives exactly that surface; the
``scripts/check_span_discipline.py`` delegation shim it once backed was
removed in ISSUE 11 — ``python -m distributed_llm_tpu.lint`` is the one
CLI).
"""

from __future__ import annotations

import ast
import os
from typing import List

from ..core import Checker, Finding, Module, Project
from ..symbols import call_name as _call_name

# Context-manager factories that MUST be with-items.
WITH_ONLY = {"span", "phase"}
# Manual-enter APIs that must not appear at all in instrumented layers.
FORBIDDEN = {"start_span", "begin_span", "__enter__"}


def _findings_for_tree(tree: ast.Module, path: str) -> List[Finding]:
    with_items = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                if isinstance(item.context_expr, ast.Call):
                    with_items.add(id(item.context_expr))

    out: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = _call_name(node)
        if name in FORBIDDEN:
            out.append(Finding(
                "span-manual-enter", path, node.lineno,
                f"manual span enter `{name}(...)` — use "
                f"`with ....span(...)` so the exit is structural"))
        elif name in WITH_ONLY and id(node) not in with_items:
            out.append(Finding(
                "span-not-with", path, node.lineno,
                f"`{name}(...)` called outside a `with` item — the "
                f"span/phase would have no guaranteed exit on "
                f"raise/return paths"))
    return out


class SpanDisciplineChecker(Checker):
    name = "span_discipline"
    rules = ("span-not-with", "span-manual-enter")
    scope = ("distributed_llm_tpu/serving", "distributed_llm_tpu/engine")

    def check(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        for mod in project.in_dirs(self.scope):
            if mod.tree is None:
                continue
            findings.extend(_findings_for_tree(mod.tree, mod.relpath))
        return findings


# -- legacy string-list API (tests/test_obs.py's back-compat pin) ------------

def check_source(src: str, path: str = "<string>") -> List[str]:
    """Violation strings for one module's source (empty = clean).
    Honors the framework's suppression comments, so the shim and the
    checker agree on what "clean" means."""
    mod = Module(path, src)
    if mod.tree is None:
        return [f"{path}: failed to parse: {mod.parse_error}"]
    return [f"{f.path}:{f.line}: {f.message}"
            for f in _findings_for_tree(mod.tree, path)
            if not mod.suppressions.covers(f.rule, f.line)]


def check_tree(dirs=None) -> List[str]:
    """Violation strings over the instrumented layers (legacy surface)."""
    from ..core import repo_root
    root = repo_root()
    if dirs is None:
        dirs = (os.path.join(root, "distributed_llm_tpu", "serving"),
                os.path.join(root, "distributed_llm_tpu", "engine"))
    out: List[str] = []
    for root_dir in dirs:
        for dirpath, _dirnames, filenames in os.walk(root_dir):
            if "__pycache__" in dirpath:
                continue
            for fname in sorted(filenames):
                if not fname.endswith(".py"):
                    continue
                path = os.path.join(dirpath, fname)
                with open(path, encoding="utf-8") as f:
                    out.extend(check_source(f.read(),
                                            os.path.relpath(path, root)))
    return out
