"""Metrics discipline: emission sites and the metric registry must
agree, and every label must ride a cardinality bound.

``obs/metrics.py`` declares every ``dllm_*`` family ONCE as data
(``METRIC_REGISTRY`` rows — attribute, kind, name, labels, help) and
``ServingMetrics`` materializes the rows, so family creation cannot
drift from the table.  What CAN drift:

- an ad-hoc creation or lookup somewhere else —
  ``registry.counter("dllm_new_thing_total", …)`` in a serving module,
  ``metrics.get("dllm_renamed_total")`` in bench.py — whose name,
  kind, or label set the registry never heard of
  (``metrics-unregistered``);
- a registry row minting a label name with no entry in
  ``BOUNDED_LABELS`` (``metrics-label-cardinality``): metric children
  are permanent, so an unbounded caller-supplied label value grows
  ``/metrics`` without bound (the PR 11 session-label lesson).

The registry rows are read from the AST (``ast.literal_eval`` per
row), not imported — line numbers come free, a malformed (non-literal)
row is itself a finding, and lint fixtures can carry their own tiny
registry module.  Emission detection is call-shaped: a call whose
attribute leaf is ``counter``/``gauge``/``histogram``/``get``/
``_family`` with a string-constant first argument starting ``dllm_``.
Non-metric ``dllm_`` strings (ContextVar names, Flask app names,
extension keys) never match that shape, preserving the no-false-edge
invariant.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from ..core import Checker, Finding, Project

REGISTRY_PATH = "distributed_llm_tpu/obs/metrics.py"
CREATE_LEAVES = ("counter", "gauge", "histogram", "get", "_family")
KIND_OF_LEAF = {"counter": "counter", "gauge": "gauge",
                "histogram": "histogram"}


def _call_leaf(call: ast.Call) -> Optional[str]:
    f = call.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return None


def _metric_name(call: ast.Call) -> Optional[str]:
    if (call.args and isinstance(call.args[0], ast.Constant)
            and isinstance(call.args[0].value, str)
            and call.args[0].value.startswith("dllm_")):
        return call.args[0].value
    return None


def _literal_labels(call: ast.Call, leaf: str) -> Optional[Tuple[str, ...]]:
    """The label-name tuple at a creation call, when statically literal
    (None = not stated / not literal — skip the label comparison)."""
    node: Optional[ast.expr] = None
    pos = 3 if leaf == "_family" else 2
    if len(call.args) > pos:
        node = call.args[pos]
    for kw in call.keywords:
        if kw.arg == "labels":
            node = kw.value
    if node is None:
        return () if len(call.args) > 1 or call.keywords else None
    try:
        val = ast.literal_eval(node)
    except (ValueError, SyntaxError):
        return None
    if isinstance(val, (tuple, list)) and all(
            isinstance(x, str) for x in val):
        return tuple(val)
    return None


def _registry_tables(mod) -> Tuple[Optional[ast.expr], Optional[ast.expr]]:
    """(METRIC_REGISTRY value node, BOUNDED_LABELS value node)."""
    reg = bounds = None
    for node in mod.tree.body:
        target = None
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            target = node.targets[0].id
        elif isinstance(node, ast.AnnAssign) \
                and isinstance(node.target, ast.Name):
            target = node.target.id
        if target == "METRIC_REGISTRY":
            reg = node.value
        elif target == "BOUNDED_LABELS":
            bounds = node.value
    return reg, bounds


class MetricsDisciplineChecker(Checker):
    name = "metrics_discipline"
    rules = ("metrics-unregistered", "metrics-label-cardinality")
    scope = ("distributed_llm_tpu", "scripts", "bench.py",
             "tests/conftest.py")
    # A new emission anywhere must be checked against the (unchanged)
    # registry module, so --changed must not narrow the project.
    whole_project = True

    def check(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        reg_mod = project.modules.get(REGISTRY_PATH)
        if reg_mod is None or reg_mod.tree is None:
            # Fixture projects carry their own tiny registry module.
            for mod in project.in_dirs(self.scope):
                if mod.tree is None:
                    continue
                if _registry_tables(mod)[0] is not None:
                    reg_mod = mod
                    break
        if reg_mod is None or reg_mod.tree is None:
            return findings
        reg_node, bounds_node = _registry_tables(reg_mod)
        rows: Dict[str, Tuple[str, Tuple[str, ...], int]] = {}
        if reg_node is not None and isinstance(reg_node, (ast.Tuple,
                                                          ast.List)):
            for elt in reg_node.elts:
                try:
                    row = ast.literal_eval(elt)
                except (ValueError, SyntaxError):
                    findings.append(Finding(
                        "metrics-unregistered", reg_mod.relpath,
                        elt.lineno,
                        "METRIC_REGISTRY row is not a pure literal — "
                        "the checker (and METRICS.md) read rows from "
                        "the AST, so computed rows are invisible"))
                    continue
                if (not isinstance(row, tuple) or len(row) != 5
                        or not all(isinstance(x, str) for x in
                                   (row[0], row[1], row[2], row[4]))
                        or not isinstance(row[3], tuple)):
                    findings.append(Finding(
                        "metrics-unregistered", reg_mod.relpath,
                        elt.lineno,
                        "METRIC_REGISTRY row shape must be (attr, "
                        "kind, name, label-tuple, help)"))
                    continue
                _attr, kind, name, labels, _help = row
                if name in rows:
                    findings.append(Finding(
                        "metrics-unregistered", reg_mod.relpath,
                        elt.lineno,
                        f"duplicate METRIC_REGISTRY row for {name} "
                        f"(first declared at line {rows[name][2]})"))
                    continue
                rows[name] = (kind, tuple(labels), elt.lineno)

        bounds: Dict[str, str] = {}
        if bounds_node is not None:
            try:
                val = ast.literal_eval(bounds_node)
                if isinstance(val, dict):
                    bounds = {str(k): str(v) for k, v in val.items()}
            except (ValueError, SyntaxError):
                pass

        # Registry-side label bounds: report at the first row minting
        # the unbounded label.
        flagged: set = set()
        for name, (kind, labels, line) in sorted(
                rows.items(), key=lambda kv: kv[1][2]):
            for lab in labels:
                if lab in bounds and bounds[lab].strip():
                    continue
                if lab in flagged:
                    continue
                flagged.add(lab)
                findings.append(Finding(
                    "metrics-label-cardinality", reg_mod.relpath, line,
                    f"label '{lab}' of {name} has no entry in "
                    f"BOUNDED_LABELS — metric children are permanent, "
                    f"so every label needs a stated cardinality bound "
                    f"(closed enum or a BoundedLabels set)"))

        # Emission sites project-wide vs the registry.
        for mod in project.in_dirs(self.scope):
            if mod.tree is None:
                continue
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.Call):
                    continue
                leaf = _call_leaf(node)
                if leaf not in CREATE_LEAVES:
                    continue
                name = _metric_name(node)
                if name is None:
                    continue
                if name not in rows:
                    findings.append(Finding(
                        "metrics-unregistered", mod.relpath, node.lineno,
                        f"metric {name} emitted here but not declared "
                        f"in obs/metrics.py METRIC_REGISTRY — add a "
                        f"row (or fix the name drift)"))
                    continue
                kind, labels, _line = rows[name]
                want_kind = KIND_OF_LEAF.get(leaf)
                if want_kind is not None and want_kind != kind:
                    findings.append(Finding(
                        "metrics-unregistered", mod.relpath, node.lineno,
                        f"metric {name} created as {want_kind} here "
                        f"but registered as {kind}"))
                    continue
                here = _literal_labels(node, leaf)
                if (leaf != "get" and here is not None
                        and here != labels):
                    findings.append(Finding(
                        "metrics-unregistered", mod.relpath, node.lineno,
                        f"metric {name} created with labels "
                        f"{here!r} but registered with {labels!r}"))
        return findings
