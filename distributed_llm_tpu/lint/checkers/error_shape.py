"""Error-shape conformance: every error-dict literal matches the schema.

The reference error path (serving/errors.py) is load-bearing parity
surface: ``src/app.py`` and ``routing_chatbot_tester.py`` both parse
``{"error": <str>}`` (plus the sanctioned ``retry_after_s`` extension).
This checker validates every dict LITERAL carrying the error key inside
the tier/router layers against the single schema constant:

- keys must all be static strings drawn from ``ALLOWED_KEYS``,
- the error value must be string-shaped (constant/f-string/concat/
  ``str(...)``/name — a nested dict or number breaks ``_extract_text``),
- ``retry_after_s`` must be numeric-shaped (constant/``round``/``float``
  /``int``/name).

Scope: serving/, engine/, and utils/faults.py — the layers whose dicts
flow into Router failover.  HTTP-layer bodies (utils/webapp.py) use
their own status-code envelope and are deliberately out of scope.
"""

from __future__ import annotations

import ast
from typing import List

from ..core import Checker, Finding, Project

# Imported for the single-source-of-truth constants; serving/errors.py
# is stdlib-only so this never drags jax into the lint CLI.
from ...serving.errors import ALLOWED_KEYS, ERROR_KEY, NUMERIC_KEYS

_STRINGY = (ast.JoinedStr,)
_NUMERIC_CALLS = {"round", "float", "int"}


def _is_stringy(node: ast.expr) -> bool:
    if isinstance(node, ast.Constant):
        return isinstance(node.value, str)
    if isinstance(node, _STRINGY):
        return True
    if isinstance(node, ast.BinOp):       # "a" + x, "%s" % x
        return _is_stringy(node.left) or _is_stringy(node.right)
    if isinstance(node, ast.Call):
        fn = node.func
        if isinstance(fn, ast.Name):
            return fn.id in ("str", "repr", "format")
        if isinstance(fn, ast.Attribute):   # "...".format(...), s.strip()
            return True
    # Names/attributes/subscripts can't be typed statically — trust them.
    return isinstance(node, (ast.Name, ast.Attribute, ast.Subscript,
                             ast.IfExp))


def _is_numericy(node: ast.expr) -> bool:
    if isinstance(node, ast.Constant):
        return isinstance(node.value, (int, float)) and not isinstance(
            node.value, bool)
    if isinstance(node, ast.Call):
        fn = node.func
        if isinstance(fn, ast.Name) and fn.id in _NUMERIC_CALLS:
            return True
        return isinstance(fn, ast.Attribute)    # max(...), math.ceil(...)
    if isinstance(node, ast.BinOp):
        return True
    if isinstance(node, ast.UnaryOp):
        return _is_numericy(node.operand)
    return isinstance(node, (ast.Name, ast.Attribute, ast.Subscript,
                             ast.IfExp))


class ErrorShapeChecker(Checker):
    name = "error_shape"
    rules = ("error-shape",)
    scope = ("distributed_llm_tpu/serving", "distributed_llm_tpu/engine",
             "distributed_llm_tpu/utils/faults.py")

    def check(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        for mod in project.in_dirs(self.scope):
            if mod.tree is None:
                continue
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.Dict):
                    continue
                keys = {}
                dynamic = False
                for k, v in zip(node.keys, node.values):
                    if (isinstance(k, ast.Constant)
                            and isinstance(k.value, str)):
                        keys[k.value] = v
                    elif k is not None:
                        dynamic = True
                if ERROR_KEY not in keys:
                    continue
                line = node.lineno
                if dynamic:
                    findings.append(Finding(
                        "error-shape", mod.relpath, line,
                        "error-shaped dict with a computed key — the "
                        "reference shape requires static keys "
                        "(serving/errors.py ALLOWED_KEYS)"))
                extra = set(keys) - ALLOWED_KEYS
                if extra:
                    findings.append(Finding(
                        "error-shape", mod.relpath, line,
                        f"error-shaped dict carries non-reference "
                        f"key(s) {sorted(extra)} — allowed: "
                        f"{sorted(ALLOWED_KEYS)} (serving/errors.py)"))
                if not _is_stringy(keys[ERROR_KEY]):
                    findings.append(Finding(
                        "error-shape", mod.relpath, line,
                        f"'{ERROR_KEY}' value must be a string "
                        f"(reference clients and _extract_text parse "
                        f"it); got "
                        f"{type(keys[ERROR_KEY]).__name__}"))
                for nk in NUMERIC_KEYS & set(keys):
                    if not _is_numericy(keys[nk]):
                        findings.append(Finding(
                            "error-shape", mod.relpath, line,
                            f"'{nk}' must be numeric (reference "
                            f"retry-after contract); got "
                            f"{type(keys[nk]).__name__}"))
        return findings
