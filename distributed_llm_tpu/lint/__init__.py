"""dllm-lint: the repo's AST-based static-analysis suite.

``python -m distributed_llm_tpu.lint`` runs nine checkers over the
project (no jax import, CPU-only, a few seconds), sharing ONE parsed
AST per file and ONE whole-project call graph per run
(symbols.ProjectSymbols — import-aware cross-module resolution):

- ``locks``             lock-discipline / race detector (PR 2's bug
                        class), blocking-ness propagated CROSS-MODULE
- ``jit_purity``        host impurity inside jit/pjit/shard_map traces
- ``error_shape``       reference error-dict conformance (parity surface)
- ``config_drift``      DLLM_* env vars + config fields vs the registry
- ``span_discipline``   span enter/exit pairing (PR 3)
- ``obs_discipline``    the SLO monitor's single-feed-site contract
- ``profiler_discipline``  no tick-profiler stamps inside the traced
                        closure (they'd bake a trace-time constant
                        into the compiled program)
- ``retrace``           compile-churn hazards at jit/pallas roots — the
                        static half of PR 6's one-decode-program pin
- ``transfer``          host↔device sync/round-trip discipline on
                        ``# dllm-lint: hot-path``-annotated roots
- ``thread_lifecycle``  non-daemon threads without a drain-reachable
                        join, acquire() without exception-safe release,
                        module-scope thread owners without a stop hook

Suppression: ``# dllm-lint: disable=<rule> -- <justification>`` (line or
file scope via ``disable-file``); the justification is mandatory and
enforced.  ``scripts/lint.sh --changed`` scopes reporting to the git
diff (whole-project checkers auto-widen).  Wired into tier-1 by
tests/test_lint.py.
"""

from __future__ import annotations

from typing import Optional, Sequence

from .checkers import all_checkers
from .core import (DEFAULT_TARGETS, Checker, Finding, LintResult, Module,
                   Project, load_project, repo_root, run_checkers)

__all__ = [
    "Checker", "Finding", "LintResult", "Module", "Project",
    "DEFAULT_TARGETS", "all_checkers", "load_project", "repo_root",
    "run_checkers", "run_lint",
]


def run_lint(root: Optional[str] = None,
             targets: Optional[Sequence[str]] = None,
             rules: Optional[Sequence[str]] = None) -> LintResult:
    """One-call entry point: load the project and run every checker."""
    project = load_project(root or repo_root(), targets)
    return run_checkers(project, all_checkers(), rules=rules)
