"""dllm-lint: the repo's AST-based static-analysis suite.

``python -m distributed_llm_tpu.lint`` runs five checkers over the
project (no jax import, CPU-only, sub-second):

- ``locks``            lock-discipline / race detector (PR 2's bug class)
- ``jit_purity``       host impurity inside jit/pjit/shard_map traces
- ``error_shape``      reference error-dict conformance (parity surface)
- ``config_drift``     DLLM_* env vars + config fields vs the registry
- ``span_discipline``  span enter/exit pairing (PR 3, migrated from
                       scripts/check_span_discipline.py)

Suppression: ``# dllm-lint: disable=<rule> -- <justification>`` (line or
file scope via ``disable-file``); the justification is mandatory and
enforced.  Wired into tier-1 by tests/test_lint.py.
"""

from __future__ import annotations

from typing import Optional, Sequence

from .checkers import all_checkers
from .core import (DEFAULT_TARGETS, Checker, Finding, LintResult, Module,
                   Project, load_project, repo_root, run_checkers)

__all__ = [
    "Checker", "Finding", "LintResult", "Module", "Project",
    "DEFAULT_TARGETS", "all_checkers", "load_project", "repo_root",
    "run_checkers", "run_lint",
]


def run_lint(root: Optional[str] = None,
             targets: Optional[Sequence[str]] = None,
             rules: Optional[Sequence[str]] = None) -> LintResult:
    """One-call entry point: load the project and run every checker."""
    project = load_project(root or repo_root(), targets)
    return run_checkers(project, all_checkers(), rules=rules)
