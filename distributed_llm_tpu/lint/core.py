"""dllm-lint core: project loading, suppressions, checker protocol.

The framework is deliberately jax-free and stdlib-only: tier-1 runs the
full suite on CPU boxes, and the AST passes must not pay (or depend on)
an accelerator-stack import.  A checker receives the whole ``Project``
(parsed modules keyed by repo-relative path) and returns ``Finding``s;
the runner applies suppression comments and the mandatory-justification
policy uniformly.

Suppression grammar (grep-able, justification REQUIRED)::

    something_flagged()   # dllm-lint: disable=<rule>[,<rule>] -- why

    # dllm-lint: disable-file=<rule> -- why          (file-scoped, any line)

A ``disable`` comment suppresses matching findings on its own line and,
when it stands alone on a line, on the next line (for statements too
long to share a line with their justification).  A suppression without
the ``-- <justification>`` tail is itself a finding
(``suppression-missing-justification``) — the whole point is that every
silenced rule carries its reviewable why inline.
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
import tokenize
from io import StringIO
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

SUPPRESS_RE = re.compile(
    r"#\s*dllm-lint:\s*(disable|disable-file)=([A-Za-z0-9_,\-]+)"
    r"(?:\s*--\s*(\S.*))?")

# ``def _loop(self):  # dllm-lint: hot-path`` (same line or the line
# above the def) marks a function as a host-transfer-discipline root:
# the transfer checker flags device syncs/round-trips in everything the
# function transitively calls, project-wide.  See DESIGN.md.
HOT_PATH_RE = re.compile(r"#\s*dllm-lint:\s*hot-path\b")

JUSTIFICATION_RULE = "suppression-missing-justification"
PARSE_RULE = "parse-error"


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    path: str            # repo-relative
    line: int
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


class Suppressions:
    """Parsed suppression comments for one module."""

    def __init__(self) -> None:
        self.by_line: Dict[int, set] = {}     # line -> {rules}
        self.file_level: set = set()
        self.malformed: List[Tuple[int, str]] = []   # (line, rules-text)
        self.hot_path_lines: set = set()      # '# dllm-lint: hot-path'

    @classmethod
    def parse(cls, source: str) -> "Suppressions":
        sup = cls()
        # tokenize (not a line regex) so a '#' inside a string literal
        # can never read as a suppression comment.
        try:
            tokens = list(tokenize.generate_tokens(StringIO(source).readline))
        except (tokenize.TokenError, IndentationError, SyntaxError):
            tokens = []
        comment_only_lines = set()
        code_lines = set()
        for tok in tokens:
            if tok.type == tokenize.COMMENT:
                continue
            if tok.type in (tokenize.NL, tokenize.NEWLINE, tokenize.INDENT,
                            tokenize.DEDENT, tokenize.ENDMARKER):
                continue
            code_lines.add(tok.start[0])
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            if HOT_PATH_RE.search(tok.string):
                sup.hot_path_lines.add(tok.start[0])
            m = SUPPRESS_RE.search(tok.string)
            if not m:
                continue
            kind, rules_text, justification = m.groups()
            line = tok.start[0]
            if line not in code_lines:
                comment_only_lines.add(line)
            if not justification:
                sup.malformed.append((line, rules_text))
                continue
            rules = {r.strip() for r in rules_text.split(",") if r.strip()}
            if kind == "disable-file":
                sup.file_level |= rules
            else:
                sup.by_line.setdefault(line, set()).update(rules)
                if line in comment_only_lines:
                    # Standalone comment: also covers the next line.
                    sup.by_line.setdefault(line + 1, set()).update(rules)
        return sup

    def covers(self, rule: str, line: int) -> bool:
        if rule in self.file_level:
            return True
        return rule in self.by_line.get(line, set())


class Module:
    """One parsed source file."""

    def __init__(self, relpath: str, source: str):
        self.relpath = relpath
        self.source = source
        self.lines = source.splitlines()
        self.parse_error: Optional[str] = None
        try:
            self.tree: Optional[ast.Module] = ast.parse(source,
                                                        filename=relpath)
        except SyntaxError as exc:
            self.tree = None
            self.parse_error = str(exc)
        self.suppressions = Suppressions.parse(source)


class Project:
    """The module set a lint run sees, keyed by repo-relative path.

    ``complete`` records whether the FULL default target set was loaded:
    absence-of-a-reader checks (config-env-stale) are only meaningful
    then — a narrowed run (``lint distributed_llm_tpu/serving``) must
    not report every knob it didn't load as dead.
    """

    def __init__(self, root: str, modules: Dict[str, Module],
                 complete: bool = True):
        self.root = root
        self.modules = modules
        self.complete = complete

    def in_dirs(self, prefixes: Sequence[str]) -> List[Module]:
        """Modules whose relpath starts with any prefix (or equals a file
        prefix exactly); prefixes use '/' separators."""
        out = []
        for rel, mod in sorted(self.modules.items()):
            for p in prefixes:
                if rel == p or rel.startswith(p.rstrip("/") + "/"):
                    out.append(mod)
                    break
        return out

    def get(self, relpath: str) -> Optional[Module]:
        return self.modules.get(relpath)


# Everything the repo-wide run parses.  tests/ stays out (fixture
# snippets deliberately contain known-bad code) except conftest.py,
# whose env reads the config-drift checker must see.
DEFAULT_TARGETS: Tuple[str, ...] = (
    "distributed_llm_tpu",
    "scripts",
    "bench.py",
    "tests/conftest.py",
)

_SKIP_DIRS = {"__pycache__", ".git", "node_modules", ".claude"}


def load_project(root: str,
                 targets: Optional[Sequence[str]] = None) -> Project:
    complete = not targets or list(targets) == list(DEFAULT_TARGETS)
    targets = list(targets) if targets else list(DEFAULT_TARGETS)
    modules: Dict[str, Module] = {}

    def add_file(abspath: str) -> bool:
        """True if the file is (now or already) part of the project."""
        rel = os.path.relpath(abspath, root).replace(os.sep, "/")
        if rel in modules:
            return True
        try:
            with open(abspath, encoding="utf-8") as f:
                modules[rel] = Module(rel, f.read())
        except OSError:
            return False
        return True

    for target in targets:
        abspath = os.path.join(root, target)
        matched = False
        if os.path.isfile(abspath) and abspath.endswith(".py"):
            matched = add_file(abspath)
        else:
            for dirpath, dirnames, filenames in os.walk(abspath):
                dirnames[:] = [d for d in dirnames if d not in _SKIP_DIRS]
                for fname in sorted(filenames):
                    if fname.endswith(".py"):
                        matched |= add_file(os.path.join(dirpath, fname))
        # A target that matched no Python files is a usage error, not a
        # clean run: a typo'd or renamed-away path in CI would otherwise
        # lint nothing and pass forever.
        if not matched:
            raise FileNotFoundError(
                f"lint target {target!r} matched no Python files "
                f"under {root}")
    return Project(root, modules, complete=complete)


class Checker:
    """Plugin API: subclass, set ``name``/``rules``, implement check().

    ``scope`` is the path-prefix set the checker examines; the runner
    passes the full project so cross-module checkers (locks, drift) can
    still see everything.

    ``whole_project`` marks checkers whose verdicts depend on the whole
    call graph or registry, not just the file a finding lands in: a
    ``--changed`` (git-diff-scoped) run auto-widens these to full
    reporting, because an edit in one file can create or cure a finding
    in another (cross-module blocking-under-lock, a knob losing its
    last reader, a hot-path callee growing a sync).
    """

    name: str = ""
    rules: Tuple[str, ...] = ()
    scope: Tuple[str, ...] = ("distributed_llm_tpu",)
    whole_project: bool = False

    def check(self, project: Project) -> List[Finding]:  # pragma: no cover
        raise NotImplementedError


@dataclasses.dataclass
class LintResult:
    findings: List[Finding]              # unsuppressed (the failures)
    suppressed: List[Tuple[Finding, str]]   # (finding, "line"|"file")

    @property
    def ok(self) -> bool:
        return not self.findings


def run_checkers(project: Project, checkers: Iterable[Checker],
                 rules: Optional[Sequence[str]] = None) -> LintResult:
    raw: List[Finding] = []
    for checker in checkers:
        raw.extend(checker.check(project))
    if rules:
        wanted = set(rules)
        raw = [f for f in raw if f.rule in wanted]

    # Policy findings from the suppression machinery itself: a
    # suppression without justification, anywhere in the project.
    for rel, mod in sorted(project.modules.items()):
        for line, rules_text in mod.suppressions.malformed:
            raw.append(Finding(
                JUSTIFICATION_RULE, rel, line,
                f"suppression for '{rules_text}' has no justification — "
                f"append ' -- <why>'"))
        if mod.parse_error is not None:
            raw.append(Finding(PARSE_RULE, rel, 1,
                               f"failed to parse: {mod.parse_error}"))

    findings: List[Finding] = []
    suppressed: List[Tuple[Finding, str]] = []
    for f in sorted(raw, key=lambda f: (f.path, f.line, f.rule)):
        mod = project.get(f.path)
        if (mod is not None and f.rule != JUSTIFICATION_RULE
                and mod.suppressions.covers(f.rule, f.line)):
            kind = ("file" if f.rule in mod.suppressions.file_level
                    else "line")
            suppressed.append((f, kind))
        else:
            findings.append(f)
    return LintResult(findings=findings, suppressed=suppressed)


def filter_changed(result: LintResult, changed: Iterable[str],
                   checkers: Iterable[Checker]) -> LintResult:
    """The ``--changed`` reporting filter: keep findings that land in a
    changed file, plus EVERY finding of a ``whole_project`` checker —
    those analyses already ran over the full project (a narrowed load
    would be unsound for them), and their findings can be caused by a
    changed file while landing in an unchanged one.  Parse errors and
    naked suppressions are never filtered either: a module that fails
    to parse is invisible to every whole-project analysis, so hiding
    its finding would report a green the graph checkers cannot back."""
    changed_set = set(changed)
    wide_rules = {r for c in checkers if c.whole_project for r in c.rules}
    wide_rules |= {PARSE_RULE, JUSTIFICATION_RULE}
    keep = [f for f in result.findings
            if f.path in changed_set or f.rule in wide_rules]
    return LintResult(findings=keep, suppressed=result.suppressed)


def repo_root() -> str:
    """The repo checkout this package sits in."""
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
