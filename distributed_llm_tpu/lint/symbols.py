"""Shared walker + symbol tables for dllm-lint checkers.

One pass over a module yields:

- every function/method (nested defs included) with a stable qualname
  (``Class.method``, ``Class.method.<locals>.worker``, ``func``),
- declared locks (``self._x = threading.Lock()`` instance attrs,
  module-level ``_lock = threading.Lock()``, and function-local
  ``state_lock = threading.Lock()``), keyed so usage sites resolve to
  the same identity,
- a module-local call graph: edges a checker can actually trust —
  ``name(...)`` to a local/module function, ``self.m(...)`` to a method
  of the same class.

On top of the per-module tables, ``ProjectSymbols`` (built once per
``Project``, cached, shared by every checker in a run) assembles the
WHOLE-PROJECT call graph: import-aware resolution of ``module.fn(...)``
(plain, dotted, and aliased imports), ``from m import fn`` (including
relative imports and one-hop re-export chains through ``__init__``
modules), ``self.method`` within a class, and ``Thread(target=...)``
worker roots whose target lives in another file.  Resolution is
strictly conservative: an edge exists only when an import chain proves
it — two modules defining the same bare name NEVER edge to each other.
Unresolvable receivers (callbacks, dispatch dicts, duck-typed objects)
stay unresolved; checkers must treat "no edge" as "unknown", not
"safe/unsafe".

Checkers layer semantics (blocking-ness, purity, guarded regions) on
top; this module only answers "what functions exist and who calls whom".
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Set, Tuple

LOCK_FACTORIES = {"Lock", "RLock", "Condition", "Semaphore",
                  "BoundedSemaphore"}


def call_name(node: ast.Call) -> str:
    """The bare called name: ``f`` for ``f(...)``/``a.b.f(...)``."""
    fn = node.func
    if isinstance(fn, ast.Attribute):
        return fn.attr
    if isinstance(fn, ast.Name):
        return fn.id
    return ""


def attr_chain(node: ast.expr) -> Optional[str]:
    """Dotted source text for Name/Attribute chains (``self._lock``,
    ``os.environ``); None for anything dynamic."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _is_lock_factory(value: ast.expr) -> bool:
    if not isinstance(value, ast.Call):
        return False
    chain = attr_chain(value.func)
    if chain is None:
        return False
    leaf = chain.rsplit(".", 1)[-1]
    return leaf in LOCK_FACTORIES


@dataclasses.dataclass
class FuncInfo:
    qualname: str
    node: ast.AST                   # FunctionDef | AsyncFunctionDef | Lambda
    class_name: Optional[str]       # nearest enclosing class
    parent: Optional[str]           # enclosing function qualname


class ModuleSymbols(ast.NodeVisitor):
    """One module's functions, locks, and call edges."""

    def __init__(self, tree: ast.Module):
        self.functions: Dict[str, FuncInfo] = {}
        # lock id -> declaration line.  Ids:
        #   "Class.self._x"  instance attr (any method of Class)
        #   "<module>.name"  module-level
        #   "<func qualname>.name"  function-local
        self.locks: Dict[str, int] = {}
        # call edges: caller qualname -> [(callee qualname | None,
        #                                  bare name, Call node)]
        self.calls: Dict[str, List[Tuple[Optional[str], str, ast.Call]]] = {}
        self._class_stack: List[str] = []
        self._func_stack: List[str] = []
        # (caller, enclosing class, node): resolution is deferred until
        # the whole module is walked — resolving mid-walk silently
        # dropped every edge to a callee defined LATER in the file.
        self._pending: List[Tuple[str, Optional[str], ast.Call]] = []
        self.visit(tree)
        for caller, cls, node in self._pending:
            callee = resolve_local_callable(
                self, caller if caller != "<module>" else None, cls,
                node.func)
            self.calls.setdefault(caller, []).append(
                (callee, call_name(node), node))
        del self._pending

    # -- scope bookkeeping -------------------------------------------------

    def _qual(self, name: str) -> str:
        if self._func_stack:
            return f"{self._func_stack[-1]}.<locals>.{name}"
        if self._class_stack:
            return f"{self._class_stack[-1]}.{name}"
        return name

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self._class_stack.append(node.name)
        self.generic_visit(node)
        self._class_stack.pop()

    def _visit_func(self, node) -> None:
        qual = self._qual(node.name)
        self.functions[qual] = FuncInfo(
            qualname=qual, node=node,
            class_name=self._class_stack[-1] if self._class_stack else None,
            parent=self._func_stack[-1] if self._func_stack else None)
        self._func_stack.append(qual)
        self.generic_visit(node)
        self._func_stack.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    # -- locks -------------------------------------------------------------

    def visit_Assign(self, node: ast.Assign) -> None:
        if _is_lock_factory(node.value):
            for target in node.targets:
                chain = attr_chain(target)
                if chain is None:
                    continue
                if chain.startswith("self.") and self._class_stack:
                    self.locks[f"{self._class_stack[-1]}.{chain}"] = \
                        node.lineno
                elif "." not in chain:
                    if self._func_stack:
                        self.locks[f"{self._func_stack[-1]}.{chain}"] = \
                            node.lineno
                    else:
                        self.locks[f"<module>.{chain}"] = node.lineno
        self.generic_visit(node)

    # -- calls -------------------------------------------------------------

    def visit_Call(self, node: ast.Call) -> None:
        caller = self._func_stack[-1] if self._func_stack else "<module>"
        self._pending.append(
            (caller,
             self._class_stack[-1] if self._class_stack else None,
             node))
        self.generic_visit(node)

    # -- queries -----------------------------------------------------------

    def resolve_lock(self, expr: ast.expr, func_qual: str,
                     class_name: Optional[str]) -> Optional[str]:
        """Map a with-item / .acquire() receiver back to a declared lock
        id, walking the enclosing-function chain for locals (closures)."""
        chain = attr_chain(expr)
        if chain is None:
            return None
        if chain.startswith("self.") and class_name:
            cand = f"{class_name}.{chain}"
            return cand if cand in self.locks else None
        if "." in chain:
            return None
        scope: Optional[str] = func_qual
        while scope:
            cand = f"{scope}.{chain}"
            if cand in self.locks:
                return cand
            info = self.functions.get(scope)
            scope = info.parent if info else None
        cand = f"<module>.{chain}"
        return cand if cand in self.locks else None

    def local_closure(self, roots: Set[str]) -> Set[str]:
        """roots + every module-local function transitively reachable
        through resolved call edges."""
        seen = set(roots)
        frontier = list(roots)
        while frontier:
            cur = frontier.pop()
            for callee, _name, _node in self.calls.get(cur, ()):
                if callee is not None and callee not in seen:
                    seen.add(callee)
                    frontier.append(callee)
        return seen


def symbols_for(module) -> Optional[ModuleSymbols]:
    """ModuleSymbols for a core.Module (None when it failed to parse),
    cached on the module object."""
    if module.tree is None:
        return None
    cached = getattr(module, "_dllm_symbols", None)
    if cached is None:
        cached = ModuleSymbols(module.tree)
        module._dllm_symbols = cached
    return cached


def resolve_local_callable(syms: ModuleSymbols, scope_qual: Optional[str],
                           class_name: Optional[str],
                           expr: ast.expr) -> Optional[str]:
    """Resolve a callable REFERENCE (not a call) in a module: a bare
    ``Name`` against the enclosing-function <locals> chain then the
    module level, or ``self.m`` against the enclosing class.  This is
    the Thread(target=...)-style resolution: strictly scoped, so a
    same-named method on an unrelated class never matches."""
    if isinstance(expr, ast.Name):
        scope = scope_qual
        while scope:
            cand = f"{scope}.<locals>.{expr.id}"
            if cand in syms.functions:
                return cand
            info = syms.functions.get(scope)
            scope = info.parent if info else None
        if expr.id in syms.functions:
            return expr.id
        return None
    if (isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Name)
            and expr.value.id == "self" and class_name):
        cand = f"{class_name}.{expr.attr}"
        if cand in syms.functions:
            return cand
    return None


# ---------------------------------------------------------------------------
# Whole-project call graph
# ---------------------------------------------------------------------------

def module_dotted_name(relpath: str) -> str:
    """``distributed_llm_tpu/serving/router.py`` ->
    ``distributed_llm_tpu.serving.router``; ``pkg/__init__.py`` ->
    ``pkg``; top-level ``bench.py`` -> ``bench``."""
    p = relpath[:-3] if relpath.endswith(".py") else relpath
    if p.endswith("/__init__"):
        p = p[: -len("/__init__")]
    return p.replace("/", ".")


class ModuleImports(ast.NodeVisitor):
    """All import bindings of one module (function-level imports
    included — the repo lazy-imports heavily; binding them module-wide
    is sound for resolution ONLY while the name binds one target: a
    name two imports bind to different targets is poisoned and never
    resolves (edge-only-when-proven — last-writer-wins would silently
    mis-edge every call site of the other import)."""

    def __init__(self, tree: ast.Module, package: str):
        # local name -> dotted module path ("import a.b as m",
        # "from a import submodule")
        self.module_aliases: Dict[str, str] = {}
        # local name -> (dotted module, attr) ("from a.b import fn")
        self.from_names: Dict[str, Tuple[str, str]] = {}
        # dotted paths reachable by their FULL dotted chain
        # ("import a.b.c" makes a.b.c.fn(...) resolvable)
        self.plain: Set[str] = set()
        self._ambiguous: Set[str] = set()
        self._package = package
        self.visit(tree)

    def _bind(self, table: Dict, local: str, target) -> None:
        if local in self._ambiguous:
            return
        for t in (self.module_aliases, self.from_names):
            prev = t.get(local)
            if prev is not None and (t is not table or prev != target):
                self._ambiguous.add(local)
                t.pop(local, None)
                return
        table[local] = target

    def visit_Import(self, node: ast.Import) -> None:
        for alias in node.names:
            if alias.asname:
                self._bind(self.module_aliases, alias.asname, alias.name)
            else:
                # ``import a.b.c`` binds ``a`` and makes every prefix
                # importable as a chain.
                parts = alias.name.split(".")
                for i in range(1, len(parts) + 1):
                    self.plain.add(".".join(parts[:i]))

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        base = node.module or ""
        if node.level:
            # Relative import: level 1 = the containing package.
            pkg_parts = self._package.split(".") if self._package else []
            keep = len(pkg_parts) - (node.level - 1)
            if keep < 0:
                return                       # beyond the project root
            prefix = ".".join(pkg_parts[:keep])
            base = f"{prefix}.{base}".rstrip(".") if base else prefix
        if not base:
            return
        for alias in node.names:
            local = alias.asname or alias.name
            if alias.name == "*":
                continue
            self._bind(self.from_names, local, (base, alias.name))


@dataclasses.dataclass
class GlobalFunc:
    gid: str                 # "<relpath>:<qualname>"
    relpath: str
    qualname: str
    info: FuncInfo


class ProjectSymbols:
    """The whole-project call graph, built once per lint run and shared
    by every graph-based checker (locks, retrace, transfer,
    thread_lifecycle).  Functions are keyed by a global id
    ``<relpath>:<qualname>``.

    Resolution rules (deliberately conservative — see DESIGN.md):

    - module-local edges come straight from ``ModuleSymbols`` (bare name
      in the enclosing scope chain, ``self.method`` on the own class);
    - ``fn(...)`` where ``fn`` was ``from m import fn``-imported edges to
      ``m:fn`` when m is a project module defining ``fn`` (one-hop
      re-exports through ``__init__`` are followed);
    - ``alias.fn(...)`` / ``pkg.mod.fn(...)`` edges through ``import``
      aliases and plain dotted imports the same way;
    - everything else (method calls on objects, callbacks, dispatch
      tables) stays unresolved — never matched by bare name.
    """

    def __init__(self, project) -> None:
        self.project = project
        self.mods: Dict[str, ModuleSymbols] = {}
        self.imports: Dict[str, ModuleImports] = {}
        self.by_name: Dict[str, str] = {}          # dotted name -> relpath
        self.functions: Dict[str, GlobalFunc] = {}
        # gid -> [(callee gid | None, bare name, Call node)]
        self.calls: Dict[str, List[Tuple[Optional[str], str, ast.Call]]] = {}
        # (relpath, id(Call node)) -> callee gid, for checkers that walk
        # bodies themselves and need per-site resolution.
        self.node_callee: Dict[Tuple[str, int], str] = {}

        for rel, mod in sorted(project.modules.items()):
            syms = symbols_for(mod)
            if syms is None:
                continue
            self.mods[rel] = syms
            dotted = module_dotted_name(rel)
            self.by_name[dotted] = rel
            package = dotted if rel.endswith("__init__.py") \
                else dotted.rsplit(".", 1)[0] if "." in dotted else ""
            self.imports[rel] = ModuleImports(mod.tree, package)
            for qual, info in syms.functions.items():
                gid = f"{rel}:{qual}"
                self.functions[gid] = GlobalFunc(gid, rel, qual, info)

        for rel, syms in self.mods.items():
            for caller, edges in syms.calls.items():
                caller_gid = f"{rel}:{caller}"
                out = self.calls.setdefault(caller_gid, [])
                info = syms.functions.get(caller)
                candidates: Optional[Dict[str, List[ast.expr]]] = None
                for local, bare, node in edges:
                    gid: Optional[str] = None
                    if local is not None:
                        gid = f"{rel}:{local}"
                    else:
                        gid = self.resolve_func_expr(rel, node.func)
                    if gid is None and isinstance(node.func, ast.Name) \
                            and info is not None:
                        # Value flow: ``op = mod.fn if c else mod.g``
                        # then ``op(...)`` — resolve every candidate the
                        # function's own scope binds to the name (the
                        # paged_kv attn-hook idiom).  Multi-valued: each
                        # resolvable candidate becomes an edge.
                        if candidates is None:
                            candidates = _value_candidates(info.node)
                        extra = []
                        for expr in candidates.get(node.func.id, ()):
                            cand = self.resolve_func_expr(rel, expr)
                            if cand is None:
                                local_cand = resolve_local_callable(
                                    syms, caller, info.class_name, expr)
                                if local_cand is not None:
                                    cand = f"{rel}:{local_cand}"
                            if cand is not None and cand not in extra:
                                extra.append(cand)
                        if extra:
                            gid = extra[0]
                            for cand in extra[1:]:
                                out.append((cand, bare, node))
                    if gid is not None:
                        self.node_callee[(rel, id(node))] = gid
                    out.append((gid, bare, node))

    # -- resolution --------------------------------------------------------

    def _module_level_func(self, rel: str, name: str,
                           _depth: int = 0) -> Optional[str]:
        """gid of module-level function ``name`` in module ``rel``,
        following re-export chains (``from .x import name`` in an
        ``__init__``) up to 4 hops."""
        syms = self.mods.get(rel)
        if syms is not None:
            info = syms.functions.get(name)
            if info is not None and info.parent is None \
                    and info.class_name is None:
                return f"{rel}:{name}"
        if _depth >= 4:
            return None
        imp = self.imports.get(rel)
        if imp is not None and name in imp.from_names:
            src_mod, src_name = imp.from_names[name]
            src_rel = self.by_name.get(src_mod)
            if src_rel is not None:
                return self._module_level_func(src_rel, src_name,
                                               _depth + 1)
        return None

    def resolve_func_expr(self, rel: str,
                          expr: ast.expr) -> Optional[str]:
        """Cross-module resolution of a function-valued expression
        (``fn`` from-imported, ``mod.fn``, ``pkg.mod.fn``) to a gid.
        Returns None for anything an import chain cannot prove."""
        imp = self.imports.get(rel)
        if imp is None:
            return None
        if isinstance(expr, ast.Name):
            entry = imp.from_names.get(expr.id)
            if entry is None:
                return None
            src_rel = self.by_name.get(entry[0])
            if src_rel is None:
                return None
            return self._module_level_func(src_rel, entry[1])
        chain = attr_chain(expr)
        if chain is None or "." not in chain:
            return None
        head, leaf = chain.rsplit(".", 1)
        modname = imp.module_aliases.get(head)
        if modname is None and head in imp.from_names:
            src_mod, src_name = imp.from_names[head]
            cand = f"{src_mod}.{src_name}"
            if cand in self.by_name:
                modname = cand                  # ``from pkg import mod``
        if modname is None and head in imp.plain:
            modname = head                      # ``import a.b.c`` chains
        if modname is None:
            return None
        target_rel = self.by_name.get(modname)
        if target_rel is None:
            return None
        return self._module_level_func(target_rel, leaf)

    # -- queries -----------------------------------------------------------

    def closure(self, roots: Set[str]) -> Set[str]:
        """roots + every function transitively reachable through
        resolved project-wide call edges."""
        seen = set(roots)
        frontier = list(roots)
        while frontier:
            cur = frontier.pop()
            for callee, _bare, _node in self.calls.get(cur, ()):
                if callee is not None and callee not in seen:
                    seen.add(callee)
                    frontier.append(callee)
        return seen

    def callee_of(self, rel: str, node: ast.Call) -> Optional[str]:
        """The resolved callee gid of a specific call site (module-local
        or cross-module), if any."""
        return self.node_callee.get((rel, id(node)))

    def thread_target_gids(self) -> Dict[str, List[Tuple[str, int]]]:
        """Every ``threading.Thread(target=X)`` whose target resolves —
        in the spawning scope (bare name / self.method, the strict local
        rules) or cross-module through imports.  Returns target gid ->
        [(spawning relpath, lineno)]."""
        out: Dict[str, List[Tuple[str, int]]] = {}
        for rel, syms in self.mods.items():
            for caller, edges in syms.calls.items():
                info = syms.functions.get(caller)
                for _callee, bare, node in edges:
                    if bare != "Thread":
                        continue
                    for kw in node.keywords:
                        if kw.arg != "target":
                            continue
                        local = resolve_local_callable(
                            syms, caller if info else None,
                            info.class_name if info else None, kw.value)
                        gid = (f"{rel}:{local}" if local is not None
                               else self.resolve_func_expr(rel, kw.value))
                        if gid is not None:
                            out.setdefault(gid, []).append(
                                (rel, node.lineno))
        return out

    # -- traced (jit) reachability -----------------------------------------

    def traced_closure(self) -> Set[str]:
        """Every function reachable, project-wide, from any jit/pjit/
        shard_map/pallas_call root in any module — the set whose bodies
        run at TRACE time.  Used by retrace to tell "pallas_call rebuilt
        inside traced code: one trace per outer compile" from "rebuilt
        per host-side call: a fresh program every time"."""
        cached = getattr(self, "_traced_closure", None)
        if cached is not None:
            return cached
        roots: Set[str] = set()
        for rel, syms in self.mods.items():
            mod = self.project.get(rel)
            quals, _lambdas = jit_roots_for(mod, syms)
            roots |= {f"{rel}:{q}" for q in quals}
        # Children of traced functions run at trace time too, even when
        # only passed as values (``jax.lax.scan(step, ...)`` never CALLS
        # ``step`` syntactically) — fixpoint over call edges + nesting.
        children: Dict[str, List[str]] = {}
        for gid, gf in self.functions.items():
            if gf.info.parent is not None:
                children.setdefault(f"{gf.relpath}:{gf.info.parent}",
                                    []).append(gid)
        closed = self.closure(roots)
        while True:
            nested = {c for gid in closed
                      for c in children.get(gid, ()) if c not in closed}
            if not nested:
                break
            closed = self.closure(closed | nested)
        self._traced_closure = closed
        return closed


def hot_path_roots(ps: ProjectSymbols) -> Set[str]:
    """gids of every function annotated ``# dllm-lint: hot-path`` (on
    the ``def`` line, the line above it, or a decorator line) — the
    transfer checker's root set, and retrace's per-request context."""
    roots: Set[str] = set()
    for rel, syms in ps.mods.items():
        mod = ps.project.get(rel)
        marked = getattr(getattr(mod, "suppressions", None),
                         "hot_path_lines", None)
        if not marked:
            continue
        for qual, info in syms.functions.items():
            node = info.node
            lines = {getattr(node, "lineno", -1),
                     getattr(node, "lineno", 0) - 1}
            for deco in getattr(node, "decorator_list", []):
                lines.add(deco.lineno)
                lines.add(deco.lineno - 1)
            if lines & marked:
                roots.add(f"{rel}:{qual}")
    return roots


def project_symbols(project) -> ProjectSymbols:
    """The ProjectSymbols for a core.Project, built once and cached on
    the project object — every graph-based checker in a run shares one
    graph (and, through ``symbols_for``, one parsed AST per file)."""
    cached = getattr(project, "_dllm_project_symbols", None)
    if cached is None:
        cached = ProjectSymbols(project)
        project._dllm_project_symbols = cached
    return cached


# ---------------------------------------------------------------------------
# jit-root discovery (shared by jit_purity and retrace)
# ---------------------------------------------------------------------------

JIT_WRAPPERS = {"jit", "pjit", "shard_map", "pallas_call"}


def wrapper_leaf(node: ast.expr) -> Optional[str]:
    """'jit' for jax.jit / jit, 'shard_map' for jax.shard_map, etc."""
    chain = attr_chain(node)
    if chain is None:
        return None
    leaf = chain.rsplit(".", 1)[-1]
    return leaf if leaf in JIT_WRAPPERS else None


def unwrap_partial(node: ast.expr) -> ast.expr:
    """partial(f, ...) -> f (functools.partial / partial)."""
    if isinstance(node, ast.Call):
        leaf = attr_chain(node.func)
        if leaf is not None and leaf.rsplit(".", 1)[-1] == "partial":
            if node.args:
                return node.args[0]
    return node


def _value_candidates(func_node) -> Dict[str, List[ast.expr]]:
    """name -> function-valued RHS expressions assigned to it in this
    function's own scope (nested defs are their own scopes).  IfExp
    branches flatten (``op = a.f if c else a.g`` yields both) and
    ``partial(f, ...)`` unwraps to ``f``."""
    out: Dict[str, List[ast.expr]] = {}

    def flatten(expr: ast.expr) -> List[ast.expr]:
        expr = unwrap_partial(expr)
        if isinstance(expr, ast.IfExp):
            return flatten(expr.body) + flatten(expr.orelse)
        if isinstance(expr, (ast.Name, ast.Attribute)):
            return [expr]
        return []

    stack = list(getattr(func_node, "body", []))
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda)):
            continue
        if (isinstance(n, ast.Assign) and len(n.targets) == 1
                and isinstance(n.targets[0], ast.Name)):
            out.setdefault(n.targets[0].id, []).extend(flatten(n.value))
        stack.extend(ast.iter_child_nodes(n))
    return out


def _scope_assignments(scope_node) -> Dict[str, Set[str]]:
    """name -> function names bound to it in this scope only (nested
    function/lambda bodies are their own scopes)."""
    out: Dict[str, Set[str]] = {}
    stack = list(getattr(scope_node, "body", []))
    while stack:
        n = stack.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                          ast.Lambda)):
            continue
        if (isinstance(n, ast.Assign) and len(n.targets) == 1
                and isinstance(n.targets[0], ast.Name)):
            value = unwrap_partial(n.value)
            if isinstance(value, ast.Name):
                out.setdefault(n.targets[0].id, set()).add(value.id)
        stack.extend(ast.iter_child_nodes(n))
    return out


def jit_roots_for(module, syms: ModuleSymbols
                  ) -> Tuple[Set[str], List[ast.Lambda]]:
    """All JIT ROOT qualnames of a module (decorated with jit/pjit/
    shard_map — directly or through partial — or passed as the function
    argument of a wrapper call, including the ``kernel = partial(_k,
    ...)`` then ``pl.pallas_call(kernel, ...)`` idiom, resolved in the
    call's own enclosing scope), plus lambda roots.  Cached on the
    module object: jit_purity and retrace share one discovery pass."""
    cached = getattr(module, "_dllm_jit_roots", None)
    if cached is not None:
        return cached

    roots: Set[str] = set()
    lambda_roots: List[ast.Lambda] = []

    for qual, info in syms.functions.items():
        node = info.node
        for deco in getattr(node, "decorator_list", []):
            target = deco
            if isinstance(deco, ast.Call):
                if wrapper_leaf(deco.func) is not None:
                    roots.add(qual)
                    continue
                chain = attr_chain(deco.func)
                if (chain is not None
                        and chain.rsplit(".", 1)[-1] == "partial"
                        and deco.args
                        and wrapper_leaf(deco.args[0]) is not None):
                    roots.add(qual)
                    continue
            if wrapper_leaf(target) is not None:
                roots.add(qual)

    module_assigned = _scope_assignments(module.tree)
    scopes = [(module.tree, module_assigned)]
    scopes += [(info.node, _scope_assignments(info.node))
               for info in syms.functions.values()
               if hasattr(info.node, "body")]
    for scope_node, assigned in scopes:
        stack = list(getattr(scope_node, "body", []))
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue          # nested defs are their own entry
            # Lambdas are NOT scope entries: keep walking their bodies,
            # or a jit/pallas_call issued inside one would escape.
            stack.extend(ast.iter_child_nodes(node))
            if (not isinstance(node, ast.Call)
                    or wrapper_leaf(node.func) is None
                    or not node.args):
                continue
            target = unwrap_partial(node.args[0])
            if isinstance(target, ast.Lambda):
                lambda_roots.append(target)
            elif isinstance(target, ast.Name):
                names = ({target.id}
                         | assigned.get(target.id, set())
                         | module_assigned.get(target.id, set()))
                for qual in syms.functions:
                    if any(qual == n or qual.endswith(f"<locals>.{n}")
                           for n in names):
                        roots.add(qual)

    result = (roots, lambda_roots)
    module._dllm_jit_roots = result
    return result
