"""Shared walker + symbol table for dllm-lint checkers.

One pass over a module yields:

- every function/method (nested defs included) with a stable qualname
  (``Class.method``, ``Class.method.<locals>.worker``, ``func``),
- declared locks (``self._x = threading.Lock()`` instance attrs,
  module-level ``_lock = threading.Lock()``, and function-local
  ``state_lock = threading.Lock()``), keyed so usage sites resolve to
  the same identity,
- a module-local call graph: edges a checker can actually trust —
  ``name(...)`` to a local/module function, ``self.m(...)`` to a method
  of the same class — plus the bare called-name for set-membership
  heuristics (cross-module calls are matched by NAME, never resolved).

Checkers layer semantics (blocking-ness, purity, guarded regions) on
top; this module only answers "what functions exist and who calls whom".
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Set, Tuple

LOCK_FACTORIES = {"Lock", "RLock", "Condition", "Semaphore",
                  "BoundedSemaphore"}


def call_name(node: ast.Call) -> str:
    """The bare called name: ``f`` for ``f(...)``/``a.b.f(...)``."""
    fn = node.func
    if isinstance(fn, ast.Attribute):
        return fn.attr
    if isinstance(fn, ast.Name):
        return fn.id
    return ""


def attr_chain(node: ast.expr) -> Optional[str]:
    """Dotted source text for Name/Attribute chains (``self._lock``,
    ``os.environ``); None for anything dynamic."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _is_lock_factory(value: ast.expr) -> bool:
    if not isinstance(value, ast.Call):
        return False
    chain = attr_chain(value.func)
    if chain is None:
        return False
    leaf = chain.rsplit(".", 1)[-1]
    return leaf in LOCK_FACTORIES


@dataclasses.dataclass
class FuncInfo:
    qualname: str
    node: ast.AST                   # FunctionDef | AsyncFunctionDef | Lambda
    class_name: Optional[str]       # nearest enclosing class
    parent: Optional[str]           # enclosing function qualname


class ModuleSymbols(ast.NodeVisitor):
    """One module's functions, locks, and call edges."""

    def __init__(self, tree: ast.Module):
        self.functions: Dict[str, FuncInfo] = {}
        # lock id -> declaration line.  Ids:
        #   "Class.self._x"  instance attr (any method of Class)
        #   "<module>.name"  module-level
        #   "<func qualname>.name"  function-local
        self.locks: Dict[str, int] = {}
        # call edges: caller qualname -> [(callee qualname | None,
        #                                  bare name, Call node)]
        self.calls: Dict[str, List[Tuple[Optional[str], str, ast.Call]]] = {}
        self._class_stack: List[str] = []
        self._func_stack: List[str] = []
        self.visit(tree)

    # -- scope bookkeeping -------------------------------------------------

    def _qual(self, name: str) -> str:
        if self._func_stack:
            return f"{self._func_stack[-1]}.<locals>.{name}"
        if self._class_stack:
            return f"{self._class_stack[-1]}.{name}"
        return name

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self._class_stack.append(node.name)
        self.generic_visit(node)
        self._class_stack.pop()

    def _visit_func(self, node) -> None:
        qual = self._qual(node.name)
        self.functions[qual] = FuncInfo(
            qualname=qual, node=node,
            class_name=self._class_stack[-1] if self._class_stack else None,
            parent=self._func_stack[-1] if self._func_stack else None)
        self._func_stack.append(qual)
        self.generic_visit(node)
        self._func_stack.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    # -- locks -------------------------------------------------------------

    def visit_Assign(self, node: ast.Assign) -> None:
        if _is_lock_factory(node.value):
            for target in node.targets:
                chain = attr_chain(target)
                if chain is None:
                    continue
                if chain.startswith("self.") and self._class_stack:
                    self.locks[f"{self._class_stack[-1]}.{chain}"] = \
                        node.lineno
                elif "." not in chain:
                    if self._func_stack:
                        self.locks[f"{self._func_stack[-1]}.{chain}"] = \
                            node.lineno
                    else:
                        self.locks[f"<module>.{chain}"] = node.lineno
        self.generic_visit(node)

    # -- calls -------------------------------------------------------------

    def visit_Call(self, node: ast.Call) -> None:
        caller = self._func_stack[-1] if self._func_stack else "<module>"
        callee = self._resolve(node)
        self.calls.setdefault(caller, []).append(
            (callee, call_name(node), node))
        self.generic_visit(node)

    def _resolve(self, node: ast.Call) -> Optional[str]:
        fn = node.func
        if isinstance(fn, ast.Name):
            # Nearest enclosing <locals> def, else module-level.
            for enclosing in reversed(self._func_stack):
                cand = f"{enclosing}.<locals>.{fn.id}"
                if cand in self.functions:
                    return cand
            if fn.id in self.functions:
                return fn.id
            return None
        if (isinstance(fn, ast.Attribute) and isinstance(fn.value, ast.Name)
                and fn.value.id == "self" and self._class_stack):
            cand = f"{self._class_stack[-1]}.{fn.attr}"
            if cand in self.functions:
                return cand
        return None

    # -- queries -----------------------------------------------------------

    def resolve_lock(self, expr: ast.expr, func_qual: str,
                     class_name: Optional[str]) -> Optional[str]:
        """Map a with-item / .acquire() receiver back to a declared lock
        id, walking the enclosing-function chain for locals (closures)."""
        chain = attr_chain(expr)
        if chain is None:
            return None
        if chain.startswith("self.") and class_name:
            cand = f"{class_name}.{chain}"
            return cand if cand in self.locks else None
        if "." in chain:
            return None
        scope: Optional[str] = func_qual
        while scope:
            cand = f"{scope}.{chain}"
            if cand in self.locks:
                return cand
            info = self.functions.get(scope)
            scope = info.parent if info else None
        cand = f"<module>.{chain}"
        return cand if cand in self.locks else None

    def local_closure(self, roots: Set[str]) -> Set[str]:
        """roots + every module-local function transitively reachable
        through resolved call edges."""
        seen = set(roots)
        frontier = list(roots)
        while frontier:
            cur = frontier.pop()
            for callee, _name, _node in self.calls.get(cur, ()):
                if callee is not None and callee not in seen:
                    seen.add(callee)
                    frontier.append(callee)
        return seen


def symbols_for(module) -> Optional[ModuleSymbols]:
    """ModuleSymbols for a core.Module (None when it failed to parse),
    cached on the module object."""
    if module.tree is None:
        return None
    cached = getattr(module, "_dllm_symbols", None)
    if cached is None:
        cached = ModuleSymbols(module.tree)
        module._dllm_symbols = cached
    return cached
