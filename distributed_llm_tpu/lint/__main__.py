"""CLI: ``python -m distributed_llm_tpu.lint [targets...] [options]``.

Exit 0 = zero unsuppressed findings; 1 = findings; 2 = usage error.
Runs without jax (pure AST passes) so it is safe on any CPU box and
cheap enough for tier-1 (tests/test_lint.py) and pre-commit hooks
(scripts/lint.sh).
"""

from __future__ import annotations

import argparse
import sys

from . import all_checkers, run_lint


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m distributed_llm_tpu.lint",
        description="dllm-lint: repo static-analysis suite")
    parser.add_argument("targets", nargs="*",
                        help="files/dirs relative to the repo root "
                             "(default: the standard project set)")
    parser.add_argument("--rule", action="append", dest="rules",
                        metavar="RULE",
                        help="only report these rule ids (repeatable)")
    parser.add_argument("--list-rules", action="store_true",
                        help="list checkers and rule ids, then exit")
    parser.add_argument("--show-suppressed", action="store_true",
                        help="also print suppressed findings")
    args = parser.parse_args(argv)

    if args.list_rules:
        for checker in all_checkers():
            print(f"{checker.name}:")
            for rule in checker.rules:
                print(f"  {rule}")
            print(f"  scope: {', '.join(checker.scope)}")
        return 0

    try:
        result = run_lint(targets=args.targets or None, rules=args.rules)
    except FileNotFoundError as exc:
        print(f"dllm-lint: {exc}", file=sys.stderr)
        return 2
    for finding in result.findings:
        print(finding.render())
    if args.show_suppressed:
        for finding, kind in result.suppressed:
            print(f"[suppressed:{kind}] {finding.render()}")
    n, s = len(result.findings), len(result.suppressed)
    print(f"dllm-lint: {n} finding(s), {s} suppressed")
    return 0 if result.ok else 1


if __name__ == "__main__":
    sys.exit(main())
