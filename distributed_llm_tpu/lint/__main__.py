"""CLI: ``python -m distributed_llm_tpu.lint [targets...] [options]``.

Exit 0 = zero unsuppressed findings; 1 = findings; 2 = usage error.
Runs without jax (pure AST passes) so it is safe on any CPU box and
cheap enough for tier-1 (tests/test_lint.py) and pre-commit hooks
(scripts/lint.sh).

``--changed`` scopes REPORTING to files changed vs the git ref in
``DLLM_LINT_CHANGED`` (default HEAD: working tree + index) plus
untracked files.  The ANALYSIS still loads the full project — the
call-graph checkers are only sound over the whole graph — and
whole-project checkers (locks, retrace, transfer, thread_lifecycle,
config_drift) auto-widen to full reporting, because an edit in one
file can create a finding in another.
"""

from __future__ import annotations

import argparse
import json
import subprocess
import sys

from . import all_checkers, run_lint
from .core import filter_changed, repo_root
from ..config_registry import env_str


def _git_changed_files(root: str, base: str):
    """Changed + untracked .py files, repo-relative with '/' seps.
    Returns None when git itself is unusable (not a repo, no base)."""
    def run(*args):
        return subprocess.run(
            ["git", *args], cwd=root, capture_output=True, text=True,
            timeout=30)

    try:
        diff = run("diff", "--name-only", base, "--")
        if diff.returncode != 0:
            return None
        untracked = run("ls-files", "--others", "--exclude-standard")
    except (OSError, subprocess.SubprocessError):
        # No git binary / hung git: unusable, same as a failed diff.
        return None
    names = diff.stdout.splitlines()
    if untracked.returncode == 0:
        names += untracked.stdout.splitlines()
    return sorted({n.strip() for n in names
                   if n.strip().endswith(".py")})


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m distributed_llm_tpu.lint",
        description="dllm-lint: repo static-analysis suite")
    parser.add_argument("targets", nargs="*",
                        help="files/dirs relative to the repo root "
                             "(default: the standard project set)")
    parser.add_argument("--rule", action="append", dest="rules",
                        metavar="RULE",
                        help="only report these rule ids (repeatable)")
    parser.add_argument("--changed", action="store_true",
                        help="report only findings in files changed vs "
                             "$DLLM_LINT_CHANGED (default HEAD); "
                             "whole-project checkers still report "
                             "everywhere")
    parser.add_argument("--list-rules", action="store_true",
                        help="list checkers and rule ids, then exit")
    parser.add_argument("--show-suppressed", action="store_true",
                        help="also print suppressed findings")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="machine-readable output: a JSON object "
                             "with a stable per-finding schema (rule, "
                             "path, line, message, suppressed) so CI "
                             "and bench tooling can diff finding sets "
                             "across rounds; exit codes unchanged")
    args = parser.parse_args(argv)

    if args.list_rules:
        for checker in all_checkers():
            print(f"{checker.name}:"
                  + (" [whole-project]" if checker.whole_project else ""))
            for rule in checker.rules:
                print(f"  {rule}")
            print(f"  scope: {', '.join(checker.scope)}")
        return 0

    changed = None
    if args.changed:
        if args.targets:
            print("dllm-lint: --changed and explicit targets are "
                  "mutually exclusive", file=sys.stderr)
            return 2
        base = env_str("DLLM_LINT_CHANGED", "HEAD") or "HEAD"
        changed = _git_changed_files(repo_root(), base)
        if changed is None:
            print(f"dllm-lint: git diff against {base!r} failed — "
                  f"running the full project instead", file=sys.stderr)
        elif not changed:
            print(f"dllm-lint: no Python files changed vs {base} — "
                  f"nothing to lint")
            return 0

    try:
        # --changed still LOADS the full project: graph soundness.
        result = run_lint(targets=args.targets or None, rules=args.rules)
    except FileNotFoundError as exc:
        print(f"dllm-lint: {exc}", file=sys.stderr)
        return 2
    if changed:
        result = filter_changed(result, changed, all_checkers())
    if args.as_json:
        # Stable schema — additions only, never renames: tooling diffs
        # finding sets across lint versions.  Suppressed findings are
        # ALWAYS included (flagged), so a suppression shows up in the
        # diff the same round it lands.
        payload = {
            "findings": [
                {"rule": f.rule, "path": f.path, "line": f.line,
                 "message": f.message, "suppressed": False}
                for f in result.findings
            ] + [
                {"rule": f.rule, "path": f.path, "line": f.line,
                 "message": f.message, "suppressed": True}
                for f, _kind in result.suppressed
            ],
            "counts": {"findings": len(result.findings),
                       "suppressed": len(result.suppressed)},
            "ok": result.ok,
        }
        json.dump(payload, sys.stdout, indent=2, sort_keys=True)
        sys.stdout.write("\n")
        return 0 if result.ok else 1
    for finding in result.findings:
        print(finding.render())
    if args.show_suppressed:
        for finding, kind in result.suppressed:
            print(f"[suppressed:{kind}] {finding.render()}")
    n, s = len(result.findings), len(result.suppressed)
    mode = f" ({len(changed)} changed file(s))" if changed else ""
    print(f"dllm-lint: {n} finding(s), {s} suppressed{mode}")
    return 0 if result.ok else 1


if __name__ == "__main__":
    sys.exit(main())
