"""Typed configuration for the whole framework.

The reference scatters configuration across plain dicts
(src/query_router_engine.py:704-731 BENCHMARK_CFG / PRODUCTION_CFG,
src/query_router_engine.py:517-553 QueryRouter._default_config, and call-site
overrides in src/app.py:9-14).  We keep the *same key names* — the benchmark
harness and Flask app pass them through verbatim — but add typed dataclasses
for everything the reference hard-codes (device endpoints, model choice, TPU
topology), so one config module covers router + engine + mesh.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

# =============================================================================
# Router-level canonical configs (reference parity)
# =============================================================================

# Semantic-cache similarity thresholds, calibrated per embedder (see the
# rationale comment at their use in PRODUCTION_CFG below).  The hashed
# value survives for the no-artifact fallback path and the r1-r3 tests.
DEFAULT_CACHE_SIMILARITY = 0.40        # hashed-ngram scale
HYBRID_CACHE_SIMILARITY = 0.17         # hybrid lexical⊕semantic scale
                                       # (α=0.35; held-out calibration:
                                       # paraphrase hit rate 0.957, false
                                       # hit 0.040 — encoder_train.py)

# Benchmark: routing cache OFF so accuracy is measured cleanly per query
# (reference: src/query_router_engine.py:704-719).
BENCHMARK_CFG: Dict[str, Any] = {
    "token_threshold": 1000,
    "model": "tpu-native-bpe-4k",              # tokenizer identity, see engine/bpe.py
    # Hybrid lexical⊕semantic embedder (routing/embedder.py
    # HybridEmbedder: contrastive-trained encoder ⊕ hashed n-grams) —
    # the in-repo stand-in for the reference's MiniLM (r4; falls back to
    # the r1-r3 hashed n-grams when no weights artifact exists).
    # Measured: centroid-routing accuracy 29/32 across all three query
    # sets (hashed alone 28/32), held-out paraphrase/unrelated
    # separation 0.963 (encoder alone 0.88, hashed alone 0.92).
    "embedding_model": "hybrid-lexsem-v1",
    "semantic_label_path": "",                 # resolved lazily to bench/semantic_labels.json
    "semantic_margin_threshold": 0.03,
    # Hybrid-scale "irrelevant" floor: trained cosines sit near 0 for
    # unrelated text and go NEGATIVE for anti-related; only a query below
    # both centroids by this much falls back to token routing.  (The
    # hashed scale used +0.05; with the trained component that misrouted
    # real multi-part questions whose embedding is near-orthogonal to
    # both centroids.)
    "semantic_min_similarity": -0.05,
    "heuristic_long_chars": 800,               # ~200 tokens
    "heuristic_multi_qmarks": 2,
    "heuristic_code_markers_needed": 2,
    "heuristic_context_chars": 3200,           # ~800 tokens — nano-tier sweet spot
    "weights": {"token": 0.25, "semantic": 0.45, "heuristic": 0.30},
    "cache_enabled": False,
    "perf_window": 30,
    "perf_fail_penalty": 3000.0,
}

# Production: predictive routing cache + response cache ON
# (reference: src/query_router_engine.py:722-731).
PRODUCTION_CFG: Dict[str, Any] = {
    **BENCHMARK_CFG,
    "cache_enabled": True,
    "cache_ttl_seconds": 3600,
    "cache_max_size": 500,
    # Reference value is 0.85, tuned to MiniLM embeddings
    # (src/query_router_engine.py:727).  The hybrid space scores
    # held-out paraphrases ≥0.21 at p10 and unrelated pairs ≤0.12 at
    # p90, so 0.17 keeps the reference's *behavior*: paraphrases hit —
    # including disjoint-wording ones the r1-r3 hashed embedder missed
    # (hit rate 0.957) — and unrelated queries miss (false-hit 0.040).
    # Residual false hits are acceptable because this cache stores
    # ROUTING predictions, not responses (the response cache keys
    # exactly, serving/router.py): a false hit can only predict a
    # device, and the low-confidence + heavy-context overrides
    # (routing/engine.py) re-route the residue.  (Hashed fallback
    # sessions re-calibrate to DEFAULT_CACHE_SIMILARITY via
    # routing/engine.py when no encoder artifact exists.)
    "cache_similarity_threshold": HYBRID_CACHE_SIMILARITY,
    "use_semantic_cache": True,
    "prediction_confidence_threshold": 0.70,
    "enable_response_cache": True,
    # Prefix-affinity routing (beyond-reference, serving/router.py):
    # steer LOW-confidence decisions to the tier already holding this
    # conversation's parked KV prefix — a cold re-prefill elsewhere
    # throws away an O(history) cache.  Production only (absent from
    # BENCHMARK_CFG): labeled-accuracy benchmarks keep reference routing
    # semantics.
    "enable_prefix_affinity": True,
    "prefix_affinity_min_confidence": 0.75,
    "prefix_affinity_min_tokens": 32,
    # Perf-strategy exploration (beyond-reference, production only): the
    # reference's perf router never probes a tier it has no samples for
    # (src/query_router_engine.py:449-451 scores an empty history as
    # +inf), so the idle tier stays idle forever and warming can never
    # change its decisions.  In production we deterministically probe a
    # tier whose samples are missing or stale (no sample in the last
    # perf_explore_interval routed queries) so both score terms stay
    # live.  Absent from BENCHMARK_CFG: benchmarks keep the reference's
    # exact never-explore semantics (PARITY.md).
    "perf_explore": True,
    "perf_explore_interval": 16,
    # Queue-aware perf routing (beyond-reference, production only): the
    # Router feeds each tier's live load (admission queue depth + batch
    # slot occupancy, serving/tiers.py) into the perf strategy before
    # every decision, and the score adds perf_queue_penalty_ms per unit
    # of load — so a saturated tier sheds quality-equivalent traffic to
    # an idle one instead of stacking its queue until requests time out.
    # On a multi-host mesh the load rows ride the same ICI health
    # allgather as the perf windows (serving/health.py); locally the
    # signal is in-process counters.  Absent from BENCHMARK_CFG: the
    # labeled-accuracy benchmarks keep the reference's pure
    # latency-per-token scoring.
    "perf_queue_aware": True,
    "perf_queue_penalty_ms": 50.0,
}


# =============================================================================
# Model architecture presets
# =============================================================================

@dataclasses.dataclass(frozen=True)
class ModelConfig:
    """LLaMA-style decoder-only transformer hyperparameters."""

    name: str
    # Tokenizer scheme + matching vocabulary size.  "bpe" = the trained
    # subword artifact (engine/bpe.py, vocab 4096 — ~3.5 chars/token on
    # the bench queries, so ~3.5× fewer decode steps per word of text
    # than byte-level; VERDICT r2 #3); "byte" = the self-contained
    # fallback (vocab 512).  engine.tokenizer.get_tokenizer validates
    # the pair.
    tokenizer: str = "bpe"
    vocab_size: int = 4096
    hidden_size: int = 2048
    num_layers: int = 16
    num_heads: int = 16
    num_kv_heads: int = 8          # grouped-query attention
    ffn_size: int = 5632
    max_seq_len: int = 4096
    rope_theta: float = 10000.0
    norm_eps: float = 1e-5
    dtype: str = "bfloat16"
    # "auto" | "pallas" | "xla": attention kernel choice.  auto = the
    # GSPMD-shardable XLA path (safe under any mesh); unsharded serving
    # engines upgrade auto to the Pallas flash kernels on TPU
    # (engine/inference.py, ops/attention.py resolve_impl).
    attention_impl: str = "auto"
    # Mixture-of-Experts (models/moe.py): >1 replaces the dense FFN with
    # top-2 routed experts sharded over the mesh's 'ep' axis.
    num_experts: int = 1
    moe_capacity_factor: float = 1.25
    moe_aux_weight: float = 0.01

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    def param_count(self) -> int:
        """Approximate parameter count (embeddings counted once, tied head)."""
        h, f, l, v = self.hidden_size, self.ffn_size, self.num_layers, self.vocab_size
        kv = self.num_kv_heads * self.head_dim
        attn = h * h + 2 * h * kv + h * h          # q, k, v, o
        mlp = 3 * h * f                            # gate, up, down
        norms = 2 * h * l + h
        return v * h + l * (attn + mlp) + norms


# Tier presets.  The "full" presets mirror the north star (1B vs 8B class);
# the "bench" presets are sized so both tiers fit one v5e chip (16 GB HBM)
# at the same time, since the driver benches on a single real chip.  The
# "test" presets keep CPU-mesh unit tests fast.
MODEL_PRESETS: Dict[str, ModelConfig] = {
    "nano_1b": ModelConfig(
        name="nano_1b", hidden_size=2048, num_layers=16, num_heads=32,
        num_kv_heads=8, ffn_size=8192, max_seq_len=8192,
    ),
    "orin_8b": ModelConfig(
        name="orin_8b", hidden_size=4096, num_layers=32, num_heads=32,
        num_kv_heads=8, ffn_size=14336, max_seq_len=8192,
    ),
    "nano_bench": ModelConfig(
        name="nano_bench", hidden_size=1024, num_layers=8, num_heads=16,
        num_kv_heads=8, ffn_size=4096, max_seq_len=2048,
    ),
    "orin_bench": ModelConfig(
        name="orin_bench", hidden_size=2048, num_layers=16, num_heads=16,
        num_kv_heads=8, ffn_size=8192, max_seq_len=2048,
    ),
    # Sized so ONE host CPU core can pretrain it to a plateau in ~1 h:
    # the weak half of the cpu_bench pair (see cpu_bench_cluster), giving
    # the chipless fallback bench a genuinely quality-asymmetric cluster.
    "mini_bench": ModelConfig(
        name="mini_bench", hidden_size=512, num_layers=6, num_heads=8,
        num_kv_heads=4, ffn_size=2048, max_seq_len=2048,
    ),
    "nano_test": ModelConfig(
        name="nano_test", hidden_size=64, num_layers=2, num_heads=4,
        num_kv_heads=2, ffn_size=128, max_seq_len=256,
    ),
    # Speculative DRAFT for the test/trend tiers (ISSUE 15): ~1/8 of
    # nano_test's per-step compute at the same vocab/context, so the
    # batched spec leg and the unit suite exercise a genuinely
    # cheaper-draft configuration on CPU.
    "draft_test": ModelConfig(
        name="draft_test", hidden_size=32, num_layers=1, num_heads=4,
        num_kv_heads=2, ffn_size=64, max_seq_len=256,
    ),
    "moe_test": ModelConfig(
        name="moe_test", hidden_size=64, num_layers=2, num_heads=4,
        num_kv_heads=2, ffn_size=128, max_seq_len=256, num_experts=4,
    ),
    "moe_8x1b": ModelConfig(
        name="moe_8x1b", hidden_size=2048, num_layers=16, num_heads=32,
        num_kv_heads=8, ffn_size=8192, max_seq_len=8192, num_experts=8,
    ),
    "orin_test": ModelConfig(
        name="orin_test", hidden_size=128, num_layers=2, num_heads=8,
        num_kv_heads=4, ffn_size=256, max_seq_len=256,
    ),
}


# =============================================================================
# Tier / topology configuration
# =============================================================================

@dataclasses.dataclass(frozen=True)
class TenantQuota:
    """Per-tenant isolation budgets (serving/tenants.py, ISSUE 17).

    All limits are per TIER (each TierClient owns one TenantQuotas
    registry).  ``None`` on any field disables that criterion for the
    tenant; a tenant absent from ``TierConfig.tenant_quotas`` gets the
    registry's default quota (the ``DLLM_TENANT_*`` env defaults, or
    unlimited when those are unset too).
    """

    # DWRR scheduling weight (engine/batching.py): a tenant with weight
    # 2 drains its admission queue twice as fast as a weight-1 tenant
    # under contention.  Must be > 0.
    weight: float = 1.0
    # Requests a tenant may have in flight (admitted, occupying engine
    # capacity) at once; the next one queues against max_queued.
    max_inflight: Optional[int] = None
    # Requests a tenant may have WAITING beyond max_inflight before
    # admission rejects with the reference error shape + retry_after_s.
    max_queued: Optional[int] = None
    # Device-time rate budget in measured milliseconds per wall second,
    # enforced by a token bucket debited from each finished request's
    # PR 11 ``device_time_ms`` bill: a tenant that burned more device
    # time than its rate allows is rejected until the bucket refills.
    device_ms_per_s: Optional[float] = None
    # Burst ceiling of that token bucket in device-milliseconds; None
    # defaults to 2 s worth of the rate.
    device_ms_burst: Optional[float] = None
    # Resident KV budget in physical refcounted blocks, billed at
    # 1/refcount per block (PR 10 dedup lowers the bill): over it, the
    # tenant's parked prefixes evict first and its COLD admissions are
    # gated by the PR 5 KV-aware gate until the bill drops.
    kv_blocks: Optional[int] = None
    # Per-tenant speculative γ cap: PR 14's per-slot EWMA γ clamps to
    # this, so one tenant's speculation cannot monopolize draft/verify
    # rounds.  None = the tier's spec_gamma_max.
    spec_gamma_max: Optional[int] = None


@dataclasses.dataclass(frozen=True)
class TierConfig:
    """One serving tier = one model resident on one device submesh.

    Replaces the reference's hard-coded device endpoints
    (src/models/nano.py:4-8, src/models/orin.py:6-10): instead of
    ip/port/tunnel-port, a tier is defined by its model preset and the shape
    of the chip submesh it owns.
    """

    name: str                       # "nano" | "orin" | ...
    model_preset: str               # key into MODEL_PRESETS
    tp: int = 1                     # tensor-parallel degree (submesh size)
    # Sequence-parallel degree for PREFILL: sp>1 makes the tier submesh 2-D
    # ('sp','tp') and the prefill runs ring attention over the sp axis
    # (parallel/ring_attention.py) with activations sequence-sharded, so a
    # long prompt's O(S²) attention spreads over sp chips.  Decode and the
    # KV cache stay sharded on tp only (sequence replicated) — decode is
    # bandwidth-bound on weights, not attention FLOPs.  Dense models only.
    sp: int = 1
    # Expert-parallel degree for MoE tiers: ep>1 makes the submesh
    # ('ep','tp') and shards WHOLE experts over it (the serving twin of
    # the trainer's ep axis — parallel/sharding.py param_specs maps
    # stacked expert weights [L,E,...] onto 'ep').  GSPMD inserts the
    # dispatch collectives; attention/caches stay on 'tp'.  Dense models
    # ignore it.
    ep: int = 1
    # Per-chip HBM residency budget in GB (utils/hbm_budget.py).  When
    # set, EngineManager.start_server budgets params + KV against the
    # tier's DEPLOYED submesh before building the engine and refuses
    # cleanly (TierOverCapacityError) when the footprint doesn't fit —
    # the tp=1-vs-tp=2 capacity demonstration in bench.py's multichip
    # leg rides this.  None (the default) keeps the historical behavior:
    # no admission-time budget, OOM surfaces wherever XLA hits it.
    hbm_gb_per_chip: Optional[float] = None
    max_new_tokens: int = 256       # decode cap (reference: num_predict, -1=unbounded)
    temperature: float = 0.0        # greedy by default (src/devices/nano_api.py:21)
    prefill_buckets: Tuple[int, ...] = (64, 128, 256, 512, 1024, 2048)
    # decode_batch > 1 turns on the continuous-batching engine (that many
    # concurrent sequences share one compiled decode step); kv_block_size is
    # its paged KV pool's block granularity (engine/batching.py, paged_kv.py).
    # decode_steps_per_tick batches that many sequential decode steps into
    # ONE device call per scheduler tick, amortizing the host↔device round
    # trip; costs ≤T-1 wasted steps per finishing request and delays new
    # admissions by <T steps.  Serving clusters default decode_batch > 1
    # (concurrent-by-default: the shipped presets set nano=8 / orin=4
    # slots); the dataclass default stays 1 so directly-constructed test
    # tiers keep the sequential engine, and requesting decode_batch=1 is
    # the documented opt-out back to it.  Speculative tiers
    # (draft_preset) always serve sequentially — EngineManager falls back
    # and logs when both are configured.
    decode_batch: int = 1
    kv_block_size: int = 64
    decode_steps_per_tick: int = 4
    # Ragged paged decode (ops/ragged_attention.py): the batched engine's
    # decode tick issues ONE fused attention call over every slot's FULL
    # block-table row with true per-slot lengths, instead of slicing the
    # tables to a bucketed window rung shared across the batch.  One
    # compiled decode program serves the engine's whole life (the rung
    # ladder minted one per (bucket, window) pair), the host stops
    # re-uploading sliced tables every tick, and on TPU the Pallas kernel
    # streams each slot's own frontier so length skew costs per-slot
    # work, not the batch max.  On a ('batch','tp') tier mesh the fused
    # tick runs UNDER shard_map over the kv-head axis (PR 16,
    # parallel/tp_attention.tp_ragged_decode_attn) when the mesh
    # qualifies — dense model, sp=ep=1, tp divides both head counts
    # (parallel/tp_attention._tp_ragged_ok); non-qualifying meshes keep
    # the dense windowed path.  On TPU the request is
    # additionally GATED by the measured dispatch verdict: while
    # ab_dispatch.json still says 'xla' for ragged_decode (the
    # conservative pre-measure rows), the engine keeps the dense
    # windowed tick — the fused XLA fallback's full-span gather is not
    # measured-better there; an on-chip A/B flipping the row to 'pallas'
    # flips the engine with no code change
    # (ContinuousBatchingEngine._resolve_ragged).  DLLM_RAGGED=0/1
    # forces the TICK SHAPE (fused vs windowed) past everything but the
    # mesh rule; the KERNEL inside the fused tick stays the table's
    # measured choice (DLLM_ATTENTION overrides that separately).
    attention_ragged: bool = True
    # Disaggregated chunked prefill (engine/batching.py): a cold
    # admission whose prompt bucket exceeds this many tokens no longer
    # prefills in ONE monolithic compiled call on the scheduler thread
    # (which froze every active decode slot for the whole prompt —
    # BENCHMARKS.md r6's concurrency ceiling).  Instead the prompt is
    # split into fixed chunks of this size and the scheduler interleaves
    # them with decode ticks (chunk_prefill_paged writes each chunk's
    # K/V straight into the slot's pool blocks), so time-between-tokens
    # for in-flight streams is bounded by ONE CHUNK of prefill work
    # instead of one whole prompt.  Must be a multiple of kv_block_size
    # (chunks page evenly); the compiled chunk-program family is keyed
    # only by (chunk, window-rung) so it stays bounded regardless of
    # prompt length.  Prompts that fit a single chunk keep the
    # monolithic path — they already meet the TBT bound.  0/None
    # disables chunking (every admission prefills in one shot).
    prefill_chunk_tokens: Optional[int] = 256
    # Prefill token budget per scheduler tick: after serving all
    # decoding slots, the tick advances AT MOST ONE in-flight prefill by
    # up to this many tokens (whole chunks; at least one chunk so a
    # prefill always progresses).  None = one chunk per tick
    # (prefill_chunk_tokens).  Larger values trade decode TBT for TTFT
    # of long prompts.
    prefill_chunk_budget: Optional[int] = None
    # Admission control (serving/tiers.py AdmissionController): the max
    # requests allowed to WAIT for this tier beyond its decode_batch
    # concurrent slots.  Past the bound — or earlier, when queued × EWMA
    # service time predicts a wait that would blow request_timeout_s —
    # new requests fail fast with the reference error shape, so Router
    # failover and the perf fail penalty fire instead of the queue
    # growing unboundedly.  None disables admission control.
    admission_max_queue: Optional[int] = 16
    # KV-pressure-aware admission (serving/tiers.py): before admitting, the
    # controller projects the request's block demand (prompt bucket +
    # decode budget, in kv_block_size blocks) against the batched engine's
    # BlockAllocator free count plus the reclaimable parked-prefix blocks,
    # and rejects — reference error shape + retry_after_s — a request that
    # must starve (a fixed HBM block pool admits by blocks, not by slots).
    # Slot-only admission would let such a request in to wait forever.
    # False disables the gate (slot/queue admission still applies); tiers
    # on the sequential engine have no block pool and ignore it.
    kv_admission: bool = True
    # Paged KV pool size override, in blocks (engine/paged_kv.py).  None =
    # full residency (decode_batch × blocks-per-slot: every slot can hold
    # max_seq_len simultaneously — no pressure possible).  Smaller values
    # model the real fixed-HBM-pool regime: admission gates on projected
    # demand and the engine preempts+replays when a running slot cannot
    # grow.  Must cover at least the largest prefill bucket plus one
    # decode tick for a single slot (validated at engine build).
    kv_pool_blocks: Optional[int] = None
    # Context-overflow policy at the serving edge (serving/router.py): a
    # prompt whose estimated token count exceeds max_seq_len -
    # max_new_tokens either fails fast with the reference error shape
    # ("reject") or drops oldest history turns until it fits
    # ("truncate_left" — the default, matching the engine's silent tail-
    # keeping truncation but surfaced in the response as
    # overflow_truncated).  Applied for the dispatching tier before
    # inference, so the choice is explicit policy, not engine behavior.
    overflow_policy: str = "truncate_left"
    # Graceful-drain deadline (engine/manager.py drain()): on SIGTERM /
    # EngineManager.drain the tier stops admitting (reference error shape
    # + retry_after_s; health reports draining), in-flight requests get
    # this long to finish, then the engine stops — stragglers past the
    # deadline fail with the engine-stopped error shape.
    drain_timeout_s: float = 30.0
    # Orbax checkpoint directory to serve trained weights from; None =
    # deterministic random init (utils/checkpoint.py load_params_for_tier).
    checkpoint_path: Optional[str] = None
    # Model preset to draft with for speculative decoding (greedy-exact;
    # engine/speculative.py sequential, engine/batching.py batched).
    # None = plain decoding.  The tier's own model_preset is the valid
    # zero-extra-weights SELF-DRAFT for the batched path (draft params
    # shared with the target; acceptance approaches 1 and the win is
    # the fused γ+1-token verify amortizing per-tick dispatch).
    draft_preset: Optional[str] = None
    speculative_gamma: int = 4
    # Batched speculative decoding (engine/batching.py, ISSUE 15): with
    # a draft_preset and decode_batch>1, each scheduler tick drafts γ
    # tokens per active slot with the draft model (its own paged pool
    # behind the SAME block tables), verifies every slot's γ+1 chunk in
    # ONE fused ragged_verify call (ops/ragged_attention.py — the
    # ragged kernel's q_len=γ+1 face), applies per-slot greedy
    # acceptance, and rewinds rejected tails' block frontiers (never
    # mutating shared/parked blocks — COW first, like admit).  Greedy
    # outputs stay byte-identical to plain decode.  Tri-state: None
    # (default) = AUTO — EngineManager arms it when a tier configures
    # draft_preset with decode_batch>1 (the PR 1 bypass retired —
    # speculation no longer forces the sequential engine; the bench
    # spec leg's tok/s bar was met at 2.0×, BENCHMARKS.md r17); True =
    # engine-level force-on (tests/bench construct engines directly);
    # False = the operator KILL SWITCH — a draft tier keeps its config
    # but serves plain batched decode.  Requires the fused ragged tick;
    # unsharded greedy tiers only.
    spec_decode: Optional[bool] = None
    # Per-slot adaptive γ cap for batched speculation: slots start at
    # this γ and an acceptance-rate EWMA scales each slot down
    # (ultimately to γ=0 = plain ragged decode for low-acceptance
    # tenants, sticky per request).  The compiled draft/verify program
    # family is the power-of-two bucket ladder up to this value —
    # bounded by config, never by observed acceptance lengths.
    spec_gamma_max: int = 4
    # Session KV prefix reuse (engine/prefix_cache.py): park each request's
    # KV cache and re-prefill only the suffix when the next prompt extends
    # it (multi-turn chats).  For DENSE models this is the same math as a
    # cold prefill (kernel rounding may differ between the Pallas and XLA
    # paths); for MoE models it is approximate — expert capacity dispatch
    # sees only the suffix's tokens, so capacity drops can differ from a
    # full-history prefill (moe.chunk_prefill documents this) — disable it
    # on MoE tiers where bit-stable replay matters.  Each parked entry pins
    # one [L, 1, S_max, N_kv, D] ×2 cache in HBM (≈1 GB for an 8B-class
    # model at 8k context) — the default of 2 serves the common
    # alternating-session chat pattern while bounding the steady-state
    # cost; raise it only with measured HBM headroom, or set
    # enable_prefix_cache=False for pure single-turn traffic.
    enable_prefix_cache: bool = True
    prefix_cache_entries: int = 2
    # Cross-request shared-prefix KV (engine/prefix_cache.py, ISSUE 10;
    # batched paged engines only): a prefix-cache hit PINS the parked
    # entry and maps its pool blocks READ-ONLY into the new slot's block
    # table (refcounted BlockAllocator.share), copying only the
    # partially-filled boundary block into a slot-private block
    # (copy-on-write) — N concurrent sessions over one system prompt
    # hold ONE physical copy, so resident KV scales with unique content
    # and a warm-prefix admission costs zero prefill compute and zero
    # new blocks for the shared region.  Greedy outputs stay
    # byte-identical to the cold path.  False restores the exclusive
    # take-ownership semantics (one live session per parked prefix; a
    # second same-prefix session misses and pays a full prefill).
    share_prefix_kv: bool = True
    # Hierarchical KV spill tier (engine/kv_spill.py, ISSUE 14; batched
    # paged engines with chunked prefill only): host-RAM byte budget for
    # DEMOTED prefix-cache entries.  An unpinned sole-owner entry
    # evicted from the device prefix cache is snapshot off the pool
    # (async gather; the device→host pull drains on the spill copier
    # thread, never the tick) instead of being dropped, and a later
    # prompt extending it is PROMOTED back via budgeted host→device
    # grants riding the chunked-prefill lane — warm TTFT becomes a
    # function of host-RAM size instead of HBM size.  Promotions that
    # lose the race (entry invalidated, copier stalled, blocks starved,
    # drain) fall back to a cold prefill with byte-identical greedy
    # output.  0/None disables the tier (exact pre-spill behavior).
    # DLLM_HOST_KV_BYTES overrides globally (bench A/B).
    host_kv_bytes: Optional[int] = None
    # Fraction of the per-tick chunked-prefill token budget
    # (prefill_chunk_budget) a promotion's host→device grants may spend
    # per tick, charged at face value (one block = kv_block_size
    # tokens).  Promotion work competes with chunk grants under ONE
    # budget, so active streams' TBT bound is unchanged by promotions.
    # Floored at one block per tick so a promotion always progresses.
    host_kv_promote_share: float = 1.0
    # Spill copier queue depth (pending demote snapshots).  A full
    # queue makes further demotions drop (blocks were already freed;
    # the prefix just isn't spilled) instead of backing up the
    # scheduler — bounded memory for the in-flight device snapshots.
    host_kv_copier_depth: int = 8
    # Weight-only quantization for serving ("none" | "int8", ops/quant.py):
    # int8 halves decode's HBM weight traffic.  Dense and MoE families;
    # unsharded tiers only (sharding rules and the trainer see
    # full-precision leaf paths).
    quantize: str = "none"
    # KV-cache quantization ("none" | "int8"): halves decode's KV read
    # traffic — the term that overtakes weights at long context × batch.
    # Symmetric per-row int8 with f32 scales; writes quantize, attention
    # reads dequantize.  Applies to the batched engine's paged pool
    # (engine/paged_kv.py) AND the sequential engine's contiguous cache
    # (models/transformer.py); dense family only (MoE keeps bf16).
    kv_quantize: str = "none"
    # Cross-host tier: base URL of a tpu_api server on another host
    # (serving/remote.py — the DCN twin of the reference's SSH-tunneled
    # device endpoints, src/models/nano.py:4-8).  When set, no local
    # engine/submesh is built for this tier; requests POST /query there.
    endpoint: Optional[str] = None
    # Supervisor spawn command for the remote tier (argv tuple): how to
    # (re)start the process serving ``endpoint`` when its /health stops
    # answering — the reference's SSH bootstrap
    # (src/models/server_manager.py:77-105 scripts a login + nohup)
    # expressed as config.  CONTRACT: the command must REPLACE any
    # existing remote instance (kill-then-start, like the reference's
    # script) — the local manager can only terminate the local process
    # it launched, so across SSH a bare start command would lose the
    # port to a wedged predecessor.  E.g. ("ssh", host, "pkill -f
    # tpu_api; nohup python -m distributed_llm_tpu.serving.tpu_api
    # --tier orin &"); in tests a local python argv.  None keeps r3
    # semantics: readiness polling only, lifecycle owned by an external
    # supervisor.
    spawn_cmd: Optional[Tuple[str, ...]] = None
    # Per-request wall-clock cap, mirroring the reference clients' HTTP
    # read timeout (requests.post(..., timeout=(5, 180)),
    # src/models/nano.py:28): a device call that exceeds it returns the
    # reference error-dict shape so the router can fail over and the
    # perf strategy records the failure — an in-process engine on a
    # wedged chip would otherwise hang the serving thread forever and
    # no failure machinery could fire.  None disables the cap.  The
    # abandoned call keeps its worker thread until the device returns
    # (in-process calls can't be cancelled), matching the reference's
    # semantics where the Jetson keeps crunching after the client
    # times out.
    request_timeout_s: Optional[float] = 180.0
    # Per-tier SLO targets (obs/slo.py, fed from the router's exactly-
    # once _finish_request exit): a request is GOODPUT only when it
    # completes ok with TTFT ≤ slo_ttft_ms and per-request p95
    # time-between-tokens ≤ slo_tbt_ms.  The open-loop bench leg and the
    # online dllm_slo_goodput gauges judge serving by these, and a tier
    # whose windowed goodput collapses raises an overload incident into
    # the flight recorder.  None disables that criterion (error-only
    # goodput); DLLM_SLO_TTFT_MS / DLLM_SLO_TBT_MS override globally.
    # Defaults are interactive-chat-shaped: first token within 2 s,
    # no p95 inter-token stall past 200 ms.
    slo_ttft_ms: Optional[float] = 2000.0
    slo_tbt_ms: Optional[float] = 200.0
    # Decode-watchdog deadline (serving/health.py + engine/batching.py):
    # a batched engine with admitted/queued work but NO step progress
    # (tick completion, admission, or idle heartbeat) for this many
    # seconds is declared wedged — the round-5 failure mode, where the
    # chip hung inside a device call and only probe-count escalation
    # (minutes later) would have noticed.  EngineManager.health() flips
    # unhealthy past the deadline and the HealthMonitor restarts the
    # engine IMMEDIATELY through its existing bounded restart path.
    # Generous default: a mid-serve XLA retrace (deeper decode window
    # rung) legitimately stalls the loop for tens of seconds on chip.
    # None disables the watchdog.
    watchdog_stall_s: Optional[float] = 300.0
    # Replicated tiers (serving/replicas.py, ISSUE 12): >1 makes the tier
    # own that many ENGINE REPLICAS — data-parallel copies of the same
    # model, each a full EngineManager with its own bounded admission
    # queue, breaker sub-gate, watchdog, and drain — so aggregate
    # throughput scales past one engine's knee as a CONFIG change.  When
    # the tier's submesh has enough devices, each replica gets its own
    # device slice (devices permitting: replicas x tp chips); on a
    # single-device/CPU box the replicas are process-local engines
    # sharing the device.  Tier-level health()/kv_stats()/slot_stats()
    # become aggregates with a per-replica breakdown; the HealthMonitor
    # probes and restarts replicas INDIVIDUALLY, so one wedged replica
    # degrades capacity instead of the tier.  1 = exactly the
    # pre-replica single-engine behavior (byte-identical).
    replicas: int = 1
    # Prefix-affinity replica routing (serving/replicas.py): dispatch
    # consults each replica's parked-prefix cache (the same select_reuse
    # longest-match the engines reuse blocks by) and routes a request to
    # the replica already holding its prefix KV, so the PR 10
    # shared-prefix dedup win survives going multi-replica instead of
    # being diluted N ways by spraying same-prefix sessions across
    # replicas.  False = pure least-loaded (queue_depth x EWMA)
    # dispatch.  DLLM_REPLICA_POLICY overrides globally.
    replica_affinity: bool = True
    # Minimum parked-prefix token match that binds a request to a
    # replica: matches below it route least-loaded (a trivial prefix is
    # cheaper to re-prefill than a load imbalance).
    replica_affinity_min_tokens: int = 16
    # Affinity-override threshold in seconds: when the affine replica's
    # predicted queue wait (queue_depth / slots x EWMA service time —
    # PR 1's admission predictor) exceeds the least-loaded replica's by
    # more than this, affinity yields and the request routes
    # least-loaded — a hot replica must not starve the others to keep
    # its cache locality.
    replica_affinity_override_s: float = 1.0
    # Per-tenant isolation (serving/tenants.py, ISSUE 17): tenant name →
    # TenantQuota for this tier.  Tenants absent from the map get the
    # registry's default quota, whose fields come from the
    # ``DLLM_TENANT_*`` env defaults (unset = unlimited).  The quota
    # layer enforces admission budgets (max in-flight / max queued / a
    # device-time-rate token bucket debited from the measured PR 11
    # bill), DWRR scheduling weights, resident-KV block budgets billed
    # at 1/refcount, and per-tenant speculative γ caps.  None = quotas
    # OFF: every code path is byte-identical to pre-tenant behavior
    # (pinned by test), and tenant_id only flows into observability.
    tenant_quotas: Optional[Dict[str, "TenantQuota"]] = None
    # SLO-driven elastic capacity (serving/autoscaler.py, ISSUE 18):
    # True arms a per-tier ReplicaAutoscaler control loop that reads the
    # signals the system already emits (SLOMonitor goodput window, queue
    # depth / slot occupancy, admission shed rate) and actuates replica
    # membership through ReplicatedTierClient.scale_to — scale-up warms
    # the new replica fully off-membership before go-live (dispatch
    # never blocks on a cold start), scale-down drains the least-affine
    # replica with its refcount-1 parked prefixes demoted through the
    # PR 13 spill tier and handed to a survivor.  False (default) keeps
    # membership exactly the static PR 12 path, byte-identical (pinned);
    # the DLLM_AUTOSCALE=0 env kill switch disarms ALL tiers at once.
    autoscale: bool = False
    # Membership bounds: the autoscaler never scales below min (capacity
    # floor — also the initial size when ``replicas`` is smaller) or
    # above max (cost ceiling; also bounds warm-up burst).
    autoscale_min_replicas: int = 1
    autoscale_max_replicas: int = 4
    # Controller cadence: one signal read + decision per interval.
    autoscale_interval_s: float = 1.0
    # Scale-up trigger 1 — goodput floor: the tier's windowed SLO
    # goodput (obs/slo.py, fed by real request outcomes) sustained
    # below this fraction for autoscale_breach_window_s.  Same scale
    # as the SLO monitor's goodput (0..1).
    autoscale_goodput_floor: float = 0.5
    # Scale-up trigger 2 — queue growth: tier queue depth sustained
    # above this many requests PER live replica (queueing theory's
    # backlog signal; per-replica so the bar scales with membership).
    autoscale_queue_high: float = 2.0
    # How long a breach (goodput floor or queue growth) must persist
    # before scale-up fires — hysteresis against one-sample spikes.
    autoscale_breach_window_s: float = 3.0
    # How long the tier must be fully idle (no queue, no active slots,
    # no admission sheds, goodput at/above floor) before scale-down
    # fires — idle windows are long on purpose: adding capacity late
    # costs SLO, removing it late only costs replica-seconds.
    autoscale_idle_window_s: float = 10.0
    # Per-direction cooldowns from the LAST membership event (either
    # direction): up re-arms fast (load is load), down re-arms slow.
    # Together with the windows these bound flap — an up-down-up needs
    # at least up+down cooldowns of wall time.
    autoscale_up_cooldown_s: float = 5.0
    autoscale_down_cooldown_s: float = 15.0
    # Warm standby pool: True pre-builds and pre-warms the replicas
    # between min and max at tier start (riding replica 0's compile
    # cache, off-membership), so a scale-up PUBLISHES a fully-warm
    # standby in milliseconds instead of paying an engine build + warm
    # trace mid-peak — exactly when capacity is short — and scale-down
    # PARKS the drained replica (after its spill handoff) for the next
    # peak.  The trade is memory: parked engines hold params + pools
    # while off-membership.  False = build-at-actuation (the engine is
    # constructed and warmed inside scale_to, and destroyed on
    # scale-down).  Only consulted when ``autoscale`` arms the tier.
    autoscale_warm_pool: bool = True
    # Crash rescue (serving/replicas.py restart_replica, ISSUE 20): when
    # a replica is restarted (HealthMonitor wedge verdict or an explicit
    # restart_replica call), its queued + in-flight requests are CAPTURED
    # (prompt + tokens already emitted, the PR 5 replay machinery) and
    # re-dispatched to a live sibling — or re-queued on the restarted
    # engine when the tier has one replica — resuming byte-identically
    # under greedy from the last emitted token.  Streams stall through
    # the rescue instead of erroring, so Router tier-level failover only
    # fires when the whole tier is dead.  False = pre-rescue behavior:
    # a restart fails every in-flight request with the engine-stopped
    # error shape.
    replica_rescue: bool = True
    # Spill-state survival (ISSUE 20): detach the host KV spill store
    # from the engine's lifetime across a replica restart — the host LRU
    # outlives stop_server and re-attaches to the rebuilt engine (or is
    # handed to a survivor replica through the scale-down handoff path
    # when the restart fails), so a restart costs warm-TTFT promotion
    # for revisited prefixes instead of a cold prefill.  False = the
    # spill store stops (and empties) with the engine, the pre-survival
    # behavior.
    spill_survive_restart: bool = True

    def model(self) -> ModelConfig:
        return MODEL_PRESETS[self.model_preset]

    def draft_model(self) -> ModelConfig:
        """The speculative draft's architecture (``draft_preset``) —
        raises KeyError when none is configured, like ``model()`` would
        on a bad preset."""
        return MODEL_PRESETS[self.draft_preset]


@dataclasses.dataclass(frozen=True)
class ClusterConfig:
    """The two-tier deployment. Tier submeshes are carved from jax.devices()
    in order: nano gets the first `nano.tp` chips, orin the next `orin.tp`.
    If fewer devices exist than requested, tiers share / shrink gracefully
    (single-chip dev boxes and the one-chip bench environment).
    """

    # Concurrent-by-default: both tiers serve through the continuous-
    # batching engine (decode_batch slots share one compiled decode
    # step); the 3.67×-measured batching speedup only reaches traffic
    # when it is the default path, not a bench-only A/B.
    nano: TierConfig = dataclasses.field(
        default_factory=lambda: TierConfig(name="nano", model_preset="nano_1b",
                                           tp=1, decode_batch=8))
    orin: TierConfig = dataclasses.field(
        default_factory=lambda: TierConfig(name="orin", model_preset="orin_8b",
                                           tp=4, decode_batch=4))
    seed: int = 0
    # Per-tier circuit breaker (serving/breaker.py): after
    # ``breaker_failures`` CONSECUTIVE error-shaped results a tier goes
    # OPEN and sheds all traffic for ``breaker_cooldown_s``, then a
    # single half-open canary request (or a HealthMonitor probe) decides
    # between closing and re-opening.  The threshold is deliberately
    # above the one-shot faults the unit suite scripts (a single
    # injected failure must keep reference failover semantics);
    # breaker_failures=0 disables the breaker entirely.
    breaker_failures: int = 5
    breaker_cooldown_s: float = 30.0
    # Bounded retry for TRANSIENT error shapes (connection refused/reset,
    # engine-returned-no-result — not timeouts, which already consumed
    # their whole budget): up to ``retry_attempts`` re-issues on the SAME
    # tier with jittered exponential backoff starting at
    # ``retry_backoff_s``.  No retry starts past the primary tier's
    # request_timeout_s from dispatch; each attempt stays individually
    # capped by the tier's own timeout (serving/router.py; failover
    # keeps its reference one-shot semantics).
    retry_attempts: int = 1
    retry_backoff_s: float = 0.05

    def tiers(self) -> Tuple[TierConfig, TierConfig]:
        return (self.nano, self.orin)


def bench_cluster() -> ClusterConfig:
    """Cluster sized for the single-chip bench environment.

    int8 weight-only serving mirrors the reference deployment (Ollama runs
    GGML-quantized models on the Jetsons) and roughly halves decode's HBM
    weight traffic on the bandwidth-bound decode loop.

    DLLM_BENCH_SPEC_ORIN=1 puts the nano model in front of the orin tier
    as a speculative draft (greedy-exact): at the measured ~0.5
    acceptance, the weight-bound orin decode does ~1 full weight pass per
    ~3 tokens instead of per token.  A/B'd by scripts/tpu_round.sh before
    any default flip.
    """
    from .config_registry import env_flag
    draft = "nano_bench" if env_flag("DLLM_BENCH_SPEC_ORIN") else None
    cluster = ClusterConfig(
        nano=TierConfig(name="nano", model_preset="nano_bench", tp=1,
                        max_new_tokens=64, quantize="int8",
                        decode_batch=8),
        orin=TierConfig(name="orin", model_preset="orin_bench", tp=1,
                        max_new_tokens=128, quantize="int8",
                        decode_batch=4, draft_preset=draft),
    )
    return _apply_tuning(cluster, draft_override=draft,
                         draft_preset="nano_bench")


def _apply_tuning(cluster: "ClusterConfig", *,
                  draft_override: "Optional[str]" = None,
                  draft_preset: str = "nano_bench") -> "ClusterConfig":
    """Defaults follow measurement (same pattern as the attention
    dispatch table): a committed bench/tuning.json — written by
    `python -m distributed_llm_tpu.bench.tune` from real bench
    artifacts, backend-tagged — overlays quantize/kv_quantize/draft per
    tier when (and only when) its backend matches the running one.  An
    explicit ``draft_override`` (the DLLM_BENCH_SPEC_ORIN A/B) still
    wins over the table's speculative verdict."""
    try:
        import jax

        from .bench.tune import load_tuning
        tiers = load_tuning(jax.default_backend())
    except Exception:
        tiers = {}
    if not tiers:
        return cluster

    def apply(tier: TierConfig) -> TierConfig:
        t = tiers.get(tier.name) or {}
        kw = {k: t[k] for k in ("quantize", "kv_quantize") if k in t}
        if (tier.name == "orin" and draft_override is None
                and "speculative" in t):
            kw["draft_preset"] = draft_preset if t["speculative"] else None
        return dataclasses.replace(tier, **kw) if kw else tier

    return dataclasses.replace(cluster, nano=apply(cluster.nano),
                               orin=apply(cluster.orin))


def cpu_bench_cluster() -> ClusterConfig:
    """Quality-consistent tiers for the chipless fallback bench.

    The premise every routing strategy trades on — orin answers BETTER
    and costs more per token (src/devices/orin_api.py:17-18 llama3 vs
    nano_api.py:15-21 phi3-mini) — must hold on whatever cluster the
    headline actually serves (VERDICT r4 missing #2).  The TPU bench
    pair (nano_bench/orin_bench) is gated on-chip by tpu_round.sh; on
    the 1-core CPU box the 1B orin_bench cannot be trained to quality,
    so the CPU bench demotes to the largest pair this box CAN train and
    serve: mini_bench (~26M, pretrained on CPU) as the weak tier under
    nano_bench (~130M, chip-pretrained, held-out loss 1.257) as the
    strong one.  Smaller decode caps keep the 1-core sweep bounded.
    """
    from .config_registry import env_flag
    draft = "mini_bench" if env_flag("DLLM_BENCH_SPEC_ORIN") else None
    # Short bucket ladder: each bucket is a separate XLA program and the
    # 1-core box pays real compile time per program.  64 stays the
    # bottom rung — the benchmark sets' median query is ~10-40 tokens
    # and padding those to 256 would 4x their prefill FLOPs steady-state
    # — while the middle rungs collapse to one (2048 covers the
    # long-context probe).
    cluster = ClusterConfig(
        nano=TierConfig(name="nano", model_preset="mini_bench", tp=1,
                        max_new_tokens=48, decode_batch=8,
                        prefill_buckets=(64, 256, 2048)),
        orin=TierConfig(name="orin", model_preset="nano_bench", tp=1,
                        max_new_tokens=64, decode_batch=4,
                        draft_preset=draft,
                        prefill_buckets=(64, 256, 2048)),
    )
    # A cpu-backend tuning.json (bench.tune over the chipless headline's
    # artifacts) steers THIS pair's quant/kv/spec defaults the same way
    # the tpu table steers bench_cluster — the draft is the pair's own
    # weak tier, and the explicit spec A/B env wins over the table here
    # too.
    return _apply_tuning(cluster, draft_override=draft,
                         draft_preset="mini_bench")


def flagship_cluster(n_devices: Optional[int] = None) -> ClusterConfig:
    """North-star-scale deployment (SURVEY.md "North star"): the 1B-class
    nano tier and the 8B-class orin tier, shaped to the devices at hand.

    On a pod slice (≥5 chips) orin serves bf16 over a tp=4 submesh — the
    layout the HBM-budget test proves out (tests/test_flagship.py).  On
    the single-chip bench box orin serves int8 (~7 GB weights), which the
    budget shows fitting 16 GB WITH its KV + parked prefix caches.  The
    bench's flagship phase drives exactly these tiers (bench.py
    flagship_phase), so the presets are exercised, not dead config
    (VERDICT r2 #2)."""
    if n_devices is None:
        import jax
        n_devices = len(jax.devices())
    nano = TierConfig(name="nano", model_preset="nano_1b", tp=1,
                      max_new_tokens=64, decode_batch=8,
                      prefill_buckets=(256, 1024, 2048))
    if n_devices >= 5:
        orin = TierConfig(name="orin", model_preset="orin_8b", tp=4,
                          max_new_tokens=128, decode_batch=4,
                          prefill_buckets=(256, 1024, 2048))
    else:
        # int8 WEIGHTS are a fit requirement here (14 GB bf16 weights
        # alone overflow the 16 GB chip — tests/test_flagship.py); int8
        # KV is a PERF knob, and the measurements say it doesn't pay:
        # r4 measured kv-int8 0.53× the bf16-KV rate, and the r5
        # re-measure on real-trained tiers landed ~break-even
        # (0.99×/0.95× — BENCHMARKS.md, bench/tuning.json evidence), so
        # it defaults OFF like everywhere else (VERDICT r5 #4: no
        # on-chip tuning table exists to justify it).  Opt back in with
        # DLLM_FLAGSHIP_KV_INT8=1 (the A/B flag) or a measured TPU
        # tuning.json; the HBM budget fits with bf16 KV (the budget
        # test pins it).
        from .config_registry import env_flag
        kv = "int8" if env_flag("DLLM_FLAGSHIP_KV_INT8") else "none"
        orin = TierConfig(name="orin", model_preset="orin_8b", tp=1,
                          max_new_tokens=128, quantize="int8",
                          kv_quantize=kv, decode_batch=4,
                          prefill_buckets=(256, 1024, 2048))
    return ClusterConfig(nano=nano, orin=orin)


def tiny_cluster() -> ClusterConfig:
    """Tiny cluster for CPU unit tests (8 virtual devices: 1 + 4 used).

    Deliberately sequential (decode_batch=1): hundreds of unit tests
    build these tiers and the sequential engine's warmup is the cheaper
    one; the concurrent-by-default serving path is covered by
    ``tiny_batched_cluster`` (admission/soak tests and the bench's
    chipless fallback) and the real serving presets above."""
    return ClusterConfig(
        nano=TierConfig(name="nano", model_preset="nano_test", tp=1,
                        max_new_tokens=8, prefill_buckets=(16, 32, 64),
                        kv_block_size=16),
        orin=TierConfig(name="orin", model_preset="orin_test", tp=4,
                        max_new_tokens=8, prefill_buckets=(16, 32, 64),
                        kv_block_size=16),
    )


def tiny_batched_cluster(nano_slots: int = 4,
                         orin_slots: int = 2) -> ClusterConfig:
    """The tiny tiers with the serving default's continuous-batching
    engines (concurrent-by-default at test scale): used by the
    admission/soak tests and by the bench's chipless tiny fallback so
    the concurrent headline exercises the same engine family the real
    presets serve.  max_new_tokens is raised to a serving-realistic 24
    (the unit tiers' 8 is a test-speed artifact): batching amortizes the
    DECODE loop, so a cap that makes requests all-prefill would
    understate the default path the real presets (48-128 caps) serve."""
    tiny = tiny_cluster()
    return dataclasses.replace(
        tiny,
        nano=dataclasses.replace(tiny.nano, decode_batch=nano_slots,
                                 max_new_tokens=24),
        orin=dataclasses.replace(tiny.orin, decode_batch=orin_slots,
                                 max_new_tokens=24))


def default_checkpoint(preset: str) -> Optional[str]:
    """Repo-local pretrained weights for a preset, if published: the
    ``checkpoints/<preset>`` directory written by training/pretrain.py
    (detected by its ``latest`` version link).  None = no artifact, tiers
    fall back to deterministic random init."""
    import os
    root = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "checkpoints", preset)
    return root if os.path.islink(os.path.join(root, "latest")) else None


def with_default_checkpoints(cluster: "ClusterConfig") -> "ClusterConfig":
    """Fill each tier's ``checkpoint_path`` with the preset's published
    pretrained artifact (when one exists and the tier doesn't already pin
    a path).  Serving entry points use this so /chat runs on learned
    weights (reference tiers serve pretrained models,
    src/devices/nano_api.py:15-16); unit tests build clusters directly
    and keep fast deterministic random init."""
    def fill(tier: TierConfig) -> TierConfig:
        if tier.checkpoint_path or tier.endpoint:
            return tier
        path = default_checkpoint(tier.model_preset)
        return (dataclasses.replace(tier, checkpoint_path=path)
                if path else tier)
    return dataclasses.replace(cluster, nano=fill(cluster.nano),
                               orin=fill(cluster.orin))


def resolve_config(config: Optional[Dict[str, Any]], benchmark_mode: bool) -> Dict[str, Any]:
    """Explicit config wins; otherwise pick the canonical dict by mode
    (reference: src/router.py:37-40)."""
    if config is not None:
        return config
    return dict(BENCHMARK_CFG) if benchmark_mode else dict(PRODUCTION_CFG)
