"""Pure-JAX LLaMA-style decoder-only transformer.

This is the native model-execution core that the reference delegates to
Ollama/llama.cpp (SURVEY.md §2.1): RMSNorm, rotary position embeddings,
grouped-query attention, SwiGLU MLP, tied LM head.  Design choices are
TPU-first:

- **Scanned layers**: per-layer parameters are stacked along a leading [L]
  axis and the forward pass is a single ``lax.scan`` over layers, so compile
  time is O(1) in depth and XLA sees one fused block body.
- **Functional params pytree** (no framework Module): makes pjit/shard_map
  sharding annotations trivial (parallel/sharding.py maps each leaf to a
  PartitionSpec) and keeps everything donate-able.
- **bfloat16 params/activations** with float32 softmax/norm accumulators —
  the MXU-native layout.
- Static shapes everywhere; the decode step is one token per call and is
  driven by a compiled ``lax.while_loop`` (engine/inference.py).
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

from ..config import ModelConfig
from ..ops import attention
from ..ops import quant

Params = Dict[str, Any]
KVCache = Dict[str, jax.Array]   # {"k": [L,B,S,N_kv,D], "v": [L,B,S,N_kv,D]}


# =============================================================================
# Init
# =============================================================================

def init_params(cfg: ModelConfig, seed: int = 0) -> Params:
    """Deterministic random init (no pretrained weights exist in this
    zero-egress environment; quality of text is not the contract, the
    execution engine is)."""
    key = jax.random.PRNGKey(seed)
    dtype = jnp.dtype(cfg.dtype)
    h, f, l = cfg.hidden_size, cfg.ffn_size, cfg.num_layers
    d = cfg.head_dim
    nq, nkv = cfg.num_heads, cfg.num_kv_heads

    def normal(key, shape, scale=0.02):
        return (scale * jax.random.normal(key, shape, jnp.float32)).astype(dtype)

    ks = jax.random.split(key, 8)
    return {
        "embed": normal(ks[0], (cfg.vocab_size, h)),
        "layers": {
            "ln1": jnp.ones((l, h), dtype),
            "wq": normal(ks[1], (l, h, nq * d)),
            "wk": normal(ks[2], (l, h, nkv * d)),
            "wv": normal(ks[3], (l, h, nkv * d)),
            "wo": normal(ks[4], (l, nq * d, h)),
            "ln2": jnp.ones((l, h), dtype),
            "w_gate": normal(ks[5], (l, h, f)),
            "w_up": normal(ks[6], (l, h, f)),
            "w_down": normal(ks[7], (l, f, h)),
        },
        "final_ln": jnp.ones((h,), dtype),
    }


# =============================================================================
# Building blocks
# =============================================================================

def rms_norm(x: jax.Array, w: jax.Array, eps: float) -> jax.Array:
    xf = x.astype(jnp.float32)
    scale = jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    return (xf * scale).astype(x.dtype) * w


def rope_sincos(positions: jax.Array, head_dim: int, theta: float
                ) -> Tuple[jax.Array, jax.Array]:
    """positions [...,] -> (sin, cos) each [..., head_dim/2], float32."""
    freqs = theta ** (-jnp.arange(0, head_dim // 2, dtype=jnp.float32)
                      / (head_dim // 2))
    angles = positions.astype(jnp.float32)[..., None] * freqs
    return jnp.sin(angles), jnp.cos(angles)


def apply_rope(x: jax.Array, sin: jax.Array, cos: jax.Array) -> jax.Array:
    """Rotate-half RoPE. x: [..., N, D]; sin/cos: [..., D/2] (broadcast over N)."""
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    sin, cos = sin[..., None, :], cos[..., None, :]
    return jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1).astype(x.dtype)


def _swiglu(x: jax.Array, gate, up, down) -> jax.Array:
    return quant.matmul(
        jax.nn.silu(quant.matmul(x, gate)) * quant.matmul(x, up), down)


# =============================================================================
# Prefill (full-sequence forward)
# =============================================================================

def prefill(cfg: ModelConfig, params: Params, tokens: jax.Array,
            positions: jax.Array, attn=None
            ) -> Tuple[jax.Array, Tuple[jax.Array, jax.Array]]:
    """Process a full (right-padded) prompt.

    tokens/positions: [B, S].  Returns (hidden [B,S,H],
    (k_all, v_all) each [L,B,S,N_kv,D]) — the per-layer K/V to seed the cache.
    ``attn`` optionally replaces the causal-attention op (q, k, v) ->
    [B,S,Nq,D] — the hook sequence-parallel prefill uses to swap in ring
    attention over the 'sp' mesh axis (parallel/ring_attention.py).
    """
    b, s = tokens.shape
    d = cfg.head_dim
    x = quant.embed_rows(params["embed"], tokens)                       # [B,S,H]
    sin, cos = rope_sincos(positions, d, cfg.rope_theta)
    if attn is None:
        attn = lambda q, k, v: attention.causal(q, k, v,
                                                impl=cfg.attention_impl)

    def layer(x, lp):
        h_in = rms_norm(x, lp["ln1"], cfg.norm_eps)
        q = quant.matmul(h_in, lp["wq"]).reshape(b, s, cfg.num_heads, d)
        k = quant.matmul(h_in, lp["wk"]).reshape(b, s, cfg.num_kv_heads, d)
        v = quant.matmul(h_in, lp["wv"]).reshape(b, s, cfg.num_kv_heads, d)
        q = apply_rope(q, sin, cos)
        k = apply_rope(k, sin, cos)
        out = attn(q, k, v).reshape(b, s, cfg.num_heads * d)
        x = x + quant.matmul(out, lp["wo"])
        x = x + _swiglu(rms_norm(x, lp["ln2"], cfg.norm_eps),
                        lp["w_gate"], lp["w_up"], lp["w_down"])
        return x, (k, v)

    x, (k_all, v_all) = jax.lax.scan(layer, x, params["layers"])
    return rms_norm(x, params["final_ln"], cfg.norm_eps), (k_all, v_all)


def logits_from_hidden(params: Params, hidden: jax.Array) -> jax.Array:
    """Tied LM head: [..., H] -> [..., V] in float32."""
    return quant.tied_head(params["embed"], hidden)


# =============================================================================
# Decode step (one token, KV cache)
# =============================================================================

def decode_step(cfg: ModelConfig, params: Params, token: jax.Array,
                pos: jax.Array, kv: KVCache, attn=None
                ) -> Tuple[jax.Array, KVCache]:
    """One autoregressive step for every sequence in the batch.

    token: [B] current input token; pos: [B] its position (0-based);
    kv: cache with [L,B,S_max,N_kv,D] arrays, written in-place at ``pos``.
    ``attn`` optionally replaces the decode-attention op
    (q, k_cache, v_cache, pos) -> [B,Nq,D] — the hook tensor-parallel
    tiers use to run the flash decode kernel per head-shard
    (parallel/tp_attention.py).
    Returns (logits [B,V] float32, updated cache).
    """
    b = token.shape[0]
    d = cfg.head_dim
    x = quant.embed_rows(params["embed"], token)      # [B,H]
    sin, cos = rope_sincos(pos, d, cfg.rope_theta)    # [B, D/2]
    quantized = "ks" in kv
    if attn is None or quantized:
        # int8 caches always use the scale-aware dispatcher (the TP flash
        # hook carries no scale operands; its policy skips quantized
        # tiers, engine/inference.py).
        attn = lambda q, kc, vc, p, ks=None, vs=None: attention.decode(
            q, kc, vc, p, impl=cfg.attention_impl, k_scale=ks, v_scale=vs)
    else:
        base = attn
        attn = lambda q, kc, vc, p, ks=None, vs=None: base(q, kc, vc, p)

    def write_rows(cache, new):
        # Write this step's K/V (or scale) rows at each sequence's pos.
        def one(c, n, p):
            return jax.lax.dynamic_update_slice(
                c, n[None], (p,) + (0,) * (c.ndim - 1))
        return jax.vmap(one)(cache, new, pos)

    def layer(x, scanned):
        if quantized:
            lp, k_cache, v_cache, ks_cache, vs_cache = scanned
        else:
            lp, k_cache, v_cache = scanned
            ks_cache = vs_cache = None
        h_in = rms_norm(x, lp["ln1"], cfg.norm_eps)
        q = quant.matmul(h_in, lp["wq"]).reshape(b, cfg.num_heads, d)
        k = quant.matmul(h_in, lp["wk"]).reshape(b, cfg.num_kv_heads, d)
        v = quant.matmul(h_in, lp["wv"]).reshape(b, cfg.num_kv_heads, d)
        q = apply_rope(q, sin, cos)
        k = apply_rope(k, sin, cos)

        if quantized:
            k, k_sc = quant.quantize_kv_rows(k)
            v, v_sc = quant.quantize_kv_rows(v)
            ks_cache = write_rows(ks_cache, k_sc)
            vs_cache = write_rows(vs_cache, v_sc)
        k_cache = write_rows(k_cache, k)
        v_cache = write_rows(v_cache, v)

        attn_out = attn(q, k_cache, v_cache, pos, ks_cache, vs_cache)
        x = x + quant.matmul(attn_out.reshape(b, cfg.num_heads * d),
                             lp["wo"])
        x = x + _swiglu(rms_norm(x, lp["ln2"], cfg.norm_eps),
                        lp["w_gate"], lp["w_up"], lp["w_down"])
        if quantized:
            return x, (k_cache, v_cache, ks_cache, vs_cache)
        return x, (k_cache, v_cache)

    if quantized:
        x, (k_new, v_new, ks_new, vs_new) = jax.lax.scan(
            layer, x, (params["layers"], kv["k"], kv["v"],
                       kv["ks"], kv["vs"]))
        new_kv = {"k": k_new, "v": v_new, "ks": ks_new, "vs": vs_new}
    else:
        x, (k_new, v_new) = jax.lax.scan(
            layer, x, (params["layers"], kv["k"], kv["v"]))
        new_kv = {"k": k_new, "v": v_new}
    hidden = rms_norm(x, params["final_ln"], cfg.norm_eps)
    return logits_from_hidden(params, hidden), new_kv


def chunk_prefill(cfg: ModelConfig, params: Params, tokens: jax.Array,
                  start: jax.Array, true_len: jax.Array, kv: KVCache,
                  window: int = 0) -> Tuple[jax.Array, KVCache]:
    """Prefill a CHUNK of a prompt against an existing KV cache.

    The op behind session prefix reuse (engine/prefix_cache.py): when a new
    prompt extends a previously-served one (the multi-turn chat pattern —
    the reference re-prefills the whole history through Ollama every turn,
    SURVEY.md §3.1), only the suffix is forwarded here, attending to the
    cached prefix at absolute positions.  Also serves as plain chunked
    prefill (start=0 over successive chunks).

    tokens: [B, S_c] right-padded chunk; start: [B] absolute position of the
    chunk's first token (prefix length already in ``kv``); true_len: [B]
    total valid length (start + real chunk tokens); kv: [L,B,S_max,N_kv,D]
    cache, written in place at [start, start+S_c).
    ``window`` (static): attend only to cache positions < window instead of
    all S_max — callers pass a bucketed bound ≥ start+S_c so attention cost
    is O(prefix bucket), not O(max_seq).  0 = full cache.
    Returns (hidden [B,S_c,H], updated cache).
    """
    b, s_c = tokens.shape
    d = cfg.head_dim
    x = quant.embed_rows(params["embed"], tokens)                                    # [B,S_c,H]
    positions = start[:, None] + jnp.arange(s_c)[None, :]          # [B,S_c]
    # Queries past each sequence's true length are padding; clamp their mask
    # frontier to the last real position (their outputs are never read).
    q_pos = jnp.minimum(positions, jnp.maximum(true_len, 1)[:, None] - 1)
    sin, cos = rope_sincos(positions, d, cfg.rope_theta)

    quantized = "ks" in kv

    def write_rows(cache, new):
        def one(c, n, p):
            return jax.lax.dynamic_update_slice(
                c, n, (p,) + (0,) * (c.ndim - 1))
        return jax.vmap(one)(cache, new, start)

    def layer(x, scanned):
        if quantized:
            lp, k_cache, v_cache, ks_cache, vs_cache = scanned
        else:
            lp, k_cache, v_cache = scanned
            ks_cache = vs_cache = None
        h_in = rms_norm(x, lp["ln1"], cfg.norm_eps)
        q = quant.matmul(h_in, lp["wq"]).reshape(b, s_c, cfg.num_heads, d)
        k = quant.matmul(h_in, lp["wk"]).reshape(b, s_c, cfg.num_kv_heads, d)
        v = quant.matmul(h_in, lp["wv"]).reshape(b, s_c, cfg.num_kv_heads, d)
        q = apply_rope(q, sin, cos)
        k = apply_rope(k, sin, cos)

        if quantized:
            k, k_sc = quant.quantize_kv_rows(k)
            v, v_sc = quant.quantize_kv_rows(v)
            ks_cache = write_rows(ks_cache, k_sc)
            vs_cache = write_rows(vs_cache, v_sc)
        k_cache = write_rows(k_cache, k)
        v_cache = write_rows(v_cache, v)

        k_att = k_cache[:, :window] if window else k_cache
        v_att = v_cache[:, :window] if window else v_cache
        scales = ((ks_cache[:, :window] if window else ks_cache,
                   vs_cache[:, :window] if window else vs_cache)
                  if quantized else (None, None))
        attn = attention.chunk(q, k_att, v_att, q_pos,
                               impl=cfg.attention_impl,
                               k_scale=scales[0], v_scale=scales[1])
        x = x + quant.matmul(attn.reshape(b, s_c, cfg.num_heads * d), lp["wo"])
        x = x + _swiglu(rms_norm(x, lp["ln2"], cfg.norm_eps),
                        lp["w_gate"], lp["w_up"], lp["w_down"])
        if quantized:
            return x, (k_cache, v_cache, ks_cache, vs_cache)
        return x, (k_cache, v_cache)

    if quantized:
        x, (k_new, v_new, ks_new, vs_new) = jax.lax.scan(
            layer, x, (params["layers"], kv["k"], kv["v"],
                       kv["ks"], kv["vs"]))
        new_kv = {"k": k_new, "v": v_new, "ks": ks_new, "vs": vs_new}
    else:
        x, (k_new, v_new) = jax.lax.scan(
            layer, x, (params["layers"], kv["k"], kv["v"]))
        new_kv = {"k": k_new, "v": v_new}
    hidden = rms_norm(x, params["final_ln"], cfg.norm_eps)
    return hidden, new_kv


def init_kv_cache(cfg: ModelConfig, batch: int, max_seq: int,
                  kv_quantize: str = "none") -> KVCache:
    """``kv_quantize="int8"``: K/V stored as symmetric per-row int8 with
    f32 scale planes {"ks","vs": [L,B,S,N_kv]} — decode streams the whole
    cache every step, so halving its bytes is a direct bandwidth win
    (ops/quant.quantize_kv_rows; the paged pool's contiguous twin)."""
    shape = (cfg.num_layers, batch, max_seq, cfg.num_kv_heads, cfg.head_dim)
    if kv_quantize == "int8":
        return {"k": jnp.zeros(shape, jnp.int8),
                "v": jnp.zeros(shape, jnp.int8),
                "ks": jnp.ones(shape[:-1], jnp.float32),
                "vs": jnp.ones(shape[:-1], jnp.float32)}
    if kv_quantize != "none":
        raise ValueError(f"kv_quantize={kv_quantize!r}: expected 'none' "
                         "or 'int8'")
    dtype = jnp.dtype(cfg.dtype)
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


def seed_kv_cache(cfg: ModelConfig, k_all: jax.Array, v_all: jax.Array,
                  cache_len: int, kv_quantize: str = "none") -> KVCache:
    """Build a cache of length ``cache_len`` holding a prefill's K/V
    ([L,B,S,N_kv,D]) at positions [0, S) — quantizing on write when the
    cache is int8."""
    b = k_all.shape[1]
    cache = init_kv_cache(cfg, b, cache_len, kv_quantize)
    if "ks" in cache:
        kq, ks = quant.quantize_kv_rows(k_all)
        vq, vs = quant.quantize_kv_rows(v_all)
        return {
            "k": jax.lax.dynamic_update_slice(cache["k"], kq,
                                              (0, 0, 0, 0, 0)),
            "v": jax.lax.dynamic_update_slice(cache["v"], vq,
                                              (0, 0, 0, 0, 0)),
            "ks": jax.lax.dynamic_update_slice(cache["ks"], ks,
                                               (0, 0, 0, 0)),
            "vs": jax.lax.dynamic_update_slice(cache["vs"], vs,
                                               (0, 0, 0, 0)),
        }
    return {
        "k": jax.lax.dynamic_update_slice(cache["k"], k_all,
                                          (0, 0, 0, 0, 0)),
        "v": jax.lax.dynamic_update_slice(cache["v"], v_all,
                                          (0, 0, 0, 0, 0)),
    }
