"""Model families: dense LLaMA-style (transformer.py) and MoE (moe.py).

``model_module(cfg)`` dispatches on ModelConfig.num_experts so the engine,
trainer, and checkpoint code serve either family through one surface:
both modules expose ``init_params(cfg, seed)``, ``prefill`` (MoE returns an
extra aux-loss scalar — use ``serving_prefill`` to normalize), and
``decode_step``; cache layout and the tied LM head live in transformer.py
and are shared.
"""

from __future__ import annotations

from ..config import ModelConfig
from . import moe, transformer  # noqa: F401


def model_module(cfg: ModelConfig):
    return moe if cfg.num_experts > 1 else transformer


def serving_prefill(cfg: ModelConfig, params, tokens, positions, attn=None):
    """(hidden, (k_all, v_all)) for either family (drops MoE aux loss).
    ``attn`` (dense only): attention-op override — see transformer.prefill."""
    if cfg.num_experts > 1:
        out = moe.prefill(cfg, params, tokens, positions)
    else:
        out = transformer.prefill(cfg, params, tokens, positions, attn=attn)
    return out[0], out[1]


def init_params(cfg: ModelConfig, seed: int = 0):
    return model_module(cfg).init_params(cfg, seed)
