"""Mixture-of-Experts transformer with expert parallelism (the 'ep' axis).

New capability (the reference has no intra-model parallelism at all,
SURVEY.md §2.2); this is the TPU-idiomatic MoE recipe: dense einsum
dispatch/combine with a static capacity (GShard/Switch style) so shapes
stay fixed under jit, experts stacked on a leading [E] axis that GSPMD
shards over the mesh's 'ep' axis — the all-to-alls fall out of the einsum
shardings, no hand-written collectives.

Layer structure mirrors models/transformer.py (RMSNorm / RoPE / GQA
attention / scanned layers); only the FFN is replaced by top-2 routed
experts.  Prefill additionally returns the load-balancing auxiliary loss
(Switch §2.2: E · Σ_e fraction_e · mean_prob_e), which the trainer adds to
the LM loss.  Decode computes every expert for the (few) decode tokens and
combines by gate weight — at batch-size-per-step scale that is cheaper and
simpler than capacity dispatch.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

from ..config import ModelConfig
from ..ops import attention
from ..ops import quant
from . import transformer

Params = Dict[str, Any]


# =============================================================================
# Init
# =============================================================================

def init_params(cfg: ModelConfig, seed: int = 0) -> Params:
    """Dense-transformer params with the FFN replaced by E stacked experts
    plus a router; structure otherwise matches transformer.init_params."""
    base = transformer.init_params(cfg, seed)
    key = jax.random.PRNGKey(seed ^ 0x3E0E)
    dtype = jnp.dtype(cfg.dtype)
    h, f, l, e = cfg.hidden_size, cfg.ffn_size, cfg.num_layers, cfg.num_experts

    def normal(key, shape, scale=0.02):
        return (scale * jax.random.normal(key, shape, jnp.float32)).astype(dtype)

    ks = jax.random.split(key, 4)
    layers = dict(base["layers"])
    for dense_key in ("w_gate", "w_up", "w_down"):
        layers.pop(dense_key)
    layers.update({
        "w_router": normal(ks[0], (l, h, e)),
        "w_gate": normal(ks[1], (l, e, h, f)),
        "w_up": normal(ks[2], (l, e, h, f)),
        "w_down": normal(ks[3], (l, e, f, h)),
    })
    return {**base, "layers": layers}


# =============================================================================
# Routed FFN
# =============================================================================

def _top2_gates(router_logits: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """[T, E] logits -> (combine weights [T, E] with ≤2 nonzeros renormed,
    probs [T, E] float32 for the aux loss)."""
    probs = jax.nn.softmax(router_logits.astype(jnp.float32), axis=-1)
    e = probs.shape[-1]
    idx1 = jnp.argmax(probs, axis=-1)
    mask1 = jax.nn.one_hot(idx1, e, dtype=probs.dtype)
    masked = probs * (1.0 - mask1)
    idx2 = jnp.argmax(masked, axis=-1)
    mask2 = jax.nn.one_hot(idx2, e, dtype=probs.dtype)
    gates = probs * (mask1 + mask2)
    gates = gates / jnp.maximum(gates.sum(-1, keepdims=True), 1e-9)
    return gates, probs


def moe_ffn_train(cfg: ModelConfig, lp: Dict[str, jax.Array], x: jax.Array
                  ) -> Tuple[jax.Array, jax.Array]:
    """Capacity-dispatch MoE FFN for full sequences.

    x: [B, S, H] -> (out [B, S, H], aux loss scalar).  Tokens over
    capacity for their expert are dropped (contribute zero), the standard
    static-shape trade-off.
    """
    b, s, h = x.shape
    t = b * s
    e = cfg.num_experts
    xt = x.reshape(t, h)

    gates, probs = _top2_gates(quant.matmul(xt, lp["w_router"]))          # [T, E]

    capacity = max(1, int(cfg.moe_capacity_factor * 2 * t / e))
    # Position of each token within its expert's buffer, per expert.
    sel = (gates > 0).astype(jnp.int32)                      # [T, E]
    pos = jnp.cumsum(sel, axis=0) * sel - 1                  # [T, E]
    keep = (pos >= 0) & (pos < capacity)
    pos = jnp.clip(pos, 0, capacity - 1)

    # dispatch [T, E, C]: one-hot of each kept token's buffer slot.
    dispatch = (keep[..., None]
                & (jax.nn.one_hot(pos, capacity, dtype=jnp.bool_)))
    dispatch = dispatch.astype(x.dtype)
    combine = dispatch * gates.astype(x.dtype)[..., None]    # weights in

    expert_in = jnp.einsum("tec,th->ech", dispatch, xt)      # [E, C, H]
    gate_h = quant.expert_einsum("ech,ehf->ecf", expert_in, lp["w_gate"])
    up_h = quant.expert_einsum("ech,ehf->ecf", expert_in, lp["w_up"])
    act = jax.nn.silu(gate_h) * up_h
    expert_out = quant.expert_einsum("ecf,efh->ech", act, lp["w_down"])
    out = jnp.einsum("tec,ech->th", combine, expert_out)

    # Switch load-balance loss: E · Σ_e fraction_of_tokens_e · mean_prob_e.
    frac = jnp.mean((gates > 0).astype(jnp.float32), axis=0)
    mean_prob = jnp.mean(probs, axis=0)
    aux = e * jnp.sum(frac * mean_prob)
    return out.reshape(b, s, h), aux


def moe_ffn_decode(cfg: ModelConfig, lp: Dict[str, jax.Array], x: jax.Array
                   ) -> jax.Array:
    """Decode-step MoE FFN: x [B, H].  Computes all experts for the few
    decode tokens and combines by (top-2) gate weight — no dispatch."""
    gates, _ = _top2_gates(quant.matmul(x, lp["w_router"]))               # [B, E]
    gate_h = quant.expert_einsum("bh,ehf->bef", x, lp["w_gate"])
    up_h = quant.expert_einsum("bh,ehf->bef", x, lp["w_up"])
    act = jax.nn.silu(gate_h) * up_h
    outs = quant.expert_einsum("bef,efh->beh", act, lp["w_down"])     # [B, E, H]
    return jnp.einsum("be,beh->bh", gates.astype(x.dtype), outs)


# =============================================================================
# Forward passes (mirror transformer.prefill / decode_step)
# =============================================================================

def prefill(cfg: ModelConfig, params: Params, tokens: jax.Array,
            positions: jax.Array
            ) -> Tuple[jax.Array, Tuple[jax.Array, jax.Array], jax.Array]:
    """Like transformer.prefill but returns (hidden, (k_all, v_all), aux):
    the summed load-balance loss across layers."""
    b, s = tokens.shape
    d = cfg.head_dim
    x = quant.embed_rows(params["embed"], tokens)
    sin, cos = transformer.rope_sincos(positions, d, cfg.rope_theta)

    def layer(carry, lp):
        x, aux = carry
        h_in = transformer.rms_norm(x, lp["ln1"], cfg.norm_eps)
        q = quant.matmul(h_in, lp["wq"]).reshape(b, s, cfg.num_heads, d)
        k = quant.matmul(h_in, lp["wk"]).reshape(b, s, cfg.num_kv_heads, d)
        v = quant.matmul(h_in, lp["wv"]).reshape(b, s, cfg.num_kv_heads, d)
        q = transformer.apply_rope(q, sin, cos)
        k = transformer.apply_rope(k, sin, cos)
        attn = attention.causal(q, k, v, impl=cfg.attention_impl
                                ).reshape(b, s, cfg.num_heads * d)
        x = x + quant.matmul(attn, lp["wo"])
        ffn_out, layer_aux = moe_ffn_train(
            cfg, lp, transformer.rms_norm(x, lp["ln2"], cfg.norm_eps))
        return (x + ffn_out, aux + layer_aux), (k, v)

    (x, aux), (k_all, v_all) = jax.lax.scan(
        layer, (x, jnp.float32(0.0)), params["layers"])
    hidden = transformer.rms_norm(x, params["final_ln"], cfg.norm_eps)
    return hidden, (k_all, v_all), aux


def chunk_prefill(cfg: ModelConfig, params: Params, tokens: jax.Array,
                  start: jax.Array, true_len: jax.Array,
                  kv: transformer.KVCache, window: int = 0
                  ) -> Tuple[jax.Array, transformer.KVCache]:
    """Prefill a chunk against an existing cache — MoE twin of
    ``transformer.chunk_prefill`` (same contract; the chunk's tokens go
    through capacity-dispatch MoE FFN, aux loss dropped as in serving).
    Enables session KV prefix reuse (engine/prefix_cache.py) for MoE tiers.

    APPROXIMATE vs a cold full-history prefill: expert capacity is computed
    from the chunk's token count, so which tokens get capacity-dropped can
    differ from running the whole prompt at once — outputs are close
    (cosine ≈ 1) but not bit-identical.  Tiers needing exact replay should
    set enable_prefix_cache=False (see config.TierConfig).
    """
    b, s_c = tokens.shape
    d = cfg.head_dim
    x = quant.embed_rows(params["embed"], tokens)
    positions = start[:, None] + jnp.arange(s_c)[None, :]
    q_pos = jnp.minimum(positions, jnp.maximum(true_len, 1)[:, None] - 1)
    sin, cos = transformer.rope_sincos(positions, d, cfg.rope_theta)

    def layer(x, scanned):
        lp, k_cache, v_cache = scanned
        h_in = transformer.rms_norm(x, lp["ln1"], cfg.norm_eps)
        q = quant.matmul(h_in, lp["wq"]).reshape(b, s_c, cfg.num_heads, d)
        k = quant.matmul(h_in, lp["wk"]).reshape(b, s_c, cfg.num_kv_heads, d)
        v = quant.matmul(h_in, lp["wv"]).reshape(b, s_c, cfg.num_kv_heads, d)
        q = transformer.apply_rope(q, sin, cos)
        k = transformer.apply_rope(k, sin, cos)

        def write(cache, new):
            def one(c, n, p):
                return jax.lax.dynamic_update_slice(c, n, (p, 0, 0))
            return jax.vmap(one)(cache, new, start)
        k_cache = write(k_cache, k)
        v_cache = write(v_cache, v)

        k_att = k_cache[:, :window] if window else k_cache
        v_att = v_cache[:, :window] if window else v_cache
        attn = attention.chunk(q, k_att, v_att, q_pos,
                               impl=cfg.attention_impl)
        x = x + quant.matmul(attn.reshape(b, s_c, cfg.num_heads * d), lp["wo"])
        ffn_out, _ = moe_ffn_train(
            cfg, lp, transformer.rms_norm(x, lp["ln2"], cfg.norm_eps))
        return x + ffn_out, (k_cache, v_cache)

    x, (k_new, v_new) = jax.lax.scan(
        layer, x, (params["layers"], kv["k"], kv["v"]))
    hidden = transformer.rms_norm(x, params["final_ln"], cfg.norm_eps)
    return hidden, {"k": k_new, "v": v_new}


def decode_step(cfg: ModelConfig, params: Params, token: jax.Array,
                pos: jax.Array, kv: transformer.KVCache
                ) -> Tuple[jax.Array, transformer.KVCache]:
    """One autoregressive step; same contract as transformer.decode_step."""
    b = token.shape[0]
    d = cfg.head_dim
    x = quant.embed_rows(params["embed"], token)
    sin, cos = transformer.rope_sincos(pos, d, cfg.rope_theta)

    def layer(x, scanned):
        lp, k_cache, v_cache = scanned
        h_in = transformer.rms_norm(x, lp["ln1"], cfg.norm_eps)
        q = quant.matmul(h_in, lp["wq"]).reshape(b, cfg.num_heads, d)
        k = quant.matmul(h_in, lp["wk"]).reshape(b, cfg.num_kv_heads, d)
        v = quant.matmul(h_in, lp["wv"]).reshape(b, cfg.num_kv_heads, d)
        q = transformer.apply_rope(q, sin, cos)
        k = transformer.apply_rope(k, sin, cos)

        def write(cache, new):
            def one(c, n, p):
                return jax.lax.dynamic_update_slice(c, n[None], (p, 0, 0))
            return jax.vmap(one)(cache, new, pos)
        k_cache = write(k_cache, k)
        v_cache = write(v_cache, v)

        attn = attention.decode(q, k_cache, v_cache, pos,
                                impl=cfg.attention_impl)
        x = x + quant.matmul(attn.reshape(b, cfg.num_heads * d), lp["wo"])
        x = x + moe_ffn_decode(
            cfg, lp, transformer.rms_norm(x, lp["ln2"], cfg.norm_eps))
        return x, (k_cache, v_cache)

    x, (k_new, v_new) = jax.lax.scan(
        layer, x, (params["layers"], kv["k"], kv["v"]))
    hidden = transformer.rms_norm(x, params["final_ln"], cfg.norm_eps)
    return transformer.logits_from_hidden(params, hidden), \
        {"k": k_new, "v": v_new}
