"""Persistent XLA compilation cache for the chip entry points.

Every bench/measurement process on this box recompiles the same
programs: the tester builds a fresh Router per config (reference
semantics — routing_chatbot_tester.py:368-376), tpu_round.sh runs each
step as a separate claimant process, and the driver's round-end bench
is yet another process.  On chip each compile is 20-40 s, so the sweep
cost is compile-dominated.  JAX's persistent cache keys serialized
executables by HLO hash on disk — fresh processes (and fresh jit
closures inside one process) deserialize instead of recompiling.

The test suite wires the same thing in tests/conftest.py; this helper
is for the runtime entry points (bench.py, bench.tester, ab_kernels,
training.pretrain).  Call before the first device computation; the
cache dir is env-overridable (JAX_COMPILATION_CACHE_DIR wins if set).
"""

from __future__ import annotations

import os

DEFAULT_CACHE_DIR = "/tmp/dllm_jax_cache"


def enable_persistent_compile_cache(path: str = None) -> str:
    """Point jax at a persistent compilation cache; returns the dir.

    Also exports the env vars so child processes (bench.py's per-kind
    A/B subprocesses, subprocess-driven tests) inherit the same cache.
    Safe to call any time before (or even after) backend init; a
    backend that can't serialize executables just logs and skips —
    never an error.
    """
    path = (path or os.environ.get("JAX_COMPILATION_CACHE_DIR")
            or DEFAULT_CACHE_DIR)
    os.environ["JAX_COMPILATION_CACHE_DIR"] = path
    os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "-1")
    os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS",
                          "0.5")
    import jax
    try:
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", int(
            os.environ["JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES"]))
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          float(os.environ[
                              "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS"]))
    except Exception:      # older jax without a knob: env vars still apply
        pass
    return path
