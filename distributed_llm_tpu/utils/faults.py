"""Injectable fault model for tier engines.

The reference's failure semantics arise naturally from its network stack —
SSH tunnels drop, Flask returns non-JSON, Ollama times out — producing
error-dict shapes like {"error": "Request timed out on Nano (...)"}
(src/models/nano.py:30-40).  An in-process TPU engine has no network layer to
fail, so failover, the perf strategy's fail-penalty, and the health plumbing
need a fault model to stay testable (SURVEY.md §7 hard part 5).

``FaultInjector`` scripts failures per tier: one-shot error queues, sticky
outage flags, artificial latency, transient (retryable) error shapes, and
mid-stream kills (``fail_stream_after`` — the stream dies after N delivered
chunks, exercising the Router's mid-stream failover).  Error payload shapes
mirror the reference client exactly so `Router._is_error` and failover
behave identically.

``FaultSchedule`` layers scripted TIMELINES on top — flaps, sticky
outages, latency spikes, mid-stream kills at chosen offsets — driven on a
background thread while load runs.  The bench's chaos leg and the chaos
soak tests both build their scenarios from it.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import defaultdict, deque
from typing import Any, Callable, Dict, List, Optional, Tuple


class FaultInjector:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._one_shot: Dict[str, deque] = defaultdict(deque)
        self._down: Dict[str, Optional[Dict[str, Any]]] = {}
        self._delay_s: Dict[str, float] = {}
        self._stream_kills: Dict[str, deque] = defaultdict(deque)
        self._publish_fails: Dict[str, deque] = defaultdict(deque)

    # -- scripting ---------------------------------------------------------

    def fail_next(self, tier: str, error: str = "injected fault") -> None:
        """Queue a one-shot failure for the next request to ``tier``."""
        with self._lock:
            self._one_shot[tier].append({"error": error})

    def timeout_next(self, tier: str) -> None:
        """One-shot timeout with the reference's client error shape
        (src/models/nano.py:38)."""
        self.fail_next(
            tier, f"Request timed out on {tier.capitalize()} "
                  "(model cold start / slow inference).")

    def fail_transient(self, tier: str) -> None:
        """One-shot TRANSIENT failure — an error shape the Router's
        bounded retry recognizes as retryable (connection-level, not a
        budget-consuming timeout)."""
        self.fail_next(
            tier, f"Request failed: connection reset by peer on "
                  f"{tier} (transient)")

    def set_down(self, tier: str, error: str = "tier offline") -> None:
        """Sticky outage until ``restore``."""
        with self._lock:
            self._down[tier] = {"error": error}

    def restore(self, tier: str) -> None:
        with self._lock:
            self._down.pop(tier, None)
            self._one_shot.pop(tier, None)
            self._delay_s.pop(tier, None)
            self._stream_kills.pop(tier, None)
            self._publish_fails.pop(tier, None)

    def add_latency(self, tier: str, seconds: float) -> None:
        """Artificial per-request latency (perf-strategy steering tests)."""
        with self._lock:
            self._delay_s[tier] = seconds

    def fail_stream_after(self, tier: str, n_chunks: int,
                          error: str = "injected mid-stream fault") -> None:
        """Queue a one-shot MID-STREAM kill: the next stream started on
        ``tier`` dies (raises) after delivering ``n_chunks`` deltas —
        the decode-loop-death-after-first-token scenario that setup-time
        failover can never catch.  ``restore`` clears pending kills."""
        with self._lock:
            self._stream_kills[tier].append((max(0, int(n_chunks)), error))

    def fail_standby_publish(self, tier: str,
                             error: str = "injected standby publish "
                                          "failure") -> None:
        """Queue a one-shot warm-standby PUBLISH failure: the next
        scale-up that tries to promote a parked standby on ``tier``
        loses it (the publish raises; the scale path records the error
        and falls through to building fresh capacity) — what a standby
        whose device went away mid-park looks like.  ``restore``
        clears pending failures."""
        with self._lock:
            self._publish_fails[tier].append(error)

    # -- hooks called by TierClient ----------------------------------------

    def intercept(self, tier: str) -> Optional[Dict[str, Any]]:
        """Return an error payload to short-circuit the request, else None.
        Applies scripted latency as a side effect."""
        with self._lock:
            delay = self._delay_s.get(tier, 0.0)
            down = self._down.get(tier)
            shot = self._one_shot[tier].popleft() if self._one_shot[tier] else None
        if delay > 0:
            time.sleep(delay)
        if down is not None:
            return dict(down)
        if shot is not None:
            return shot
        return None

    def stream_kill(self, tier: str) -> Optional[Tuple[int, str]]:
        """Pop the next scheduled mid-stream kill for ``tier`` (one-shot):
        (chunks to deliver before dying, error message), or None."""
        with self._lock:
            kills = self._stream_kills.get(tier)
            return kills.popleft() if kills else None

    def standby_publish_fail(self, tier: str) -> Optional[str]:
        """Pop the next scheduled standby-publish failure for ``tier``
        (one-shot): the error message, or None.  Consulted by
        ``ReplicatedTierClient._scale_up`` before promoting a parked
        warm standby to membership."""
        with self._lock:
            fails = self._publish_fails.get(tier)
            return fails.popleft() if fails else None


def crash_replica_engine(engine) -> bool:
    """Kill a continuous-batching engine's scheduler loop mid-decode
    with NO cleanup — the replica-crash fault (ISSUE 20).  The loop
    thread exits; its decoding slots and queued requests strand (callers
    block on ``done.wait()``, streams stall), the progress heartbeat
    goes stale, so the decode watchdog reads WEDGED and the
    HealthMonitor's next probe routes the replica into
    ``restart_replica`` — the rescue path's entry point.  Returns False
    when there is no running loop to kill."""
    stop = getattr(engine, "_stop", None)
    if stop is None or getattr(engine, "_thread", None) is None:
        return False
    stop.set()
    wake = getattr(engine, "_wake", None)
    if wake is not None:
        wake.set()
    return True


def maybe_break_stream(faults: Optional["FaultInjector"], tier: str,
                       handle):
    """Apply a scripted mid-stream kill to a freshly-built stream handle
    (shared by the local and remote tier clients): pops the next
    ``fail_stream_after`` entry for ``tier`` and wraps the handle so it
    dies after that many chunks.  No injector / no kill scheduled → the
    handle unchanged."""
    if faults is None:
        return handle
    kill = faults.stream_kill(tier)
    if kill is None:
        return handle
    n, err = kill
    logging.getLogger(__name__).warning(
        "tier %s: scripted mid-stream kill after %d chunks", tier, n)
    return BrokenStream(handle, n, err)


class BrokenStream:
    """Stream wrapper that dies after ``n_chunks`` deltas — what a chip
    wedging mid-decode looks like to the consumer.  Keeps the wrapped
    handle's ``.result`` surface (None until/unless the underlying stream
    finished, which a killed one never does)."""

    def __init__(self, handle, n_chunks: int, error: str):
        self._handle = handle
        self._n = n_chunks
        self._error = error

    def __iter__(self):
        served = 0
        it = iter(self._handle)
        while True:
            if served >= self._n:
                close = getattr(self._handle, "close", None)
                if callable(close):
                    close()
                raise RuntimeError(self._error)
            try:
                delta = next(it)
            except StopIteration:
                return                    # shorter than the kill point
            served += 1
            yield delta

    @property
    def result(self):
        return getattr(self._handle, "result", None)


class BlockStarver:
    """Memory-pressure fault: temporarily confiscate free blocks from a
    paged engine's BlockAllocator (engine/paged_kv.py) — what a co-tenant
    grabbing HBM, a parked-prefix burst, or an undersized pool looks like
    to the scheduler.  Admission's KV gate starts rejecting, and running
    slots that can no longer grow exercise the preempt→replay path.

    ``starve(n)`` takes up to ``n`` currently-free blocks (repeatable:
    holdings accumulate); ``release()`` returns every held block.  The
    starver never touches allocated blocks, so in-flight sequences keep
    their KV — exactly like real external pressure."""

    def __init__(self, allocator):
        self.allocator = allocator
        self._held: List[int] = []
        self._lock = threading.Lock()

    def starve(self, n: int) -> int:
        """Confiscate up to ``n`` free blocks; returns how many were
        actually taken (the pool may already be tighter than asked)."""
        take = min(max(0, int(n)), self.allocator.available)
        got = self.allocator.alloc(take) if take else None
        if not got:
            return 0
        with self._lock:
            self._held.extend(got)
        return len(got)

    def release(self) -> int:
        """Return every confiscated block to the pool."""
        with self._lock:
            held, self._held = self._held, []
        if held:
            self.allocator.free(held)
        return len(held)

    @property
    def held(self) -> int:
        with self._lock:
            return len(self._held)


class FaultSchedule:
    """A scripted fault timeline over a FaultInjector, driven on a
    background thread: the chaos harness's scenario language.

    Events are (offset_s, fn, args) applied relative to ``start()``;
    convenience builders cover the common shapes.  ``stop()`` halts the
    driver and restores every tier it ever touched, so a schedule can
    never leak a sticky outage past its run.
    """

    def __init__(self, injector: FaultInjector):
        self.injector = injector
        self._events: List[Tuple[float, str, Callable[[], None]]] = []
        self._tiers: set = set()
        self._starvers: List[BlockStarver] = []
        self._paused_spills: List[Any] = []
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.applied: List[Tuple[float, str]] = []   # (offset_s, label)
        self._lock = threading.Lock()

    # -- builders -----------------------------------------------------------

    def at(self, offset_s: float, label: str,
           fn: Callable[[], None], tier: Optional[str] = None
           ) -> "FaultSchedule":
        self._events.append((float(offset_s), label, fn))
        if tier:
            self._tiers.add(tier)
        return self

    def outage(self, tier: str, start_s: float, end_s: float,
               error: str = "tier offline (scheduled outage)"
               ) -> "FaultSchedule":
        """Sticky down from start_s to end_s."""
        self.at(start_s, f"down:{tier}",
                lambda: self.injector.set_down(tier, error), tier)
        self.at(end_s, f"up:{tier}",
                lambda: self.injector.restore(tier), tier)
        return self

    def flaps(self, tier: str, n: int, period_s: float, down_s: float,
              start_s: float = 0.0) -> "FaultSchedule":
        """n down/up cycles: down for down_s out of every period_s."""
        for i in range(n):
            t0 = start_s + i * period_s
            self.outage(tier, t0, t0 + down_s,
                        error=f"tier offline (flap {i + 1}/{n})")
        return self

    def latency_spike(self, tier: str, start_s: float, end_s: float,
                      seconds: float) -> "FaultSchedule":
        self.at(start_s, f"lag:{tier}",
                lambda: self.injector.add_latency(tier, seconds), tier)
        self.at(end_s, f"unlag:{tier}",
                lambda: self.injector.add_latency(tier, 0.0), tier)
        return self

    def starve_blocks(self, allocator, start_s: float, end_s: float,
                      n: int, tier: Optional[str] = None
                      ) -> "FaultSchedule":
        """Memory-pressure window: confiscate up to ``n`` free blocks
        from ``allocator`` at ``start_s``, return them at ``end_s``.
        ``stop()`` also releases (a schedule may never leak pool
        blocks past its run)."""
        starver = BlockStarver(allocator)
        self._starvers.append(starver)
        label = tier or "pool"
        self.at(start_s, f"starve:{label}:{n}",
                lambda: starver.starve(n), tier)
        self.at(end_s, f"unstarve:{label}", starver.release, tier)
        return self

    def kill_stream(self, tier: str, at_s: float, after_chunks: int
                    ) -> "FaultSchedule":
        self.at(at_s, f"streamkill:{tier}",
                lambda: self.injector.fail_stream_after(
                    tier, after_chunks,
                    error="scheduled mid-stream kill"), tier)
        return self

    def kill_replica(self, engine_getter: Callable[[], Any], at_s: float,
                     tier: Optional[str] = None) -> "FaultSchedule":
        """Crash one replica's scheduler loop mid-decode at ``at_s``
        (``crash_replica_engine``).  ``engine_getter`` resolves the
        victim at FIRE time, not build time — engines are rebuilt
        across restarts, so a handle captured now could point at a
        corpse by then."""
        def _kill():
            crash_replica_engine(engine_getter())
        self.at(at_s, f"replicakill:{tier or 'replica'}", _kill, tier)
        return self

    def wedge_spill_copier(self, spill_getter: Callable[[], Any],
                           start_s: float, end_s: float,
                           tier: Optional[str] = None) -> "FaultSchedule":
        """Wedge the host-KV spill copier thread from ``start_s`` to
        ``end_s`` (``HostKVSpill.pause``/``resume``): demote copies park
        in COPYING, promotion claims find nothing RESIDENT, and the
        promote-stall race-fallback path runs — what a host memcpy
        stall under memory-bandwidth pressure looks like."""
        def _hold(fn_name):
            def _apply():
                spill = spill_getter()
                fn = getattr(spill, fn_name, None)
                if callable(fn):
                    fn()
                if fn_name == "pause" and spill is not None:
                    self._paused_spills.append(spill)
                elif fn_name == "resume":
                    try:
                        self._paused_spills.remove(spill)
                    except ValueError:
                        pass
            return _apply
        self.at(start_s, f"spillwedge:{tier or 'spill'}",
                _hold("pause"), tier)
        self.at(end_s, f"spillunwedge:{tier or 'spill'}",
                _hold("resume"), tier)
        return self

    def fail_standby_publish(self, tier: str, at_s: float
                             ) -> "FaultSchedule":
        """Queue a one-shot warm-standby publish failure at ``at_s`` —
        the next scale-up on ``tier`` loses its first parked standby
        and must build fresh capacity instead."""
        self.at(at_s, f"publishfail:{tier}",
                lambda: self.injector.fail_standby_publish(tier), tier)
        return self

    # -- driver -------------------------------------------------------------

    def duration_s(self) -> float:
        return max((t for t, _, _ in self._events), default=0.0)

    def start(self) -> "FaultSchedule":
        if self._thread is not None:
            return self
        self._stop.clear()
        events = sorted(self._events, key=lambda e: e[0])
        t0 = time.monotonic()

        def drive():
            for offset, label, fn in events:
                wait = offset - (time.monotonic() - t0)
                if wait > 0 and self._stop.wait(wait):
                    return
                if self._stop.is_set():
                    return
                try:
                    fn()
                except Exception:
                    pass
                with self._lock:
                    self.applied.append(
                        (round(time.monotonic() - t0, 3), label))

        self._thread = threading.Thread(target=drive, daemon=True,
                                        name="fault-schedule")
        self._thread.start()
        return self

    def join(self, timeout: Optional[float] = None) -> None:
        if self._thread is not None:
            self._thread.join(timeout)

    def stop(self) -> None:
        """Halt the driver and restore every touched tier (no schedule
        may leak a sticky outage — or confiscated pool blocks — past
        its run)."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        for tier in self._tiers:
            self.injector.restore(tier)
        for starver in self._starvers:
            starver.release()
        for spill in list(self._paused_spills):
            # A schedule may never leave a copier wedged past its run
            # (same contract as sticky outages and confiscated blocks).
            try:
                spill.resume()
            except Exception:
                pass
        self._paused_spills = []
