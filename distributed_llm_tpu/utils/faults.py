"""Injectable fault model for tier engines.

The reference's failure semantics arise naturally from its network stack —
SSH tunnels drop, Flask returns non-JSON, Ollama times out — producing
error-dict shapes like {"error": "Request timed out on Nano (...)"}
(src/models/nano.py:30-40).  An in-process TPU engine has no network layer to
fail, so failover, the perf strategy's fail-penalty, and the health plumbing
need a fault model to stay testable (SURVEY.md §7 hard part 5).

``FaultInjector`` scripts failures per tier: one-shot error queues, sticky
outage flags, and artificial latency.  Error payload shapes mirror the
reference client exactly so `Router._is_error` and failover behave
identically.
"""

from __future__ import annotations

import threading
import time
from collections import defaultdict, deque
from typing import Any, Dict, Optional


class FaultInjector:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._one_shot: Dict[str, deque] = defaultdict(deque)
        self._down: Dict[str, Optional[Dict[str, Any]]] = {}
        self._delay_s: Dict[str, float] = {}

    # -- scripting ---------------------------------------------------------

    def fail_next(self, tier: str, error: str = "injected fault") -> None:
        """Queue a one-shot failure for the next request to ``tier``."""
        with self._lock:
            self._one_shot[tier].append({"error": error})

    def timeout_next(self, tier: str) -> None:
        """One-shot timeout with the reference's client error shape
        (src/models/nano.py:38)."""
        self.fail_next(
            tier, f"Request timed out on {tier.capitalize()} "
                  "(model cold start / slow inference).")

    def set_down(self, tier: str, error: str = "tier offline") -> None:
        """Sticky outage until ``restore``."""
        with self._lock:
            self._down[tier] = {"error": error}

    def restore(self, tier: str) -> None:
        with self._lock:
            self._down.pop(tier, None)
            self._one_shot.pop(tier, None)
            self._delay_s.pop(tier, None)

    def add_latency(self, tier: str, seconds: float) -> None:
        """Artificial per-request latency (perf-strategy steering tests)."""
        with self._lock:
            self._delay_s[tier] = seconds

    # -- hook called by TierClient ----------------------------------------

    def intercept(self, tier: str) -> Optional[Dict[str, Any]]:
        """Return an error payload to short-circuit the request, else None.
        Applies scripted latency as a side effect."""
        with self._lock:
            delay = self._delay_s.get(tier, 0.0)
            down = self._down.get(tier)
            shot = self._one_shot[tier].popleft() if self._one_shot[tier] else None
        if delay > 0:
            time.sleep(delay)
        if down is not None:
            return dict(down)
        if shot is not None:
            return shot
        return None
