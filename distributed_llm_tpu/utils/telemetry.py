"""Telemetry: the TPU equivalent of the reference's power subsystem.

The reference SSHes a jtop sampler onto each Jetson (src/tests/
logging_power.py: 1 Hz lines "<ts>: <total_mW>"), scp's the logs back, and
integrates power over each query's [start, end) window into mJ
(src/tests/routing_chatbot_tester.py:239-254).  Cloud TPU exposes no
per-query power, so the same *shape* of subsystem samples what the hardware
does expose — per-device HBM occupancy (``device.memory_stats()``) — at the
same 1 Hz cadence, writes the same "<ts>: <value>" log format, and offers
the same trapezoidal window integration.  The integral is bytes·s (an
occupancy proxy, NOT millijoules); CSV columns keep the reference schema
with this documented substitution (SURVEY.md §5.1).

Also here: ``jax.profiler`` capture helpers — the flamegraph-class tooling
the reference never had — and a phase-timer used by the serving stack to
attribute time to tokenize/prefill/decode/detokenize.
"""

from __future__ import annotations

import contextlib
import threading
import time
from collections import defaultdict
from datetime import datetime
from typing import Any, Dict, Iterable, List, Optional, Tuple

import jax


def device_memory_snapshot() -> List[Dict[str, Any]]:
    """Per-device memory stats (empty dict per device where unsupported,
    e.g. host CPU backends)."""
    out = []
    for dev in jax.devices():
        try:
            stats = dev.memory_stats() or {}
        except Exception:
            stats = {}
        out.append({
            "device": dev.id,
            "platform": dev.platform,
            "bytes_in_use": stats.get("bytes_in_use", 0),
            "peak_bytes_in_use": stats.get("peak_bytes_in_use", 0),
            "bytes_limit": stats.get("bytes_limit", 0),
        })
    return out


@contextlib.contextmanager
def profiler_trace(log_dir: str = "/tmp/dllm_tpu_trace"):
    """Capture a jax.profiler trace (TensorBoard / xprof readable) around a
    block — per-op HLO timings on TPU, the flamegraph the reference lacked."""
    jax.profiler.start_trace(log_dir)
    try:
        yield log_dir
    finally:
        jax.profiler.stop_trace()


class PhaseTimer:
    """Accumulates wall-time per named phase across queries, plus the
    roofline work (FLOPs / HBM bytes / tokens, utils/roofline.py) the
    engines report for each device phase — so utilization = work / time
    falls out of one snapshot."""

    def __init__(self):
        self.totals: Dict[str, float] = defaultdict(float)
        self.counts: Dict[str, int] = defaultdict(int)
        self.work: Dict[str, Dict[str, float]] = defaultdict(
            lambda: defaultdict(float))

    @contextlib.contextmanager
    def phase(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.totals[name] += time.perf_counter() - t0
            self.counts[name] += 1

    def add_work(self, name: str, **amounts: float) -> None:
        """Accumulate work counters (flops, hbm_bytes, tokens) for a phase."""
        acc = self.work[name]
        for key, val in amounts.items():
            acc[key] += float(val)

    def summary(self) -> Dict[str, Dict[str, float]]:
        return {name: {"total_s": round(self.totals[name], 4),
                       "count": self.counts[name],
                       "mean_ms": round(1000 * self.totals[name]
                                        / max(1, self.counts[name]), 3)}
                for name in self.totals}

    def work_summary(self) -> Dict[str, Dict[str, float]]:
        """Per-phase accumulated work joined with its measured seconds."""
        return {name: {**{k: round(v, 2) for k, v in acc.items()},
                       "seconds": round(self.totals.get(name, 0.0), 4)}
                for name, acc in self.work.items() if acc}


def engine_stats(engine) -> Dict[str, Any]:
    """Per-engine observability snapshot shared by GET /stats
    (serving/app.py) and bench.py's tier section — one assembler so the
    two surfaces cannot drift.  Tolerates any engine type (remote tiers
    have none; batching/speculative engines expose different subsets)."""
    entry: Dict[str, Any] = {}
    if engine is None:
        return entry
    if getattr(engine, "phases", None) is not None:
        entry["phases"] = engine.phases.summary()
        work = engine.phases.work_summary()
        if work:
            entry["work"] = work
    if getattr(engine, "prefix_cache", None) is not None:
        entry["prefix_cache"] = engine.prefix_cache.stats()
    kv_fn = getattr(engine, "kv_stats", None)
    if callable(kv_fn):
        # Pool-pressure + sharing snapshot (ISSUE 10): free/reclaimable
        # supply as the admission gate sees it, plus shared/pinned block
        # counts and the dedup ratio — GET /stats shows WHAT the KV gate
        # is gating on, inspectable without a metrics scrape.
        try:
            entry["kv"] = kv_fn()
        except Exception:
            pass
    if hasattr(engine, "acceptance_rate"):
        entry["speculative_acceptance_rate"] = round(
            engine.acceptance_rate, 4)
    return entry


class TierTelemetry:
    """1 Hz sampler of per-tier device memory, window-integrable.

    Mirrors the reference power logger's lifecycle: ``start()`` (SSH nohup
    equivalent), ``stop()``, ``save_log(tier, path)`` ("scp" equivalent,
    same "<ts>: <value>" line format), and ``energy_for_window`` with the
    v2 harness's trapezoidal accumulation semantics.
    """

    def __init__(self, tiers: Iterable[str], interval_s: float = 1.0,
                 tier_devices: Optional[Dict[str, List[int]]] = None):
        self.tiers = list(tiers)
        self.interval_s = interval_s
        # Without an explicit tier→device map, every tier reads all devices
        # (correct for the single-chip bench; multi-slice deployments pass
        # the carved submesh device ids).
        self.tier_devices = tier_devices or {}
        self.samples: Dict[str, List[Tuple[float, float]]] = {
            t: [] for t in self.tiers}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _sample_once(self) -> None:
        now = time.time()
        snap = device_memory_snapshot()
        by_id = {s["device"]: s for s in snap}
        for tier in self.tiers:
            ids = self.tier_devices.get(tier)
            rows = ([by_id[i] for i in ids if i in by_id]
                    if ids else snap)
            total = float(sum(r["bytes_in_use"] for r in rows))
            self.samples[tier].append((now, total))

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self._sample_once()
            except Exception:
                pass
            self._stop.wait(self.interval_s)

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="tier-telemetry")
        self._thread.start()

    def stop(self) -> None:
        if self._thread is None:
            return
        self._stop.set()
        self._thread.join(timeout=2 * self.interval_s)
        self._thread = None

    def save_log(self, tier: str, path: str) -> None:
        """Write the reference power-log line format: "<unix_ts>: <value>"."""
        with open(path, "w") as f:
            for ts, val in self.samples.get(tier, []):
                f.write(f"{ts:.3f}: {val:.0f}\n")

    def energy_for_window(self, tier: str, start: datetime,
                          end: datetime) -> float:
        """Integrate the piecewise-linear sample trace over [start, end)
        (the v2 harness's mW·s accumulation, routing_chatbot_tester.py:
        239-254).  Units: <sample unit>·s.

        Unlike the reference (whose multi-second Jetson queries always
        spanned several 1 Hz samples), TPU queries can finish between two
        samples — so the trace is interpolated to the exact window edges,
        and a window inside one sampling interval still integrates a
        nonzero slice.
        """
        t0, t1 = start.timestamp(), end.timestamp()
        pts = self.samples.get(tier, [])
        if not pts or t1 <= t0:
            return 0.0

        def value_at(t: float) -> float:
            # Clamp outside the trace; linear interpolation inside.
            if t <= pts[0][0]:
                return pts[0][1]
            if t >= pts[-1][0]:
                return pts[-1][1]
            for (ta, va), (tb, vb) in zip(pts, pts[1:]):
                if ta <= t <= tb:
                    if tb == ta:
                        return va
                    return va + (vb - va) * (t - ta) / (tb - ta)
            return pts[-1][1]

        knots = ([(t0, value_at(t0))]
                 + [(ts, v) for ts, v in pts if t0 < ts < t1]
                 + [(t1, value_at(t1))])
        total = 0.0
        for (ta, va), (tb, vb) in zip(knots, knots[1:]):
            total += 0.5 * (va + vb) * (tb - ta)
        return total
