"""Roofline accounting: model FLOPs and HBM traffic per serving phase.

The reference could never answer "is it actually fast?" — Ollama hid the
arithmetic (src/devices/nano_api.py:76 just forwards a JSON blob), so its
benchmarks report wall-clock only.  Here every engine phase also accounts
the work the hardware did — matmul FLOPs and HBM bytes, derived from the
model config and the *computed* shapes (padded buckets, masked cache
spans), not the logical token counts — so the bench can report MFU and
HBM-bandwidth utilization against chip peaks and place each phase on the
roofline: prefill is compute-bound (judge by MFU), decode is
bandwidth-bound (judge by HBM utilization).

Conventions (How-to-Scale-Your-Model accounting):
- a matmul of a token through P params is 2·P FLOPs;
- attention scores+values for one query over a span of s keys is 4·h·s
  FLOPs per layer (2 for QKᵀ, 2 for A·V, h = hidden width already
  aggregated over heads);
- masked positions COUNT: the XLA/Pallas decode kernels compute the full
  allocated cache span and mask, so that is the work the MXU executed;
- decode HBM traffic per step = one full weight-set read (shared by the
  whole batch) + each sequence's KV-cache span read.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

# Chip peaks for utilization denominators.  The bench box is a single
# TPU v5e (16 GB HBM): 197 TFLOP/s bf16 on the MXU, 819 GB/s HBM.
# Overridable for other chips without a code change.
_V5E_PEAK_FLOPS = 197e12
_V5E_PEAK_HBM = 819e9


def chip_peaks(backend: str) -> Optional[Dict[str, float]]:
    """Peak FLOP/s and HBM B/s for the backend, or None when utilization
    is meaningless (host CPU fallback has no published roofline here)."""
    if backend == "cpu":
        return None
    return {
        "peak_flops": float(os.environ.get("DLLM_PEAK_FLOPS",
                                           _V5E_PEAK_FLOPS)),
        "peak_hbm_bytes_per_s": float(os.environ.get("DLLM_PEAK_HBM",
                                                     _V5E_PEAK_HBM)),
        "chip": os.environ.get("DLLM_CHIP", "tpu_v5e"),
    }


def active_matmul_params(cfg) -> int:
    """Matmul params touched per token: attention + active FFN experts
    (top-2 routing for MoE) + the tied LM head.  Embedding lookup is a
    gather, not a matmul."""
    h, f, l = cfg.hidden_size, cfg.ffn_size, cfg.num_layers
    kv = cfg.num_kv_heads * cfg.head_dim
    attn = h * h + 2 * h * kv + h * h
    ffn = 3 * h * f
    if cfg.num_experts > 1:
        ffn *= 2                       # top-2 of E experts per token
    return l * (attn + ffn) + cfg.vocab_size * h


def weight_bytes(cfg, quantize: str = "none") -> int:
    """Resident weight bytes streamed by one decode step.  For MoE this is
    the FULL expert set: the dense-dispatch einsum reads every expert's
    weights regardless of routing (models/moe.py)."""
    h, f, l = cfg.hidden_size, cfg.ffn_size, cfg.num_layers
    kv = cfg.num_kv_heads * cfg.head_dim
    attn = h * h + 2 * h * kv + h * h
    ffn = 3 * h * f * max(1, cfg.num_experts)
    per_param = 1 if quantize == "int8" else 2
    body = l * (attn + ffn) * per_param
    # Embedding/head + norms stay bf16 even under int8 weight-only quant.
    return body + (cfg.vocab_size * h + (2 * l + 1) * h) * 2


def kv_bytes_per_pos(cfg, kv_quantize: str = "none") -> int:
    """K+V bytes per cached position: bf16, or int8 + f32 per-row scales
    (engine/paged_kv.py)."""
    rows = 2 * cfg.num_layers * cfg.num_kv_heads
    if kv_quantize == "int8":
        return rows * (cfg.head_dim + 4)
    return rows * cfg.head_dim * 2


def prefill_work(cfg, end: int, start: int = 0,
                 wbytes: Optional[int] = None) -> Dict[str, float]:
    """Work for prefilling positions [start, end) of one sequence (end is
    the PADDED/computed span — bucket or chunk stride, not the logical
    prompt length).  Causal attention: position p attends to p+1 keys."""
    pm = active_matmul_params(cfg)
    n = max(0, end - start)
    h, l = cfg.hidden_size, cfg.num_layers
    flops = 2.0 * pm * n + 2.0 * h * l * float(end**2 - start**2)
    if wbytes is None:
        wbytes = weight_bytes(cfg)
    # One weight-set read per chunk (approximation: prefill is
    # compute-bound, the weight term only anchors the roofline position),
    # plus the KV written for the new span.
    hbm = float(wbytes) + kv_bytes_per_pos(cfg) * n
    return {"flops": flops, "hbm_bytes": hbm, "tokens": n}


def decode_work(cfg, steps: int, ctx: int, batch: int = 1,
                wbytes: Optional[int] = None,
                kv_quantize: str = "none",
                kv_ctx: Optional[float] = None,
                kv_batch: Optional[int] = None) -> Dict[str, float]:
    """Work for ``steps`` sequential decode steps of a ``batch`` of
    sequences whose kernels each span ``ctx`` cached positions (the
    ALLOCATED span the full-span XLA kernels compute over, masked or not).

    ``kv_ctx`` overrides the span per sequence when the ACTIVE kernel
    prunes past the causal frontier: the Pallas decode kernels stream (and
    compute) only ceil((pos+1)/bk) KV tiles, not the allocated span — the
    engines pass ``ops.attention.decode_kv_span`` so hbm_util reflects the
    tiles the kernel actually moved.  ``kv_batch`` overrides how many
    DISTINCT cache streams one step reads: a chunked verify of γ+1 queries
    reads its shared cache once, not γ+1 times (engine/speculative.py)."""
    pm = active_matmul_params(cfg)
    h, l = cfg.hidden_size, cfg.num_layers
    span = float(ctx) if kv_ctx is None else min(float(kv_ctx), float(ctx))
    kvb = batch if kv_batch is None else kv_batch
    flops = float(steps) * batch * (2.0 * pm + 4.0 * h * l * span)
    if wbytes is None:
        wbytes = weight_bytes(cfg)
    hbm = float(steps) * (wbytes + kvb
                          * kv_bytes_per_pos(cfg, kv_quantize) * span)
    return {"flops": flops, "hbm_bytes": hbm, "tokens": steps * batch}


def utilization(work: Dict[str, Any], seconds: float,
                peaks: Optional[Dict[str, float]]) -> Dict[str, Any]:
    """MFU + HBM utilization for accumulated work over measured seconds."""
    out: Dict[str, Any] = {
        "tflops_per_s": round(work.get("flops", 0.0) / max(seconds, 1e-9)
                              / 1e12, 4),
        "hbm_gb_per_s": round(work.get("hbm_bytes", 0.0) / max(seconds, 1e-9)
                              / 1e9, 3),
    }
    if peaks:
        out["mfu"] = round(work.get("flops", 0.0)
                           / max(seconds, 1e-9) / peaks["peak_flops"], 4)
        out["hbm_util"] = round(work.get("hbm_bytes", 0.0)
                                / max(seconds, 1e-9)
                                / peaks["peak_hbm_bytes_per_s"], 4)
    return out
