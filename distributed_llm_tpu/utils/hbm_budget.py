"""Serving HBM budget: does a tier's model + KV actually fit its submesh?

VERDICT r2 #2: the flagship presets (nano_1b / orin_8b / moe_8x1b) were
"dead config" — nothing ever verified that orin_8b (~7B params, ~14 GB
bf16) plus a KV pool fits its tp=4 submesh at 16 GB/chip.  This module
budgets a tier with ``jax.eval_shape`` over the REAL code paths — the
model family's init (models/__init__.py), the serving quantizer
(ops/quant.quantize_params), the contiguous cache / paged pool
allocators, and the tensor-parallel sharding rules
(parallel/sharding.py) — so no weights materialize and the 8B-class
budget runs on the CPU test box.

The reference never had this problem (Ollama picks GGML files sized for
the Jetson); a framework that owns its engine has to prove residency.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence

import jax
import numpy as np

# The bench chip (TPU v5e) — overridable per deployment.
DEFAULT_HBM_PER_CHIP_GB = 16.0


def _tree_gb(tree: Any) -> float:
    return sum(int(np.prod(l.shape)) * l.dtype.itemsize
               for l in jax.tree_util.tree_leaves(tree)) / 1e9


def _sharded_tree_gb(tree: Any, shardings: Any) -> float:
    """Per-chip bytes under NamedShardings (max over chips is what HBM
    residency cares about; these rules shard evenly)."""
    total = 0
    for leaf, sh in zip(jax.tree_util.tree_leaves(tree),
                        jax.tree_util.tree_leaves(shardings)):
        shard = sh.shard_shape(leaf.shape)
        total += int(np.prod(shard)) * leaf.dtype.itemsize
    return total / 1e9


def tier_hbm_budget(tier, devices: Optional[Sequence[jax.Device]] = None,
                    hbm_per_chip_gb: float = DEFAULT_HBM_PER_CHIP_GB,
                    mesh: Optional[jax.sharding.Mesh] = None
                    ) -> Dict[str, Any]:
    """Budget ``tier`` against its submesh.

    Returns {params_gb_per_chip, kv_gb_per_chip, total_gb_per_chip,
    chips, hbm_per_chip_gb, fits, headroom_gb}.  ``devices`` backs the
    tp>1 sharding evaluation (any devices do — CPU works); tp=1 tiers
    need none.

    With ``mesh`` (the tier's DEPLOYED submesh, e.g. from
    ``carve_tier_meshes``) the budget reads tp/sp/ep from the mesh axes
    instead of re-deriving them from the tier against the full local
    device count — a cluster's later tiers see only the chips earlier
    tiers left over, so the standalone derivation can certify a larger
    (smaller-footprint) sharding than any deployment uses.  Use
    ``cluster_hbm_budget`` to budget a whole cluster that way.
    """
    from .. import models
    from ..ops.quant import quantize_params

    cfg = tier.model()
    if mesh is not None:
        tp = mesh.shape.get("tp", 1)
        ep = mesh.shape.get("ep", 1)
        sp = mesh.shape.get("sp", 1)
        devices = list(mesh.devices.flat)
    else:
        tp = tier.tp
        sp = tier.sp
        # Budget the degree carve_tier_meshes would actually DEPLOY: ep
        # must divide the expert count and fit the devices (param_specs
        # silently replicates a non-dividing axis, which would certify a
        # sharding no deployment uses).
        from ..parallel.mesh import _fit_ep
        n_avail = (len(devices) if devices is not None
                   else len(jax.devices()))
        ep = _fit_ep(tier, n_avail, tp)
    chips = tp * max(1, sp, ep)

    # -- params (the serving engines' exact init + quantize pipeline) -----
    quantized = tier.quantize == "int8"
    if quantized:
        shapes = jax.eval_shape(
            lambda: quantize_params(models.init_params(cfg, 0)))
    else:
        shapes = jax.eval_shape(lambda: models.init_params(cfg, 0))
    if tp > 1 or ep > 1:
        from ..parallel.sharding import (param_shardings,
                                         quantized_param_shardings)
        if mesh is None:
            need = tp * ep
            if devices is None or len(devices) < need:
                devices = jax.devices()
            if len(devices) < need:
                raise ValueError(f"need {need} devices to evaluate the "
                                 f"tp×ep sharding, have {len(devices)}")
            from ..parallel.mesh import ep_tp_mesh, tp_mesh
            mesh = (ep_tp_mesh(list(devices)[:need], ep, tp) if ep > 1
                    else tp_mesh(list(devices)[:tp], tp))
        shardings = (quantized_param_shardings(cfg, mesh, shapes=shapes)
                     if quantized else param_shardings(cfg, mesh))
        params_gb = _sharded_tree_gb(shapes, shardings)
    else:
        params_gb = _tree_gb(shapes)

    # -- KV (the engine the tier would actually build) ---------------------
    if tier.decode_batch > 1:
        from ..engine.paged_kv import PagedConfig, init_pool
        pcfg = PagedConfig(block_size=tier.kv_block_size,
                           max_slots=tier.decode_batch,
                           max_seq_len=cfg.max_seq_len)
        pool = jax.eval_shape(lambda: init_pool(cfg, pcfg,
                                                tier.kv_quantize))
        kv_gb = _tree_gb(pool) / tp     # pool shards its kv-head axis
        # Parked prefix entries hold block lists inside the same pool.
        parked = 0.0
    else:
        from ..models import transformer
        kvq = tier.kv_quantize if cfg.num_experts == 1 else "none"
        cache = jax.eval_shape(
            lambda: transformer.init_kv_cache(cfg, 1, cfg.max_seq_len, kvq))
        # The cache shards its kv-head axis over tp, and — under
        # sequence-parallel decode (dense bf16 caches,
        # parallel/sp_attention.py) — its sequence axis over sp.
        sp_div = (sp if sp > 1 and cfg.num_experts == 1
                  and kvq == "none" else 1)
        kv_gb = _tree_gb(cache) / tp / sp_div
        # Each parked prefix-cache entry pins one full cache
        # (engine/prefix_cache.py, TierConfig.prefix_cache_entries) —
        # except under sequence-parallel decode, where the engine
        # disables prefix reuse (engine/inference.py _sp_shard).
        parked = (kv_gb * tier.prefix_cache_entries
                  if tier.enable_prefix_cache and sp_div == 1 else 0.0)

    total = params_gb + kv_gb + parked
    return {
        "tier": tier.name,
        "model": cfg.name,
        "chips": chips,
        "quantize": tier.quantize,
        "params_gb_per_chip": round(params_gb, 3),
        "kv_gb_per_chip": round(kv_gb + parked, 3),
        "total_gb_per_chip": round(total, 3),
        "hbm_per_chip_gb": hbm_per_chip_gb,
        # ~0.75 GB/chip headroom for activations, compiled program
        # temps and XLA's allocator slack.
        "fits": total <= hbm_per_chip_gb - 0.75,
        "headroom_gb": round(hbm_per_chip_gb - total, 3),
    }


def cluster_hbm_budget(cluster,
                       devices: Optional[Sequence[jax.Device]] = None,
                       hbm_per_chip_gb: float = DEFAULT_HBM_PER_CHIP_GB
                       ) -> Dict[str, Dict[str, Any]]:
    """Budget every local tier of ``cluster`` against the submesh
    ``carve_tier_meshes`` actually hands it.

    Tiers claim chips in declaration order, so a later tier's deployed
    tp/sp/ep can be SMALLER (bigger per-chip footprint) than the tier
    config asks for — budgeting each tier standalone against the full
    pod would miss that.  Remote tiers (``endpoint`` set) are skipped:
    their chips live on another host.
    """
    from ..parallel.mesh import carve_tier_meshes
    meshes = carve_tier_meshes(cluster, devices)
    return {tier.name: tier_hbm_budget(tier, hbm_per_chip_gb=hbm_per_chip_gb,
                                       mesh=meshes[tier.name])
            for tier in cluster.tiers() if not tier.endpoint}
