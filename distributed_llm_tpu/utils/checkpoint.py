"""Checkpoint/resume for model weights and trainer state (Orbax).

The reference has no model checkpointing — its models live inside Ollama
and its only persistent state is the routing cache's JSON round-trip
(SURVEY.md §5.4; kept as QueryRouter.save_cache/load_cache).  Owning the
models makes weight checkpointing a real subsystem:

- **Preemption-safe layout**: each ``Trainer.save`` writes a fresh
  ``<dir>/v<step>`` checkpoint (Orbax's own write is atomic), then swaps
  the ``<dir>/latest`` symlink and prunes all but the newest two versions.
  A kill at any instant leaves a valid, complete checkpoint behind —
  never a half-deleted one (force-overwriting in place would first remove
  the only good copy).
- **One copy of the weights**: the train state (params + optimizer
  moments + step) is written once; serving loads just the ``params``
  subtree via Orbax partial restore instead of keeping a second full
  copy of the weights on disk.
- **Restore is placement-aware**: targets carry explicit shardings, so a
  checkpoint from an 8-chip dp×tp mesh restores straight onto a 1-chip
  serving tier or a different training mesh — resharding happens at
  restore time, never as a conversion step.  (Without explicit shardings
  Orbax replays the *saved* topology, which does not exist on the new
  host.)
"""

from __future__ import annotations

import logging
import os
import re
from typing import Any, Dict, Optional

import jax
import numpy as np
import orbax.checkpoint as ocp

from ..config import ModelConfig
from ..models import init_params as family_init_params

_VERSION_RE = re.compile(r"^v(\d+)$")


def _abspath(path: str) -> str:
    return os.path.abspath(os.path.expanduser(path))


def save_checkpoint(path: str, tree: Any) -> str:
    """Write a pytree of (possibly sharded) jax arrays. Overwrites."""
    path = _abspath(path)
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(path, tree, force=True)
    return path


def restore_checkpoint(path: str, like: Any) -> Any:
    """Restore onto the structure/dtypes/shardings of ``like`` (a concrete
    or abstract-with-sharding pytree)."""
    with ocp.StandardCheckpointer() as ckptr:
        return ckptr.restore(_abspath(path), like)


def restore_subtree(path: str, like: Dict[str, Any]) -> Dict[str, Any]:
    """Partial restore: only the keys present in ``like`` are read; their
    leaves must be ShapeDtypeStructs WITH shardings (explicit placement)."""
    restore_args = ocp.checkpoint_utils.construct_restore_args(like)
    with ocp.PyTreeCheckpointer() as ckptr:
        return ckptr.restore(
            _abspath(path),
            args=ocp.args.PyTreeRestore(item=like, restore_args=restore_args,
                                        partial_restore=True))


def abstract_params(cfg: ModelConfig, shardings: Any) -> Any:
    """ShapeDtypeStruct tree for the model's params, annotated with the
    target shardings (a matching tree or a single Sharding for all)."""
    abstract = jax.eval_shape(lambda: family_init_params(cfg, seed=0))
    if not isinstance(shardings, (dict,)):
        shardings = jax.tree.map(lambda _: shardings, abstract)
    return jax.tree.map(
        lambda a, s: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=s),
        abstract, shardings)


# -- versioned train-state directories --------------------------------------

def _latest_dir(root: str) -> Optional[str]:
    link = os.path.join(_abspath(root), "latest")
    return os.path.realpath(link) if os.path.islink(link) else None


def _swap_latest(root: str, version_dir: str) -> None:
    """Atomically point <root>/latest at version_dir (symlink rename)."""
    link = os.path.join(root, "latest")
    tmp = os.path.join(root, ".latest.tmp")
    if os.path.lexists(tmp):
        os.unlink(tmp)
    os.symlink(os.path.basename(version_dir), tmp)
    os.replace(tmp, link)


def _prune_versions(root: str, keep: int = 2) -> None:
    import shutil
    current = _latest_dir(root)
    versions = sorted(
        (int(m.group(1)), os.path.join(root, d))
        for d in os.listdir(root)
        if (m := _VERSION_RE.match(d)) and os.path.isdir(os.path.join(root, d)))
    for _, d in versions[:-keep]:
        if os.path.realpath(d) != current:
            shutil.rmtree(d, ignore_errors=True)


def save_train_state(path: str, trainer) -> Optional[str]:
    """Checkpoint params + optimizer moments + step counter under a new
    ``v<step>`` version, then atomically publish it as ``latest``.

    Returns the checkpoint root, or **None when the save was skipped**
    because this exact step is already the published ``latest`` — the
    caller can then advance a step and retry if its state genuinely
    differs (resume from an older version reached by a different path);
    a log warning alone gave no programmatic signal (ADVICE r5)."""
    root = _abspath(path)
    os.makedirs(root, exist_ok=True)
    version_dir = os.path.join(root, f"v{trainer.step_count}")
    if os.path.realpath(version_dir) == _latest_dir(root):
        # Already published at this exact step (save_every divided
        # max_steps, so the loop's save and the final save coincide).
        # The orbax save would force-overwrite the LIVE artifact in
        # place — a preemption mid-rewrite would leave 'latest' pointing
        # at a half-written dir, breaking the kill-at-any-instant
        # invariant.  In the in-run double-save case the state is
        # identical; a run that reaches the published step by a
        # DIFFERENT path (resumed from an older version) is discarded
        # here — None tells the caller, who can step once more to
        # publish such a state under a fresh version.
        logging.getLogger(__name__).warning(
            "save skipped: %s is already the published 'latest' at step "
            "%d; if this run's state differs (resume from an older "
            "version), advance one step so it publishes under a new "
            "version", version_dir, trainer.step_count)
        return None
    # A stale same-step dir from an abandoned/rolled-back run is NOT the
    # published artifact; orbax force-overwrites it below.
    save_checkpoint(os.path.join(version_dir, "state"), {
        "params": trainer.params,
        "opt_state": trainer.opt_state,
        "step": np.asarray(trainer.step_count, np.int64),
    })
    _swap_latest(root, version_dir)
    _prune_versions(root)
    return root


def _mesh_like(tree: Any, mesh: jax.sharding.Mesh) -> Any:
    """Abstract restore target pinned to the mesh: leaves keep their
    NamedSharding if they have one, everything else (e.g. optax's scalar
    step counters, created uncommitted at eager init) restores replicated.
    Restoring onto a committed single-device placement instead would make
    the next jitted step fail its cross-device consistency check."""
    replicated = jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec())

    def leaf(x):
        sharding = getattr(x, "sharding", None)
        if not isinstance(sharding, jax.sharding.NamedSharding):
            sharding = replicated
        return jax.ShapeDtypeStruct(np.shape(x), x.dtype
                                    if hasattr(x, "dtype")
                                    else np.asarray(x).dtype,
                                    sharding=sharding)

    return jax.tree.map(leaf, tree)


def load_train_state(path: str, trainer) -> None:
    """Resume from <path>/latest in place, onto the trainer's mesh."""
    latest = _latest_dir(path)
    if latest is None:
        raise FileNotFoundError(f"no 'latest' checkpoint under {path!r}")
    restored = restore_checkpoint(os.path.join(latest, "state"), {
        "params": _mesh_like(trainer.params, trainer.mesh),
        "opt_state": _mesh_like(trainer.opt_state, trainer.mesh),
        "step": np.asarray(trainer.step_count, np.int64),
    })
    trainer.params = restored["params"]
    trainer.opt_state = restored["opt_state"]
    trainer.step_count = int(restored["step"])


def peek_vocab_size(path: str) -> Optional[int]:
    """Row count of the saved embedding table, read from checkpoint
    METADATA only (no tensor bytes) — lets scripts detect a
    stale-vocabulary artifact (e.g. a byte-level 512 vocab from before the
    subword migration) before trying to serve it.  None if unreadable."""
    latest = _latest_dir(path)
    target = os.path.join(latest, "state") if latest else _abspath(path)
    try:
        with ocp.PyTreeCheckpointer() as ckptr:
            meta = ckptr.metadata(target)
        # Orbax returns a StepMetadata whose pytree lives under
        # item_metadata.tree (older releases exposed .tree directly).
        tree = getattr(getattr(meta, "item_metadata", None), "tree", None)
        if tree is None:
            tree = getattr(meta, "tree", meta)
        embed = tree["params"]["embed"]
        return int(embed.shape[0])
    except Exception:
        return None


def load_params_for_tier(path: str, cfg: ModelConfig,
                         mesh: Optional[jax.sharding.Mesh] = None,
                         devices: Optional[Any] = None) -> Dict[str, Any]:
    """Load serving weights, placed for the tier's submesh (tensor-sharded
    when a mesh is given, single-device otherwise).  ``path`` may be a
    Trainer.save directory (its ``latest`` version's params subtree is
    read) or a weights-only checkpoint."""
    if mesh is not None:
        from ..parallel.sharding import param_shardings
        shardings: Any = param_shardings(cfg, mesh)
    else:
        dev = (list(devices)[0] if devices else jax.devices()[0])
        shardings = jax.sharding.SingleDeviceSharding(dev)
    like = abstract_params(cfg, shardings)

    latest = _latest_dir(path)
    if latest is not None:
        return restore_subtree(os.path.join(latest, "state"),
                               {"params": like})["params"]
    return restore_checkpoint(path, like)
