"""Minimal stdlib-only WSGI web framework with a Flask-compatible surface.

The reference's HTTP layer is Flask (src/app.py, src/devices/*_api.py), but
this image has no flask package and nothing can be installed (zero egress).
This module implements exactly the subset the serving layer uses — `Flask`,
`@app.route`, `jsonify`, the `request` proxy (`get_json`, `args`), tuple
`(response, status)` returns, `app.test_client()`, and a threaded
`app.run()` on wsgiref — so the serving code keeps the reference's idioms
and drops in real Flask when present (see http_compat.py).
"""

from __future__ import annotations

import json
import threading
from socketserver import ThreadingMixIn
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple
from urllib.parse import parse_qs, urlsplit
from wsgiref.simple_server import WSGIServer, make_server

_local = threading.local()


class BadRequest(Exception):
    """flask/werkzeug BadRequest parity: raised by ``Request.json`` on a
    missing or unparseable body; the dispatcher maps it to a 400."""


class Request:
    def __init__(self, method: str, path: str, query: str, body: bytes,
                 content_type: str = "application/json"):
        self.method = method
        self.path = path
        self.args = _Args(parse_qs(query))
        self._body = body
        self.content_type = content_type

    def get_json(self, silent: bool = False) -> Optional[Any]:
        try:
            return json.loads(self._body.decode("utf-8")) if self._body else None
        except (ValueError, UnicodeDecodeError):
            if silent:
                return None
            raise

    @property
    def json(self) -> Optional[Any]:
        """flask.Request.json parity (the reference app reads it,
        /root/reference/src/app.py): a missing/unparseable body is a 400,
        matching Flask's BadRequest; a literal JSON ``null`` body parses
        to None like Flask's does."""
        if not self._body:
            raise BadRequest("request body must be JSON")
        try:
            return self.get_json()
        except (ValueError, UnicodeDecodeError) as exc:
            raise BadRequest(f"invalid JSON body: {exc}") from exc


class _Args:
    def __init__(self, parsed: Dict[str, List[str]]):
        self._parsed = parsed

    def get(self, key: str, default: Optional[str] = None) -> Optional[str]:
        vals = self._parsed.get(key)
        return vals[0] if vals else default


class _RequestProxy:
    """Thread-local stand-in for flask.request."""

    def __getattr__(self, name: str) -> Any:
        req = getattr(_local, "request", None)
        if req is None:
            raise RuntimeError("no request context")
        return getattr(req, name)


request = _RequestProxy()


class Response:
    def __init__(self, body: bytes, status: int = 200,
                 content_type: str = "application/json"):
        self.body = body
        self.status_code = status
        self.content_type = content_type

    def get_json(self) -> Any:
        return json.loads(self.body.decode("utf-8")) if self.body else None

    @property
    def text(self) -> str:
        return self.body.decode("utf-8", errors="replace")


def jsonify(obj: Any = None, **kwargs: Any) -> Response:
    payload = kwargs if kwargs else obj
    return Response(json.dumps(payload).encode("utf-8"))


class StreamingResponse(Response):
    """Chunked response: body is produced by an iterator of str/bytes
    (used for SSE streaming; WSGI yields each chunk as it arrives)."""

    def __init__(self, chunks: Iterable[Any],
                 content_type: str = "text/event-stream"):
        super().__init__(b"", 200, content_type)
        self.chunks = chunks

    def iter_encoded(self) -> Iterable[bytes]:
        for chunk in self.chunks:
            yield chunk.encode("utf-8") if isinstance(chunk, str) else chunk

    @property
    def text(self) -> str:
        # Draining for tests: consume the iterator once.
        if not self.body:
            self.body = b"".join(self.iter_encoded())
        return self.body.decode("utf-8", errors="replace")


def _coerce(rv: Any) -> Response:
    status = 200
    if isinstance(rv, tuple):
        rv, status = rv
    if isinstance(rv, Response):
        rv.status_code = status if status != 200 else rv.status_code
        return rv
    if isinstance(rv, (dict, list)):
        resp = jsonify(rv)
        resp.status_code = status
        return resp
    if isinstance(rv, str):
        return Response(rv.encode("utf-8"), status, "text/plain; charset=utf-8")
    if isinstance(rv, bytes):
        return Response(rv, status, "application/octet-stream")
    raise TypeError(f"unsupported view return type: {type(rv)}")


class Flask:
    def __init__(self, name: str):
        self.name = name
        self.extensions: Dict[str, Any] = {}
        self.testing = False
        self._routes: Dict[Tuple[str, str], Callable[[], Any]] = {}

    def route(self, path: str, methods: Optional[Iterable[str]] = None):
        methods = [m.upper() for m in (methods or ["GET"])]

        def deco(fn: Callable[[], Any]):
            for m in methods:
                self._routes[(m, path)] = fn
            return fn
        return deco

    # -- dispatch ----------------------------------------------------------

    def _dispatch(self, req: Request) -> Response:
        fn = self._routes.get((req.method, req.path))
        if fn is None:
            methods = sorted({m for (m, p) in self._routes if p == req.path})
            if req.method == "OPTIONS" and methods:
                # CORS preflight for the browser frontend.
                resp = Response(b"", 204)
                resp.allow_methods = ", ".join(methods + ["OPTIONS"])
                return resp
            if methods:
                return Response(b'{"error": "method not allowed"}', 405)
            return Response(b'{"error": "not found"}', 404)
        _local.request = req
        try:
            return _coerce(fn())
        except BadRequest as exc:
            return Response(json.dumps({"error": str(exc)}).encode(), 400)
        except Exception as exc:
            if self.testing:
                raise
            return Response(
                json.dumps({"error": f"internal error: {exc}"}).encode(), 500)
        finally:
            _local.request = None

    # -- WSGI --------------------------------------------------------------

    def __call__(self, environ, start_response):
        try:
            length = int(environ.get("CONTENT_LENGTH") or 0)
        except ValueError:
            length = 0
        body = environ["wsgi.input"].read(length) if length else b""
        req = Request(
            method=environ.get("REQUEST_METHOD", "GET").upper(),
            path=environ.get("PATH_INFO", "/"),
            query=environ.get("QUERY_STRING", ""),
            body=body,
            content_type=environ.get("CONTENT_TYPE", ""),
        )
        resp = self._dispatch(req)
        streaming = isinstance(resp, StreamingResponse)
        headers = [("Content-Type", resp.content_type),
                   ("Access-Control-Allow-Origin", "*"),
                   ("Access-Control-Allow-Headers", "Content-Type")]
        if not streaming:
            headers.append(("Content-Length", str(len(resp.body))))
        allow = getattr(resp, "allow_methods", None)
        if allow:
            headers.append(("Access-Control-Allow-Methods", allow))
        start_response(
            f"{resp.status_code} {_STATUS.get(resp.status_code, 'OK')}",
            headers)
        if streaming:
            return resp.iter_encoded()
        return [resp.body]

    def run(self, host: str = "127.0.0.1", port: int = 8000,
            threaded: bool = True, debug: bool = False) -> None:
        server_cls = _ThreadingWSGIServer if threaded else WSGIServer
        with make_server(host, port, self, server_class=server_cls) as httpd:
            httpd.serve_forever()

    # -- test client (flask-compatible subset) -----------------------------

    def test_client(self) -> "TestClient":
        return TestClient(self)


class _ThreadingWSGIServer(ThreadingMixIn, WSGIServer):
    daemon_threads = True


class TestClient:
    def __init__(self, app: Flask):
        self.app = app

    def open(self, path: str, method: str = "GET",
             json_body: Any = None) -> Response:
        split = urlsplit(path)
        body = (json.dumps(json_body).encode("utf-8")
                if json_body is not None else b"")
        req = Request(method=method.upper(), path=split.path,
                      query=split.query, body=body)
        return self.app._dispatch(req)

    def get(self, path: str, **kw) -> Response:
        return self.open(path, "GET", kw.get("json"))

    def post(self, path: str, **kw) -> Response:
        return self.open(path, "POST", kw.get("json"))

    def delete(self, path: str, **kw) -> Response:
        return self.open(path, "DELETE", kw.get("json"))


_STATUS = {200: "OK", 204: "No Content", 400: "Bad Request",
           404: "Not Found", 405: "Method Not Allowed",
           500: "Internal Server Error", 504: "Gateway Timeout"}
