"""Flask if installed, else the stdlib micro-framework (utils/webapp.py).

Serving modules import Flask/jsonify/request from here so the same code runs
in this zero-egress image (no flask wheel) and in a normal deployment with
real Flask + flask-cors.
"""

from __future__ import annotations

try:
    from flask import Flask, jsonify, request          # noqa: F401
    HAVE_FLASK = True
except ImportError:
    from .webapp import Flask, jsonify, request       # noqa: F401
    HAVE_FLASK = False


import json as _json


def sse_event(obj) -> str:
    """One server-sent event frame; the single source of the SSE framing
    used by every streaming endpoint (tier /query/stream, app
    /chat/stream)."""
    return f"data: {_json.dumps(obj)}\n\n"


def sse_done_event(result) -> str:
    """The shared terminal event: token count + engine-true TTFT and total
    generation time from a GenerationResult (or None).  total_ms lets a
    cross-host stream consumer (serving/remote.py) feed the perf strategy
    engine-true latency instead of wall time shaped by consumer pacing."""
    return sse_event({
        "done": True,
        "tokens": result.gen_tokens if result else 0,
        "ttft_ms": round(result.ttft_ms, 2) if result else None,
        "total_ms": round(result.total_ms, 2) if result else None,
    })


def streaming_response(chunks, content_type: str = "text/event-stream"):
    """A chunked/SSE response on either backend."""
    if HAVE_FLASK:
        from flask import Response
        return Response(chunks, mimetype=content_type)
    from .webapp import StreamingResponse
    return StreamingResponse(chunks, content_type)


def static_response(body: bytes, content_type: str):
    """A raw-body response with an explicit content type, on either
    backend (used to serve the frontend files)."""
    if HAVE_FLASK:
        from flask import Response
        return Response(body, mimetype=content_type)
    from .webapp import Response
    return Response(body, 200, content_type)


def enable_cors(app) -> None:
    """flask-cors when real Flask is present; webapp.py already sends
    Access-Control-Allow-Origin."""
    if HAVE_FLASK:
        try:
            from flask_cors import CORS
            CORS(app)
        except ImportError:
            pass
