"""Interactive CLI chatbot — reference parity: src/main.py.

A REPL over the Router; "exit"/"quit" stops both tier engines (the
reference's only clean-shutdown path, src/main.py:16-18)."""

from __future__ import annotations

import logging
from typing import Any, Dict, List, Optional

from ..config import PRODUCTION_CFG
from .router import Router


class Chatbot:
    def __init__(self, strategy: str = "semantic",
                 config: Optional[Dict[str, Any]] = None,
                 router: Optional[Router] = None):
        self.router = router or Router(strategy=strategy, config=config)
        self.history: List[Dict[str, str]] = []

    def add_message(self, role: str, content: str) -> None:
        self.history.append({"role": role, "content": content})

    def ask(self, text: str) -> str:
        """One turn: append, route, record the reply."""
        self.add_message("user", text)
        response, _tokens, device = self.router.route_query(self.history)
        reply = (response.get("response", "") if isinstance(response, dict)
                 else str(response))
        self.add_message("assistant", reply)
        return f"[{device}] {reply}"

    def shutdown(self, graceful: bool = True) -> None:
        """Stop both tier engines.  ``graceful`` drains first (stop
        admitting, finish in-flight work under drain_timeout_s) — the
        SIGTERM path and the REPL exit both use it; False keeps the old
        immediate stop for callers that know nothing is in flight."""
        if graceful and callable(getattr(self.router, "drain", None)):
            self.router.drain()
            return
        self.router.nano.server_manager.stop_server()
        self.router.orin.server_manager.stop_server()

    def chat(self) -> None:
        print("Chatbot ready — type 'exit' or 'quit' to stop.")
        while True:
            try:
                text = input("> ").strip()
            except (EOFError, KeyboardInterrupt):
                text = "exit"
            if text.lower() in ("exit", "quit"):
                self.shutdown()
                print("Tier engines stopped. Bye.")
                return
            if text:
                print(self.ask(text))


def main() -> None:
    logging.basicConfig(level=logging.WARNING)
    bot = Chatbot(strategy="semantic", config=dict(PRODUCTION_CFG))
    # SIGTERM mid-conversation drains in-flight work before exit, same
    # contract as the API server (serving/app.py install_drain_handler).
    from .app import install_drain_handler
    install_drain_handler(bot.router)
    bot.chat()


if __name__ == "__main__":
    main()
