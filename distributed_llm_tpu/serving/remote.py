"""Remote tier client — the cross-host (DCN) device-client layer.

Reference parity: src/models/nano.py / src/models/orin.py POST the chat
history as JSON to a per-device Flask server reached through an SSH tunnel
(src/models/nano.py:23-28, src/models/server_manager.py:34-50).  In a
multi-host TPU deployment the same ``/query`` + ``/health`` contract
(serving/tpu_api.py) crosses hosts over plain HTTP on the data-center
network — intra-slice traffic rides ICI inside each engine; only
request/response JSON crosses DCN, exactly like the reference's
router→device hop.

Divergences from the reference client, documented:

- Both tiers get (connect, read) timeouts.  The reference's Orin client has
  NO timeout (src/models/orin.py:26, SURVEY.md §7 quirk list) — an
  asymmetric bug we fix rather than reproduce.
- ``RemoteServerManager.start_server`` bootstraps the remote process when
  the tier config carries a ``spawn_cmd`` — the reference's SSH script
  (a login + nohup, server_manager.py:77-105) expressed as an argv the
  deployment chooses (``ssh host python -m ...`` on a pod, a plain local
  argv in tests/single-host).  It then keeps the same *readiness*
  semantics: poll ``GET /health`` 15×1 s (reference
  server_manager.py:122-134) and raise if the server never comes up.
  Without a spawn_cmd, lifecycle stays with the remote host's supervisor
  (readiness polling only) and ``stop_server`` is a no-op.
- ``process`` opts into the ``stats`` extension of ``/query`` so the
  router's perf strategy and TTFT accounting keep working across hosts
  (the reference measures latency host-side only).

Error-dict shapes match src/models/nano.py:30-40 so Router failover and
``_is_error`` treat remote tiers exactly like local ones.
"""

from __future__ import annotations

import json
import logging
import socket
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import Any, Dict, List, Optional, Sequence, Union

from ..engine.inference import GenerationResult
from ..utils.faults import FaultInjector

logger = logging.getLogger(__name__)

History = Union[str, List[Dict[str, Any]]]

HEALTH_POLL_ATTEMPTS = 15          # reference: 15×1 s (server_manager.py:128)
HEALTH_POLL_INTERVAL_S = 1.0
SPAWN_READY_ATTEMPTS = 120         # spawned child: jax import + engine build
SPAWN_GRACE_S = 180.0              # live child younger than this is starting,
                                   # not wedged — never kill it mid-load
CONNECT_TIMEOUT_S = 5.0            # reference nano.py:28 (5, 180)
READ_TIMEOUT_S = 180.0
CONNECT_RETRY_ATTEMPTS = 3         # connection-refused during tier bring-up
CONNECT_RETRY_BACKOFF_S = 0.2      # (spawned server not yet listening) —
                                   # short bounded retry so cross-host spawn
                                   # races don't surface as instant failover


def _http_json(url: str, payload: Optional[Dict[str, Any]] = None,
               timeout: float = READ_TIMEOUT_S) -> Dict[str, Any]:
    """POST (or GET when payload is None) expecting a JSON body.  Raises
    ValueError on a non-JSON reply — the remote twin of the reference's
    content-type guard (src/models/nano.py:30-33)."""
    data = None if payload is None else json.dumps(payload).encode("utf-8")
    req = urllib.request.Request(
        url, data=data, headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        ctype = resp.headers.get("Content-Type", "")
        body = resp.read()
    if "application/json" not in ctype:
        raise ValueError(f"non-JSON response (Content-Type {ctype!r})")
    return json.loads(body.decode("utf-8"))


class RemoteServerManager:
    """ServerManager surface over a tier server on another host.

    With a ``spawn_cmd`` this manager owns the remote lifecycle the way
    the reference's ServerManager does over SSH (server_manager.py:77-105):
    ``start_server`` launches the argv when /health is dead, then polls
    readiness; ``stop_server`` terminates a process it spawned.  Without
    one, lifecycle belongs to the remote host's supervisor and this
    manager owns *readiness* only.

    ``spawn_cmd`` contract (see TierConfig.spawn_cmd): the command must
    REPLACE any existing remote instance — terminate() here only reaches
    the LOCAL process (for ``ssh host ...`` that is the ssh client, not
    the tier server), so a wedged remote can only be put down by the
    command itself (the reference's script is kill-then-start for the
    same reason)."""

    # Health-monitor contract: a tier served by this manager that was seen
    # running and later stops answering /health has DIED (there is no
    # deliberate local stop for a remote process) — the monitor treats
    # "stopped" as failed and revives it (serving/health.py).
    remote_lifecycle = True

    def __init__(self, base_url: str,
                 connect_timeout: float = CONNECT_TIMEOUT_S,
                 spawn_cmd: Optional[Sequence[str]] = None,
                 spawn_ready_attempts: int = SPAWN_READY_ATTEMPTS,
                 spawn_grace_s: float = SPAWN_GRACE_S):
        self.base_url = base_url.rstrip("/")
        self.connect_timeout = connect_timeout
        self.spawn_cmd = tuple(spawn_cmd) if spawn_cmd else None
        # A process we just spawned gets a longer readiness budget than
        # the reference's 15 s (a tier server imports jax and builds an
        # engine), and a live child is only put down as wedged once its
        # unhealthy age exceeds spawn_grace_s — never mid-startup.
        self.spawn_ready_attempts = spawn_ready_attempts
        self.spawn_grace_s = spawn_grace_s
        self._proc: Optional["subprocess.Popen"] = None
        self._spawned_at: Optional[float] = None

    def is_server_running(self) -> bool:
        try:
            return bool(self.health().get("ok"))
        except Exception:
            return False

    def health(self) -> Dict[str, Any]:
        return _http_json(f"{self.base_url}/health",
                          timeout=self.connect_timeout)

    def _spawn(self) -> None:
        """Launch the supervisor argv, detached (the reference's
        ``nohup ... &`` over SSH): no inherited stdio, own session, so a
        router restart never takes the tier server down with it."""
        import subprocess
        logger.info("spawning remote tier server: %s",
                    " ".join(self.spawn_cmd))
        self._proc = subprocess.Popen(
            list(self.spawn_cmd),
            stdin=subprocess.DEVNULL,
            stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
            start_new_session=True)
        self._spawned_at = time.monotonic()

    def start_server(self, beat=None) -> None:
        """Revive the remote tier if needed, then wait for readiness
        (reference protocol: spawn over SSH then /health poll 15×1 s,
        server_manager.py:77-134; a freshly-spawned child gets the
        longer spawn_ready_attempts budget).  ``beat`` feeds a caller's
        liveness watchdog through the wait."""
        attempts = HEALTH_POLL_ATTEMPTS
        if self.spawn_cmd and not self.is_server_running():
            child_alive = self._proc is not None and self._proc.poll() is None
            if not child_alive:
                self._spawn()              # never spawned, or died with host
            elif (self._spawned_at is not None
                  and time.monotonic() - self._spawned_at > self.spawn_grace_s):
                # A live child unhealthy past the startup grace has
                # wedged (a still-loading server would have answered by
                # now): put it down and respawn.  Inside the grace, keep
                # polling — killing a mid-startup child would loop
                # kill/respawn forever and the tier could never revive.
                self._put_down(self._proc)
                self._spawn()
            attempts = max(attempts, self.spawn_ready_attempts)
        for attempt in range(attempts):
            if self.is_server_running():
                return
            if beat is not None:
                beat()
            if attempt < attempts - 1:
                time.sleep(HEALTH_POLL_INTERVAL_S)
        raise TimeoutError(
            f"remote tier at {self.base_url} not healthy after "
            f"{attempts} attempts")

    @staticmethod
    def _put_down(proc) -> None:
        """Terminate → kill → reap.  The final wait matters: a SIGKILL'd
        child left unreaped is a zombie for the router's lifetime, and a
        successor spawned before the old child released its listen port
        would lose the bind race."""
        proc.terminate()
        try:
            proc.wait(timeout=5)
            return
        except Exception:
            pass
        proc.kill()
        try:
            proc.wait(timeout=5)
        except Exception:
            pass

    def stop_server(self) -> None:
        """Terminate a process WE spawned; no-op otherwise (the remote
        host supervises its own process, see module docstring)."""
        if self._proc is not None and self._proc.poll() is None:
            self._put_down(self._proc)
        self._proc = None
        self._spawned_at = None


class RemoteTierClient:
    """TierClient twin whose engine lives across DCN: same ``.process``,
    ``.server_manager``, ``.last_result`` surface as serving/tiers.py."""

    def __init__(self, name: str, base_url: str,
                 fault_injector: Optional[FaultInjector] = None,
                 read_timeout: float = READ_TIMEOUT_S,
                 spawn_cmd: Optional[Sequence[str]] = None):
        self.name = name
        self.tier = None                   # no local TierConfig — remote
        self.base_url = base_url.rstrip("/")
        self.read_timeout = read_timeout
        self.server_manager = RemoteServerManager(self.base_url,
                                                  spawn_cmd=spawn_cmd)
        self.faults = fault_injector
        self.last_result: Optional[GenerationResult] = None

    def _intercept(self) -> Optional[Dict[str, Any]]:
        if self.faults is not None:
            return self.faults.intercept(self.name)
        return None

    def _probe(self) -> None:
        """Enforce the connect timeout separately (urllib has a single
        timeout knob, and inference can legitimately take the full read
        timeout): a cheap 5 s TCP probe makes a dead/blackholed host fail
        fast into the router's failover instead of stalling each request
        for read_timeout.  The reference client's lazy SSH restart
        (src/models/nano.py:19-21) has no equivalent here — the remote
        host supervises its own process.

        Connection-REFUSED gets a short bounded retry
        (CONNECT_RETRY_ATTEMPTS × CONNECT_RETRY_BACKOFF_S): during tier
        spawn the process exists but hasn't bound its port yet, and that
        bring-up race should cost milliseconds, not an instant failover
        that brands the tier failed.  Timeouts/unreachable hosts are NOT
        retried — a blackholed host would multiply the 5 s probe cost."""
        parts = urllib.parse.urlsplit(self.base_url)
        port = parts.port or (443 if parts.scheme == "https" else 80)
        for attempt in range(CONNECT_RETRY_ATTEMPTS):
            try:
                conn = socket.create_connection(
                    (parts.hostname, port),
                    timeout=self.server_manager.connect_timeout)
                conn.close()
                return
            except ConnectionRefusedError:
                if attempt == CONNECT_RETRY_ATTEMPTS - 1:
                    raise
                logger.info("tier %s: connection refused (bring-up race?) "
                            "— connect retry %d/%d", self.name, attempt + 1,
                            CONNECT_RETRY_ATTEMPTS - 1)
                time.sleep(CONNECT_RETRY_BACKOFF_S * (attempt + 1))

    def process(self, history: History) -> Dict[str, Any]:
        fault = self._intercept()
        if fault is not None:
            return fault
        try:
            self._probe()
            payload = _http_json(f"{self.base_url}/query",
                                 {"query": history, "stats": True},
                                 timeout=self.read_timeout)
        except (urllib.error.URLError, socket.timeout, TimeoutError,
                ValueError, OSError) as exc:
            return {"error": f"Request failed: {exc}"}

        stats = payload.pop("stats", None)
        if isinstance(stats, dict):
            self.last_result = GenerationResult(
                text=payload.get("response", ""),
                token_ids=[],
                prompt_tokens=int(stats.get("prompt_tokens", 0)),
                gen_tokens=int(stats.get("gen_tokens", 0)),
                ttft_ms=float(stats.get("ttft_ms", 0.0)),
                total_ms=float(stats.get("total_ms", 0.0)),
            )
        return payload

    def process_stream(self, history: History):
        """Cross-host token streaming: consume the remote tier's
        /query/stream SSE over DCN and expose the same handle surface as
        a local engine stream (iterable of deltas, ``.result`` once the
        terminal event arrives).  Setup failures — unreachable host,
        non-SSE reply, an error event before any delta — return the
        reference error-dict shape so the router's stream failover picks
        another tier."""
        fault = self._intercept()
        if fault is not None:
            return fault
        resp = None
        try:
            self._probe()
            data = json.dumps({"query": history}).encode("utf-8")
            req = urllib.request.Request(
                f"{self.base_url}/query/stream", data=data,
                headers={"Content-Type": "application/json"})
            resp = urllib.request.urlopen(req, timeout=self.read_timeout)
            ctype = resp.headers.get("Content-Type", "")
            if "text/event-stream" not in ctype:
                body = resp.read(2048).decode("utf-8", "replace")
                return {"error": f"Request failed: non-SSE reply "
                                 f"({ctype!r}): {body[:200]}"}
            handle = _RemoteStream(resp)
            # Surface pre-first-token failures (incl. an SSE error event,
            # which prime raises as RuntimeError) as the error-dict shape —
            # this is the router's stream-failover window.
            handle.prime()
            resp = None                  # handle owns the connection now
            # Scripted mid-stream kills apply to remote tiers too, so the
            # chaos harness can exercise cross-host stream failover.
            from ..utils.faults import maybe_break_stream
            return maybe_break_stream(self.faults, self.name, handle)
        except (urllib.error.URLError, socket.timeout, TimeoutError,
                ValueError, OSError, RuntimeError) as exc:
            return {"error": f"Request failed: {exc}"}
        finally:
            if resp is not None:
                resp.close()


class _RemoteStream:
    """Client side of the /query/stream SSE contract: iterates text
    deltas; ``.result`` is assembled from the terminal ``done`` event
    (engine-true tokens/TTFT from across the wire)."""

    def __init__(self, resp):
        self._resp = resp
        self._buf = b""
        self._queued: List[str] = []
        self._done = False
        self.result: Optional[GenerationResult] = None
        self._text_parts: List[str] = []

    def _read_frames(self):
        """Read until at least one complete SSE frame is handled or the
        connection ends.  Returns True if anything was handled."""
        while not self._done:
            sep = self._buf.find(b"\n\n")
            if sep >= 0:
                frame = self._buf[:sep].decode("utf-8", "replace")
                self._buf = self._buf[sep + 2:]
                if not frame.startswith("data: "):
                    continue
                ev = json.loads(frame[len("data: "):])
                if "delta" in ev:
                    self._queued.append(ev["delta"])
                    self._text_parts.append(ev["delta"])
                    return True
                if ev.get("done"):
                    self._done = True
                    self.result = GenerationResult(
                        text="".join(self._text_parts), token_ids=[],
                        prompt_tokens=0,
                        gen_tokens=int(ev.get("tokens", 0)),
                        ttft_ms=float(ev.get("ttft_ms") or 0.0),
                        # Engine-true generation time from across the
                        # wire: keeps the router's perf feedback immune
                        # to consumer pacing (see sse_done_event).
                        total_ms=float(ev.get("total_ms") or 0.0))
                    self._resp.close()
                    return True
                if "error" in ev:
                    self._done = True
                    self._resp.close()
                    raise RuntimeError(ev["error"])
                continue
            chunk = self._resp.read1(65536) if hasattr(self._resp, "read1") \
                else self._resp.read(65536)
            if not chunk:
                self._done = True
                self._resp.close()
                return False
            self._buf += chunk
        return False

    def prime(self) -> None:
        """Pull the first event so setup-time errors raise here (the
        router failover window), mirroring tiers._PrimedStream."""
        if not self._queued and not self._done:
            self._read_frames()

    def close(self) -> None:
        """Drop the connection (mid-stream kill / abandoning consumer)."""
        self._done = True
        try:
            self._resp.close()
        except Exception:
            pass

    def __iter__(self):
        while True:
            while self._queued:
                yield self._queued.pop(0)
            if self._done:
                return
            if not self._read_frames() and self._done:
                return
