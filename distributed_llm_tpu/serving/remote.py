"""Remote tier client — the cross-host (DCN) device-client layer.

Reference parity: src/models/nano.py / src/models/orin.py POST the chat
history as JSON to a per-device Flask server reached through an SSH tunnel
(src/models/nano.py:23-28, src/models/server_manager.py:34-50).  In a
multi-host TPU deployment the same ``/query`` + ``/health`` contract
(serving/tpu_api.py) crosses hosts over plain HTTP on the data-center
network — intra-slice traffic rides ICI inside each engine; only
request/response JSON crosses DCN, exactly like the reference's
router→device hop.

Divergences from the reference client, documented:

- Both tiers get (connect, read) timeouts.  The reference's Orin client has
  NO timeout (src/models/orin.py:26, SURVEY.md §7 quirk list) — an
  asymmetric bug we fix rather than reproduce.
- ``RemoteServerManager.start_server`` cannot SSH-bootstrap the remote
  process (the reference scripts a login + nohup, server_manager.py:77-105;
  a TPU pod host runs its tier server under its own supervisor).  It keeps
  the same *readiness* semantics instead: poll ``GET /health`` 15×1 s
  (reference server_manager.py:122-134) and raise if the server never
  comes up.  ``stop_server`` is a local no-op for the same reason.
- ``process`` opts into the ``stats`` extension of ``/query`` so the
  router's perf strategy and TTFT accounting keep working across hosts
  (the reference measures latency host-side only).

Error-dict shapes match src/models/nano.py:30-40 so Router failover and
``_is_error`` treat remote tiers exactly like local ones.
"""

from __future__ import annotations

import json
import logging
import socket
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import Any, Dict, List, Optional, Union

from ..engine.inference import GenerationResult
from ..utils.faults import FaultInjector

logger = logging.getLogger(__name__)

History = Union[str, List[Dict[str, Any]]]

HEALTH_POLL_ATTEMPTS = 15          # reference: 15×1 s (server_manager.py:128)
HEALTH_POLL_INTERVAL_S = 1.0
CONNECT_TIMEOUT_S = 5.0            # reference nano.py:28 (5, 180)
READ_TIMEOUT_S = 180.0


def _http_json(url: str, payload: Optional[Dict[str, Any]] = None,
               timeout: float = READ_TIMEOUT_S) -> Dict[str, Any]:
    """POST (or GET when payload is None) expecting a JSON body.  Raises
    ValueError on a non-JSON reply — the remote twin of the reference's
    content-type guard (src/models/nano.py:30-33)."""
    data = None if payload is None else json.dumps(payload).encode("utf-8")
    req = urllib.request.Request(
        url, data=data, headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        ctype = resp.headers.get("Content-Type", "")
        body = resp.read()
    if "application/json" not in ctype:
        raise ValueError(f"non-JSON response (Content-Type {ctype!r})")
    return json.loads(body.decode("utf-8"))


class RemoteServerManager:
    """ServerManager surface over a tier server on another host.

    Lifecycle of the remote process belongs to that host's supervisor; this
    manager owns *readiness*: ``start_server`` blocks until ``/health``
    answers (or raises), ``is_server_running`` probes it once."""

    def __init__(self, base_url: str,
                 connect_timeout: float = CONNECT_TIMEOUT_S):
        self.base_url = base_url.rstrip("/")
        self.connect_timeout = connect_timeout

    def is_server_running(self) -> bool:
        try:
            return bool(self.health().get("ok"))
        except Exception:
            return False

    def health(self) -> Dict[str, Any]:
        return _http_json(f"{self.base_url}/health",
                          timeout=self.connect_timeout)

    def start_server(self, beat=None) -> None:
        """Wait for the remote tier to be ready (reference readiness
        protocol: /health poll 15×1 s, server_manager.py:122-134).
        ``beat`` is accepted for EngineManager signature parity (callers
        feed a liveness watchdog); the wait loop is already bounded."""
        for attempt in range(HEALTH_POLL_ATTEMPTS):
            if self.is_server_running():
                return
            if attempt < HEALTH_POLL_ATTEMPTS - 1:
                time.sleep(HEALTH_POLL_INTERVAL_S)
        raise TimeoutError(
            f"remote tier at {self.base_url} not healthy after "
            f"{HEALTH_POLL_ATTEMPTS} attempts")

    def stop_server(self) -> None:
        """No-op: the remote host supervises its own process (see module
        docstring)."""


class RemoteTierClient:
    """TierClient twin whose engine lives across DCN: same ``.process``,
    ``.server_manager``, ``.last_result`` surface as serving/tiers.py."""

    def __init__(self, name: str, base_url: str,
                 fault_injector: Optional[FaultInjector] = None,
                 read_timeout: float = READ_TIMEOUT_S):
        self.name = name
        self.tier = None                   # no local TierConfig — remote
        self.base_url = base_url.rstrip("/")
        self.read_timeout = read_timeout
        self.server_manager = RemoteServerManager(self.base_url)
        self.faults = fault_injector
        self.last_result: Optional[GenerationResult] = None

    def _intercept(self) -> Optional[Dict[str, Any]]:
        if self.faults is not None:
            return self.faults.intercept(self.name)
        return None

    def _probe(self) -> None:
        """Enforce the connect timeout separately (urllib has a single
        timeout knob, and inference can legitimately take the full read
        timeout): a cheap 5 s TCP probe makes a dead/blackholed host fail
        fast into the router's failover instead of stalling each request
        for read_timeout.  The reference client's lazy SSH restart
        (src/models/nano.py:19-21) has no equivalent here — the remote
        host supervises its own process."""
        parts = urllib.parse.urlsplit(self.base_url)
        port = parts.port or (443 if parts.scheme == "https" else 80)
        conn = socket.create_connection(
            (parts.hostname, port),
            timeout=self.server_manager.connect_timeout)
        conn.close()

    def process(self, history: History) -> Dict[str, Any]:
        fault = self._intercept()
        if fault is not None:
            return fault
        try:
            self._probe()
            payload = _http_json(f"{self.base_url}/query",
                                 {"query": history, "stats": True},
                                 timeout=self.read_timeout)
        except (urllib.error.URLError, socket.timeout, TimeoutError,
                ValueError, OSError) as exc:
            return {"error": f"Request failed: {exc}"}

        stats = payload.pop("stats", None)
        if isinstance(stats, dict):
            self.last_result = GenerationResult(
                text=payload.get("response", ""),
                token_ids=[],
                prompt_tokens=int(stats.get("prompt_tokens", 0)),
                gen_tokens=int(stats.get("gen_tokens", 0)),
                ttft_ms=float(stats.get("ttft_ms", 0.0)),
                total_ms=float(stats.get("total_ms", 0.0)),
            )
        return payload

    def process_stream(self, history: History):
        """Cross-host token streaming: consume the remote tier's
        /query/stream SSE over DCN and expose the same handle surface as
        a local engine stream (iterable of deltas, ``.result`` once the
        terminal event arrives).  Setup failures — unreachable host,
        non-SSE reply, an error event before any delta — return the
        reference error-dict shape so the router's stream failover picks
        another tier."""
        fault = self._intercept()
        if fault is not None:
            return fault
        resp = None
        try:
            self._probe()
            data = json.dumps({"query": history}).encode("utf-8")
            req = urllib.request.Request(
                f"{self.base_url}/query/stream", data=data,
                headers={"Content-Type": "application/json"})
            resp = urllib.request.urlopen(req, timeout=self.read_timeout)
            ctype = resp.headers.get("Content-Type", "")
            if "text/event-stream" not in ctype:
                body = resp.read(2048).decode("utf-8", "replace")
                return {"error": f"Request failed: non-SSE reply "
                                 f"({ctype!r}): {body[:200]}"}
            handle = _RemoteStream(resp)
            # Surface pre-first-token failures (incl. an SSE error event,
            # which prime raises as RuntimeError) as the error-dict shape —
            # this is the router's stream-failover window.
            handle.prime()
            resp = None                  # handle owns the connection now
            return handle
        except (urllib.error.URLError, socket.timeout, TimeoutError,
                ValueError, OSError, RuntimeError) as exc:
            return {"error": f"Request failed: {exc}"}
        finally:
            if resp is not None:
                resp.close()


class _RemoteStream:
    """Client side of the /query/stream SSE contract: iterates text
    deltas; ``.result`` is assembled from the terminal ``done`` event
    (engine-true tokens/TTFT from across the wire)."""

    def __init__(self, resp):
        self._resp = resp
        self._buf = b""
        self._queued: List[str] = []
        self._done = False
        self.result: Optional[GenerationResult] = None
        self._text_parts: List[str] = []

    def _read_frames(self):
        """Read until at least one complete SSE frame is handled or the
        connection ends.  Returns True if anything was handled."""
        while not self._done:
            sep = self._buf.find(b"\n\n")
            if sep >= 0:
                frame = self._buf[:sep].decode("utf-8", "replace")
                self._buf = self._buf[sep + 2:]
                if not frame.startswith("data: "):
                    continue
                ev = json.loads(frame[len("data: "):])
                if "delta" in ev:
                    self._queued.append(ev["delta"])
                    self._text_parts.append(ev["delta"])
                    return True
                if ev.get("done"):
                    self._done = True
                    self.result = GenerationResult(
                        text="".join(self._text_parts), token_ids=[],
                        prompt_tokens=0,
                        gen_tokens=int(ev.get("tokens", 0)),
                        ttft_ms=float(ev.get("ttft_ms") or 0.0),
                        # Engine-true generation time from across the
                        # wire: keeps the router's perf feedback immune
                        # to consumer pacing (see sse_done_event).
                        total_ms=float(ev.get("total_ms") or 0.0))
                    self._resp.close()
                    return True
                if "error" in ev:
                    self._done = True
                    self._resp.close()
                    raise RuntimeError(ev["error"])
                continue
            chunk = self._resp.read1(65536) if hasattr(self._resp, "read1") \
                else self._resp.read(65536)
            if not chunk:
                self._done = True
                self._resp.close()
                return False
            self._buf += chunk
        return False

    def prime(self) -> None:
        """Pull the first event so setup-time errors raise here (the
        router failover window), mirroring tiers._PrimedStream."""
        if not self._queued and not self._done:
            self._read_frames()

    def __iter__(self):
        while True:
            while self._queued:
                yield self._queued.pop(0)
            if self._done:
                return
            if not self._read_frames() and self._done:
                return
