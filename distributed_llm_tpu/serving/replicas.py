"""Replicated tiers — N data-parallel engine replicas behind one tier.

Until ISSUE 12 a tier was exactly ONE engine, so aggregate throughput was
capped at one engine's knee and "scale out" meant an architecture change.
``TierConfig.replicas > 1`` makes the tier own N full ``EngineManager``
replicas — the TPU-serving data-parallel shape (per-replica batching over
a mesh axis; the Gemma-on-TPU comparison in PAPERS.md): when the tier's
submesh has enough devices each replica gets its own device slice
(``replicas × tp`` chips, the ``P('batch')`` data-parallel carve), and on
a single-device/CPU box the replicas are process-local engines sharing
the device.  Every replica keeps the WHOLE single-engine machinery it
had before — bounded admission queue + EWMA wait predictor (PR 1),
watchdog (PR 2), drain (PR 5), chunked prefill (PR 9), shared-prefix KV
(PR 10), tick profiler (PR 11) — because each replica IS a TierClient
over an EngineManager, just not the only one.

Dispatch picks a replica by a two-level policy:

1. **Prefix affinity** (``TierConfig.replica_affinity``): the request is
   tokenized ONCE and every live replica's parked-prefix cache is peeked
   with the same ids — the identical ``select_reuse``/longest-match the
   engines reuse blocks by (engine/prefix_cache.py), so the host-side
   "which replica holds this prefix" map is exactly the caches
   themselves, never a second bookkeeping structure that could drift.
   A match of at least ``replica_affinity_min_tokens`` binds the request
   to that replica — a session (or a same-system-prompt sibling) lands
   where its blocks are parked, so the PR 10 dedup/warm-TTFT win
   survives going multi-replica instead of being diluted N ways.
2. **Least-loaded** otherwise: smallest predicted queue wait
   (queue_depth / slots × EWMA service time — PR 1's admission
   predictor), ties broken by in-flight count then round-robin.  An
   affine replica whose predicted wait exceeds the least-loaded's by
   more than ``replica_affinity_override_s`` is OVERRIDDEN — cache
   locality must not starve the other replicas behind one hot queue.

Each replica has its own breaker sub-gate (serving/breaker.py, keyed
``r<rid>``, thresholds from the cluster's breaker config): dispatch
skips open replicas, stream/sync verdicts feed back per replica, and
admission rejections stay breaker-neutral (healthy backpressure — the
PR 2 rule).  Tier-level ``health()`` / ``kv_stats()`` / ``slot_stats()``
aggregate across replicas with a per-replica breakdown, and the
HealthMonitor probes/restarts replicas INDIVIDUALLY — one wedged
replica degrades capacity (``healthy_replicas``/``replica_count``)
instead of the tier.

**Dynamic membership (ISSUE 18).**  Membership is a LIST OF MEMBER
RECORDS shared between the client and its ReplicaSetManager, each
record carrying a monotonic replica id (``rid``) minted at build time
and NEVER reused — engine-side tier names (``nano/r2``), per-replica
metric labels, and breaker keys are baked at construction, so removal
must not shift surviving replicas' identities the way positional
indices would.  ``scale_to(n)`` is the actuation verb (the autoscaler's
— serving/autoscaler.py — and the operator's): scale-up builds each new
replica OFF-membership, warms it fully against the process XLA compile
cache replica 0 populated (new replicas compile nothing beyond their
own per-engine one-decode-program), and only then publishes it —
deferred go-live, dispatch never sees a cold replica; scale-down picks
the least-affine replica, removes it from membership FIRST (no new
dispatch), waits out its in-flight work, DEMOTES its refcount-1 parked
prefixes through the PR 13 host spill tier and hands the resident
entries to a survivor's store (scale-down costs warm TTFT, never
correctness), then drains and stops it.  All dispatch/probe/aggregate
paths iterate SNAPSHOTS (``list(members)`` — atomic under the GIL) so
they tolerate membership changes mid-flight.

``replicas = 1`` without ``autoscale`` never builds any of this:
build_tiers keeps the plain TierClient/EngineManager path,
byte-identical to pre-replica behavior.
"""

from __future__ import annotations

import dataclasses
import logging
import random
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..config import ClusterConfig, TierConfig
from ..config_registry import env_str
from ..engine.manager import EngineManager
from ..obs import get_observability
from ..obs import spans as obs_spans
from ..obs.spans import current_trace
from ..utils.faults import FaultInjector
from .breaker import CircuitBreaker, OPEN
from .errors import error_dict, is_error_shape
from .tiers import TierClient

logger = logging.getLogger(__name__)

_POLICIES = ("affinity", "load", "random")


def replica_name(i: int) -> str:
    return f"r{i}"


def _split_devices(devices: List, n: int, tp: int) -> List[List]:
    """Per-replica device groups: when the tier's submesh has at least
    ``n × tp`` devices each replica gets its own contiguous ``tp``-chip
    slice (the data-parallel carve — replicas are the 'batch' axis of
    the SNIPPETS.md NamedSharding/P('batch') shape, realized as disjoint
    submeshes because each replica runs its own engine); otherwise every
    replica shares the whole group (process-local replicas — the CPU /
    single-chip box)."""
    per = max(1, tp)
    if len(devices) >= n * per:
        return [devices[i * per:(i + 1) * per] for i in range(n)]
    if per == 1 and devices:
        # Fewer devices than replicas: pin each replica to ONE device
        # round-robin (an unsharded replica must never grow a mesh just
        # because the box is short — extra replicas time-share).
        return [[devices[i % len(devices)]] for i in range(n)]
    return [list(devices) for _ in range(n)]


class _Replica:
    """One live member: the stable replica id (metric/breaker identity,
    minted monotonically, never reused), the request client, and the
    engine manager.  Records are immutable once published — membership
    changes replace/append records, never mutate them."""

    __slots__ = ("rid", "client", "mgr")

    def __init__(self, rid: int, client: TierClient, mgr: EngineManager):
        self.rid = rid
        self.client = client
        self.mgr = mgr

    @property
    def name(self) -> str:
        return replica_name(self.rid)


class ReplicaSetManager:
    """The EngineManager-shaped facade over a tier's replica managers.

    Everything that used to talk to ``tier.server_manager`` — the bench
    harness's start/stop between configs, Router.drain, GET /health —
    keeps working: lifecycle verbs fan out to every replica, liveness
    reads aggregate, and ``health()``/``kv_stats()``/``slot_stats()``
    return tier-level aggregates carrying a per-replica breakdown.
    Probe-surface methods stay lock-free exactly like EngineManager's
    (each sub-manager's health/is_server_running already are), and all
    of them iterate a SNAPSHOT of the member list so dynamic membership
    (scale_to) can change it mid-flight."""

    def __init__(self, tier: TierConfig,
                 managers: Optional[Sequence[EngineManager]] = None,
                 members: Optional[List[_Replica]] = None,
                 standby: Optional[List[_Replica]] = None):
        self.tier = tier
        if members is not None:
            # The SAME list object the ReplicatedTierClient mutates —
            # membership has one source of truth, not two views that
            # could drift.
            self._members = members
        else:
            self._members = [_Replica(i, None, m)
                             for i, m in enumerate(managers or [])]
        # Warm standby pool, shared by reference with the client's
        # scale_to (same one-source-of-truth rule): start_server warms
        # these alongside the sibling members, stop_server stops them.
        # NOT part of the serving surface — health/kv/slot aggregates
        # and drain cover MEMBERS only (a parked engine serves nothing).
        self._standby = standby if standby is not None else []

    # -- replica access -----------------------------------------------------

    @property
    def managers(self) -> List[EngineManager]:
        """Snapshot of the per-replica EngineManagers (historic
        attribute surface, now derived from the member records)."""
        return [r.mgr for r in list(self._members)]

    def replica_managers(self) -> List[EngineManager]:
        """The per-replica EngineManagers — the HealthMonitor's probe and
        restart targets (one wedged replica restarts alone)."""
        return self.managers

    def replica_items(self) -> List[Tuple[int, EngineManager]]:
        """(rid, manager) snapshot — the membership-stable iteration for
        probe keys and metric labels: rids never shift on removal, so
        ``nano/r1`` keeps meaning the same engine across scale events."""
        return [(r.rid, r.mgr) for r in list(self._members)]

    def live_engines(self) -> List[Tuple[str, Any]]:
        """(replica key, engine) for every RUNNING replica — the obs
        surfaces' iteration point (profiler trace, sampler, /stats).
        Never lazy-starts an engine."""
        out = []
        for r in list(self._members):
            engine = getattr(r.mgr, "_engine", None)
            if engine is not None:
                out.append((r.name, engine))
        return out

    # -- lifecycle (ServerManager surface) ----------------------------------

    def start_server(self, beat=None) -> None:
        """Start every replica (idempotent per replica).  Replica 0
        warms FIRST and alone — its warmup populates the in-process XLA
        compile cache — then the siblings AND the warm-standby pool
        warm CONCURRENTLY against that warm cache (the same
        deferred-go-live warm path scale-up rides): concurrent COLD
        compiles of the same programs would just contend, but cache-hit
        warmups only pay tracing.  Standbys warm here, at startup,
        precisely so scale-up never traces mid-peak."""
        members = list(self._members)
        if not members:
            return
        members[0].mgr.start_server(beat=beat)
        rest = members[1:] + list(self._standby)
        if not rest:
            return
        # Every key pre-populated BEFORE the workers start (value
        # overwrites only — safe under the GIL, never a size-changing
        # insert racing the error scan below).
        errors: Dict[int, Optional[BaseException]] = {
            r.rid: None for r in rest}
        threads = []
        for r in rest:
            def _start(r=r):
                try:
                    r.mgr.start_server()
                except BaseException as exc:
                    errors[r.rid] = exc
            t = threading.Thread(target=_start, daemon=True,
                                 name=f"warm-{self.tier.name}-{r.name}")
            threads.append(t)
            t.start()
        # ``beat`` fires from the JOINING loop, not the workers — the
        # bench watchdog's beat callback is not promised thread-safe.
        for t in threads:
            while t.is_alive():
                t.join(timeout=0.5)
                if beat is not None:
                    beat()
        for r in rest:
            if errors[r.rid] is not None:
                raise errors[r.rid]

    def stop_server(self) -> None:
        for mgr in self.managers:
            mgr.stop_server()
        for rec in list(self._standby):
            rec.mgr.stop_server()

    def drain(self, timeout_s: Optional[float] = None) -> Dict[str, Any]:
        """Drain every replica CONCURRENTLY and wait them all out — the
        tier is drained only when its last replica is (each replica
        stops admitting immediately, so the concurrent fan-out never
        extends the deadline past one replica's drain_timeout_s plus
        join slack).  Returns the aggregate summary with the per-replica
        breakdown."""
        timeout = (timeout_s if timeout_s is not None
                   else self.tier.drain_timeout_s)
        t0 = time.monotonic()
        members = list(self._members)
        # Every key pre-populated BEFORE the workers start: a worker
        # abandoned past the join bound may still finish later, and its
        # write must be a value OVERWRITE (safe under the GIL), never a
        # size-changing insert racing the summary's iteration below.
        results: Dict[str, Any] = {
            r.name: {"error": "Request failed: replica drain "
                     "did not return within the join bound"}
            for r in members}
        threads = []
        for r in members:
            def _drain(key=r.name, mgr=r.mgr):
                try:
                    results[key] = mgr.drain(timeout_s=timeout)
                except Exception as exc:   # a dead replica must not
                    results[key] = {"error": f"Request failed: {exc}"}
            t = threading.Thread(target=_drain, daemon=True,
                                 name=f"drain-{self.tier.name}-{r.name}")
            threads.append(t)
            t.start()
        deadline = time.monotonic() + max(0.0, float(timeout)) + 30.0
        for t in threads:
            t.join(timeout=max(0.0, deadline - time.monotonic()))
        summary = {
            "draining_started": True,
            "in_flight_at_start": sum(
                int(r.get("in_flight_at_start", 0))
                for r in results.values() if isinstance(r, dict)),
            "drained": sum(int(r.get("drained", 0))
                           for r in results.values()
                           if isinstance(r, dict)),
            "aborted": sum(int(r.get("aborted", 0))
                           for r in results.values()
                           if isinstance(r, dict)),
            "waited_s": round(time.monotonic() - t0, 3),
            "replicas": dict(results),      # snapshot, not the live dict
        }
        return summary

    @property
    def draining(self) -> bool:
        """The TIER is draining only when every replica is: a partially
        drained tier still serves traffic on the survivors."""
        members = list(self._members)
        return bool(members) and all(r.mgr.draining for r in members)

    def is_server_running(self) -> bool:
        return any(m.is_server_running() for m in self.managers)

    def engine(self):
        """Single-engine compatibility accessor (bench legs and tests
        that introspect ``server_manager.engine()``): the first live
        member's engine, lazy-started like EngineManager.engine()."""
        return list(self._members)[0].mgr.engine()

    # -- aggregate observability --------------------------------------------

    def health(self) -> Dict[str, Any]:
        """Tier-level health = aggregate over per-replica health():
        ``ok`` while ANY replica serves (one wedged replica is degraded
        capacity, not a dead tier), ``wedged`` only when every replica
        is, capacity counters, and the full per-replica breakdown."""
        members = list(self._members)
        reps: Dict[str, Dict[str, Any]] = {}
        for r in members:
            try:
                reps[r.name] = r.mgr.health()
            except Exception as exc:
                reps[r.name] = {"ok": False,  # dllm-lint: disable=error-shape -- health-probe snapshot (GET /health surface), not the tier error path
                                "error": str(exc)[:200]}
        healthy = sum(1 for h in reps.values() if h.get("ok"))
        running = sum(1 for h in reps.values() if h.get("uptime_s"))
        entry: Dict[str, Any] = {
            "ok": healthy > 0,
            "draining": self.draining,
            "tier": self.tier.name,
            "model": self.tier.model_preset,
            "uptime_s": max((h.get("uptime_s") or 0.0)
                            for h in reps.values()) if reps else 0.0,
            "devices": None,
            "replica_count": len(members),
            "healthy_replicas": healthy,
            "degraded": 0 < healthy < len(members),
            "queue_depth": sum(int(h.get("queue_depth") or 0)
                               for h in reps.values()),
            "active_slots": sum(int(h.get("active_slots") or 0)
                                for h in reps.values()),
            "max_slots": sum(int(h.get("max_slots") or 0)
                             for h in reps.values()),
            "replicas": reps,
        }
        devices = [d for h in reps.values()
                   for d in (h.get("devices") or ())]
        if devices:
            entry["devices"] = devices
        if entry["max_slots"]:
            entry["slot_occupancy"] = round(
                entry["active_slots"] / entry["max_slots"], 3)
        if reps and all(h.get("wedged") for h in reps.values()):
            # Every replica stalled: the tier as a whole is wedged (the
            # per-replica watchdog verdicts still drive the individual
            # restarts — this flag is the operator's summary).
            entry["ok"] = False
            entry["wedged"] = True
        if running and not healthy:
            entry["error"] = "no healthy replica (all wedged or failed)"
        return entry

    def kv_stats(self) -> Optional[Dict[str, Any]]:
        """Summed block-pool picture over the live paged replicas, with
        the per-replica breakdown; None when no live replica has a paged
        pool (sequential engines).  ``dedup_ratio`` reports the MAX
        across replicas — the per-replica ratios are the meaningful
        series (block pools are disjoint; averaging them would hide a
        replica whose pool sharing collapsed)."""
        reps: Dict[str, Dict[str, Any]] = {}
        for key, engine in self.live_engines():
            fn = getattr(engine, "kv_stats", None)
            if callable(fn):
                try:
                    reps[key] = fn()
                except Exception:
                    pass
        if not reps:
            return None
        summed = ("free_blocks", "reclaimable_blocks", "total_blocks",
                  "preempted_total", "prefill_pending_blocks",
                  "prefill_backlog_tokens", "shared_blocks",
                  "pinned_entries")
        out: Dict[str, Any] = {k: sum(int(r.get(k, 0))
                                      for r in reps.values())
                               for k in summed}
        first = next(iter(reps.values()))
        out["block_size"] = first.get("block_size")
        out["dedup_ratio"] = max(float(r.get("dedup_ratio", 1.0))
                                 for r in reps.values())
        # Hierarchical-KV spill tier (ISSUE 14): host-tier occupancy and
        # demote/promote counters sum like the pool fields, but only
        # when some replica actually runs a spill tier — a spill-less
        # tier's aggregate keeps its historical shape.  (Affinity
        # already treats a replica's DEMOTED entries as eligible: the
        # per-engine prefix_affinity_tokens peek consults the spill
        # store, so a session follows its spilled prefix home.)
        spill_keys = ("host_entries", "host_blocks", "host_bytes",
                      "host_budget_bytes", "demotions_total",
                      "promotions_total", "promotion_races_total",
                      "demote_inflight", "promote_backlog_blocks")
        for k in spill_keys:
            if any(k in r for r in reps.values()):
                out[k] = sum(int(r.get(k, 0)) for r in reps.values())
        out["replicas"] = reps
        return out

    def slot_stats(self) -> Dict[str, Any]:
        """Summed occupancy over live replicas with per-replica rows."""
        reps: Dict[str, Dict[str, Any]] = {}
        for key, engine in self.live_engines():
            fn = getattr(engine, "slot_stats", None)
            if callable(fn):
                try:
                    reps[key] = fn()
                except Exception:
                    pass
        summed = ("queue_depth", "active_slots", "max_slots",
                  "preempted_total", "prefill_inflight",
                  "prefill_backlog_tokens")
        out: Dict[str, Any] = {k: sum(int(r.get(k, 0))
                                      for r in reps.values())
                               for k in summed}
        out["slot_occupancy"] = round(
            out["active_slots"] / max(1, out["max_slots"]), 3)
        out["replicas"] = reps
        return out

    def prefix_affinity(self, history) -> int:
        """Best parked-prefix match across the live replicas — the
        tier-level probe the Router's cross-TIER affinity steering
        consults (serving/router.py _apply_prefix_affinity): the tier
        holds a conversation's prefix if ANY replica does.  Tokenizes
        once, peeks each replica (non-destructive)."""
        best = 0
        ids = None
        for _key, engine in self.live_engines():
            peek = getattr(engine, "prefix_affinity_tokens", None)
            if not callable(peek):
                continue
            try:
                if ids is None:
                    ids = engine.affinity_token_ids(history)
                best = max(best, int(peek(ids)))
            except Exception:
                continue
        return best


class _ReplicaStream:
    """Stream wrapper feeding the replica breaker its COMPLETION verdict
    (the same rule as the Router's tier-level on_done: setup only proves
    one primed token, so a mid-decode death must reach the breaker as
    the failure it is; a consumer disconnect is not the replica's
    fault).  Transparent to RoutedStream: iteration and ``.result``
    forward to the tier handle."""

    def __init__(self, handle, on_done):
        self._handle = handle
        self._on_done = on_done
        self._fired = False

    def _fire(self, ok: bool) -> None:
        if not self._fired:
            self._fired = True
            try:
                self._on_done(ok)
            except Exception:
                pass

    def __iter__(self):
        try:
            for delta in self._handle:
                yield delta
        except GeneratorExit:
            self._fire(True)              # client disconnect: replica fine
            raise
        except BaseException:
            self._fire(False)
            raise
        self._fire(True)

    @property
    def result(self):
        return self._handle.result


def fail_captured(reqs: Sequence[Any], tier_name: str) -> int:
    """Last-resort release of a rescue capture (ISSUE 20): no sibling
    adopted the requests and the restarted engine cannot take them, so
    each fails with the engine-stopped error shape — the pre-rescue
    outcome.  Blocked callers unblock, streams see the end-of-stream
    sentinel.  Returns the number failed."""
    from ..engine.batching import EngineStoppedError
    n = 0
    for req in reqs:
        req.error = EngineStoppedError(error_dict(
            f"Request failed: tier {tier_name} engine stopped "
            f"mid-flight"))
        tq = getattr(req, "token_queue", None)
        if tq is not None:
            tq.put(None)
        req.done.set()
        n += 1
    return n


class ReplicatedTierClient:
    """The tier client over N replica TierClients — same surface as
    TierClient (``process`` / ``process_stream`` / ``load_snapshot`` /
    ``server_manager`` / ``tier`` / ``name``), with dispatch choosing a
    replica per request (module docstring: affinity → least-loaded, with
    the per-replica breaker veto) and membership actuatable at runtime
    (``scale_to`` — the autoscaler's verb)."""

    def __init__(
        self,
        tier: TierConfig,
        cluster: ClusterConfig,
        mesh=None,
        devices: Optional[List] = None,
        fault_injector: Optional[FaultInjector] = None,
        warmup_on_start: bool = True,
        seed: int = 0,
    ):
        if tier.replicas < 1:
            raise ValueError(f"tier {tier.name}: replicas must be >= 1, "
                             f"got {tier.replicas}")
        if tier.ep > 1 or tier.sp > 1:
            # Replica submeshes are tp-only: silently serving without
            # the configured expert/sequence sharding would look like
            # ep/sp is in effect while it is not (same warn-and-degrade
            # policy as _fit_sp's engine-mismatch rule).
            logger.warning(
                "tier %s: ep=%d sp=%d IGNORED — replicated tiers build "
                "tp-only submeshes per replica (replicas=%d wins); set "
                "replicas=1 to keep expert/sequence parallelism",
                tier.name, tier.ep, tier.sp, tier.replicas)
        self.tier = tier
        self.name = tier.name
        self.faults = fault_injector
        n = tier.replicas
        if getattr(tier, "autoscale", False):
            # Elastic tiers start at the autoscaler's capacity floor
            # (min may exceed the static replicas field, which is then
            # just the pre-elastic default).
            n = max(n, int(getattr(tier, "autoscale_min_replicas", 1)))
        self._devices = (list(mesh.devices.flat) if mesh is not None
                         else list(devices or []))
        from ..parallel.mesh import requested_tp
        self._tp_req = requested_tp(tier)  # honors the DLLM_TP override
        self._seed = seed
        self._warmup_on_start = warmup_on_start
        groups = _split_devices(self._devices, n, self._tp_req)
        # Membership: ONE list of member records, shared by reference
        # with the ReplicaSetManager below.  Mutations are atomic list
        # ops under _scale_lock; every reader takes list() snapshots.
        self._members: List[_Replica] = []
        self._next_rid = 0
        # Scale serialization: the lock guards only the BUSY FLAG, never
        # the minutes-long warm/quiesce work itself — a scale operation
        # blocks on compiles and drains, and holding a lock across that
        # would stall any operator/autoscaler caller (and trips the
        # lock-blocking-call lint).  Membership mutations stay atomic
        # list ops; readers take list() snapshots.
        self._scale_lock = threading.Lock()
        self._scaling = False
        for i in range(n):
            group = groups[i] if i < len(groups) else self._devices
            self._members.append(self._build_replica(self._mint_rid(),
                                                     group))
        # Warm standby pool (autoscale tiers): the replicas between min
        # and max are BUILT here and WARMED by start_server, parked
        # off-membership.  scale_to(up) then publishes a warm standby in
        # milliseconds instead of tracing an engine mid-peak, and
        # scale_to(down) parks the drained replica for the next peak.
        # The pool shares by reference with the ReplicaSetManager below
        # (one source of truth, like the member list).
        self._standby: List[_Replica] = []
        if getattr(tier, "autoscale", False) and \
                getattr(tier, "autoscale_warm_pool", False):
            n_max = max(n, int(getattr(tier, "autoscale_max_replicas", n)))
            for k in range(n, n_max):
                self._standby.append(self._build_replica(
                    self._mint_rid(), self._device_group(k, n_max)))
        self.server_manager = ReplicaSetManager(tier,
                                                members=self._members,
                                                standby=self._standby)
        # Per-replica breaker sub-gate: same thresholds as the cluster's
        # tier-level breaker; breaker_failures=0 disables both.  The
        # tier-level breaker (Router) still owns whole-tier shedding —
        # this one only steers dispatch AWAY from a failing replica
        # while the survivors keep the tier closed.
        self.breaker = CircuitBreaker(
            [r.name for r in self._members],
            failure_threshold=getattr(cluster, "breaker_failures", 0),
            cooldown_s=getattr(cluster, "breaker_cooldown_s", 30.0))
        self._rr_lock = threading.Lock()
        self._rr = 0
        self._rng = random.Random(seed ^ 0x5EED)
        self._last_client: Optional[TierClient] = None
        # Observability sink, lazily resolved so tests/bench can inject
        # a fresh registry after construction (same pattern as the
        # manager's global fallbacks).
        self.obs = None

    # -- membership ----------------------------------------------------------

    @property
    def clients(self) -> List[TierClient]:
        """Snapshot of the live replica clients (historic attribute
        surface, now derived from the member records)."""
        return [r.client for r in list(self._members)]

    def replica_count(self) -> int:
        return len(self._members)

    def _mint_rid(self) -> int:
        rid = self._next_rid
        self._next_rid += 1
        return rid

    def _build_replica(self, rid: int, group: List) -> _Replica:
        """Construct one replica's EngineManager + TierClient (NOT yet
        published to membership, NOT yet started)."""
        # Replica-suffixed tier identity for the ENGINE side: logs,
        # per-replica metric labels (dllm_decode_tick_ms{tier=
        # "nano/r0"}, the per-replica compiled-programs gauge the
        # bench leg pins), profiler timelines.  The CLIENT keeps the
        # base name: error shapes, fault targeting, and trace spans
        # must stay byte-identical to the single-replica tier.
        rtier = dataclasses.replace(
            self.tier, name=f"{self.tier.name}/{replica_name(rid)}")
        if len(group) > 1:
            from ..parallel.mesh import tp_mesh
            # Multi-device group = this replica's own TP submesh,
            # at the TIER's tp degree (a short box sharing devices
            # must not inflate tp past the config).
            mgr = EngineManager(
                rtier,
                mesh=tp_mesh(group,
                             min(max(1, self._tp_req), len(group))),
                seed=self._seed, warmup_on_start=self._warmup_on_start)
        else:
            mgr = EngineManager(rtier, devices=(group or None),
                                seed=self._seed,
                                warmup_on_start=self._warmup_on_start)
        client = TierClient(rtier, mgr, self.faults)
        client.name = self.tier.name  # base-name error/fault identity
        return _Replica(rid, client, mgr)

    def scale_to(self, n: int, reason: str = "manual",
                 timeout_s: Optional[float] = None) -> Dict[str, Any]:
        """Actuate membership to ``n`` replicas (bounded below at 1).
        One scale operation at a time — a busy flag claimed under
        ``_scale_lock``; an overlapping call returns immediately with a
        ``busy`` error rather than queueing behind minutes of warmup
        (the autoscaler treats a refused actuation as retryable).
        Dispatch is NEVER blocked, because membership reads are
        lock-free snapshots and the blocking warm/quiesce work runs
        with no lock held.

        Scale-UP builds the new replicas off-membership and warms them
        CONCURRENTLY and fully (start_server → engine warmup, riding
        the process XLA compile cache an existing replica populated)
        before publishing: deferred go-live — dispatch never sees a
        replica that would block on a cold compile or pay first-touch
        traces mid-peak (a half-warm replica trades cheap actuation
        for a trace storm exactly when the tier is saturated).

        Scale-DOWN retires the least-affine replica: membership removal
        first (no new dispatch), bounded quiesce of in-flight work,
        demote of its refcount-1 parked prefixes through the host spill
        tier with the resident entries HANDED OFF to a survivor's store
        (the shrink costs warm TTFT only where no spill tier exists,
        never correctness), then PR 5 drain-and-stop."""
        n = max(1, int(n))
        summary: Dict[str, Any] = {"target": n, "reason": reason,
                                   "added": [], "removed": [],
                                   "errors": []}
        with self._scale_lock:
            if self._scaling:
                summary["errors"].append("busy: scale in progress")
                summary["replicas"] = len(self._members)
                return summary
            self._scaling = True
        try:
            cur = len(self._members)
            if cur < n:
                self._scale_up(n, summary)
            elif cur > n:
                while len(self._members) > n:
                    info = self._scale_down_one(timeout_s)
                    if info is None:
                        break
                    summary["removed"].append(info)
        finally:
            with self._scale_lock:
                self._scaling = False
        summary["replicas"] = len(self._members)
        return summary

    def _scale_up(self, n: int, summary: Dict[str, Any]) -> None:
        """Add members up to ``n`` (busy flag claimed, no lock held):
        publish warm standbys first (already built and warmed — go-live
        is a breaker key + an atomic append, milliseconds), then build
        and warm any remainder concurrently and publish the
        survivors."""
        while len(self._members) < n and self._standby:
            r = self._standby.pop(0)
            try:
                if self.faults is not None:
                    # Injected warm-standby publish failure (ISSUE 20
                    # fault matrix): the parked engine's device went
                    # away — the publish raises, the handler below
                    # retires the handle, and the loop falls through
                    # to building fresh capacity.
                    fail = self.faults.standby_publish_fail(self.name)
                    if fail is not None:
                        raise RuntimeError(fail)
                r.mgr.start_server()     # idempotent; no-op when warm
                # ensure() is inside the handler's reach: the handle is
                # neither standby nor member here, so any raise before
                # the append below must stop the server or it leaks.
                self.breaker.ensure(r.name)
            except BaseException as exc:
                try:
                    r.mgr.stop_server()
                except Exception:
                    pass
                summary["errors"].append(f"{r.name}: {exc}")
                continue
            self._members.append(r)
            summary["added"].append(r.name)
            logger.info(
                "tier %s: replica %s live (scale-up from warm "
                "standby, %s)", self.name, r.name,
                summary.get("reason"))
        count = len(self._members)
        fresh = []
        for k in range(n - count):
            group = self._device_group(count + k, n)
            fresh.append(self._build_replica(self._mint_rid(), group))
        errors: Dict[int, Optional[BaseException]] = {
            r.rid: None for r in fresh}
        threads = []
        for r in fresh:
            def _warm(r=r):
                try:
                    r.mgr.start_server()
                except BaseException as exc:
                    errors[r.rid] = exc
            t = threading.Thread(target=_warm, daemon=True,
                                 name=f"warm-{self.name}-{r.name}")
            threads.append(t)
            t.start()
        for t in threads:
            t.join()
        for r in fresh:
            if errors[r.rid] is not None:
                summary["errors"].append(
                    f"{r.name}: {errors[r.rid]}")
                try:
                    r.mgr.stop_server()
                except Exception:
                    pass
                continue
            # Go-live: breaker key first (a keyless replica would be
            # waved through ungated), then the atomic membership append.
            self.breaker.ensure(r.name)
            self._members.append(r)
            summary["added"].append(r.name)
            logger.info("tier %s: replica %s live (scale-up, %s)",
                        self.name, r.name, summary.get("reason"))

    def _device_group(self, slot: int, count: int) -> List:
        """The device slice for a NEW replica taking position ``slot``
        of ``count``: the same carve rule as construction, recomputed at
        the new width.  Existing replicas keep the groups they were
        built with — only the new slot's slice is consulted, and on the
        shared-device (CPU / single-chip) box every slice is the whole
        group anyway."""
        groups = _split_devices(self._devices, count, self._tp_req)
        return groups[slot] if slot < len(groups) else self._devices

    def _pick_victim(self) -> Optional[_Replica]:
        """The least-affine live replica: fewest parked prefix tokens
        (its warm state is the cheapest to walk away from), ties broken
        by least in-flight work, then youngest rid (the most recently
        added capacity goes first)."""
        members = list(self._members)
        if len(members) <= 1:
            return None

        def score(rec: _Replica):
            parked = 0
            engine = getattr(rec.mgr, "_engine", None)
            cache = getattr(engine, "prefix_cache", None)
            if cache is not None:
                try:
                    parked = sum(len(e.ids)
                                 for e in cache.entries_snapshot())
                except Exception:
                    parked = 0
            try:
                snap = rec.client.load_snapshot()
                busy = (int(snap.get("queue_depth", 0))
                        + int(snap.get("active_slots", 0)))
            except Exception:
                busy = 0
            return (parked, busy, -rec.rid)

        return min(members, key=score)

    def _scale_down_one(
            self, timeout_s: Optional[float]) -> Optional[Dict[str, Any]]:
        """Retire one replica (busy flag claimed).  Ordering is the
        correctness argument: (1) membership removal — no new dispatch;
        (2) bounded quiesce — finishing requests PARK their prefixes;
        (3) demote sweep + spill handoff — BEFORE drain flips the
        engine's ``_stop``, after which ``_try_demote`` stands down;
        (4) drain-and-stop; (5) breaker key retired."""
        victim = self._pick_victim()
        if victim is None:
            return None
        self._members.remove(victim)          # atomic: dispatch stops here
        try:
            return self._retire(victim, timeout_s)
        except BaseException:
            # The handle left membership above and was never re-homed
            # (standby parks and drain-stop both return normally), so
            # this unwind is the last reference to a live server.
            if victim not in self._standby:
                try:
                    victim.mgr.stop_server()
                except Exception:
                    pass
                self.breaker.forget(victim.name)
            raise

    def _retire(
            self, victim: _Replica,
            timeout_s: Optional[float]) -> Optional[Dict[str, Any]]:
        """Quiesce → demote/handoff → park-or-drain one removed member
        (the body of ``_scale_down_one``; the caller owns the unwind)."""
        timeout = (timeout_s if timeout_s is not None
                   else self.tier.drain_timeout_s)
        deadline = time.monotonic() + max(0.5, float(timeout))
        while time.monotonic() < deadline:
            try:
                snap = victim.client.load_snapshot()
                if not snap.get("queue_depth") \
                        and not snap.get("active_slots"):
                    break
            except Exception:
                break
            time.sleep(0.05)
        demoted = handed = 0
        engine = getattr(victim.mgr, "_engine", None)
        if engine is not None:
            sweep = getattr(engine, "demote_parked", None)
            if callable(sweep):
                try:
                    demoted = int(sweep() or 0)
                except Exception:
                    demoted = 0
            spill = getattr(engine, "kv_spill", None)
            if spill is not None:
                try:
                    spill.flush(timeout_s=5.0)
                except Exception:
                    pass
                target = self._spill_target(exclude=victim)
                if target is not None:
                    try:
                        for ids, tiles, nbytes, nb in \
                                spill.export_resident():
                            if target.admit_resident(ids, tiles,
                                                     nbytes, nb):
                                handed += 1
                    except Exception:
                        logger.exception(
                            "tier %s: spill handoff from %s failed",
                            self.name, victim.name)
        # Warm pool: a QUIESCED victim parks (engine kept warm,
        # off-membership) instead of draining to destruction — the next
        # scale-up republishes it in milliseconds.  A victim still busy
        # at the deadline is NOT parked: parking an engine with live
        # work would hide in-flight requests from every serving
        # aggregate, so it falls through to the full drain-and-stop.
        parked = False
        if getattr(self.tier, "autoscale", False) and \
                getattr(self.tier, "autoscale_warm_pool", False):
            try:
                snap = victim.client.load_snapshot()
                parked = (not snap.get("queue_depth")
                          and not snap.get("active_slots"))
            except Exception:
                parked = False
        if parked:
            drain = None
            self._standby.append(victim)
        else:
            try:
                drain = victim.mgr.drain(
                    timeout_s=max(0.5, deadline - time.monotonic()))
            except Exception as exc:
                # A failed drain still retires the replica: without the
                # stop the server would outlive its membership with no
                # reference left to ever shut it down.
                try:
                    victim.mgr.stop_server()
                except Exception:
                    pass
                drain = {"error": f"Request failed: {exc}"}
        self.breaker.forget(victim.name)
        logger.info("tier %s: replica %s %s (scale-down; "
                    "%d entries demoted, %d handed off)",
                    self.name, victim.name,
                    "parked to warm standby" if parked else "retired",
                    demoted, handed)
        return {"replica": victim.name, "demoted_entries": demoted,
                "handed_off": handed, "parked": parked,
                "drained": (drain or {}).get("drained", 0)
                if isinstance(drain, dict) else 0}

    def _spill_target(self, exclude: _Replica):
        """A survivor's spill store for the retiring replica's resident
        entries — the first live member with one (host tiles are in
        pool layout, identical across same-config replicas)."""
        for rec in list(self._members):
            if rec is exclude:
                continue
            engine = getattr(rec.mgr, "_engine", None)
            spill = getattr(engine, "kv_spill", None)
            if spill is not None:
                return spill
        return None

    # -- crash rescue (ISSUE 20) --------------------------------------------

    def restart_replica(self, rid: int,
                        reason: str = "wedged") -> Dict[str, Any]:
        """Restart ONE replica's engine with crash rescue: the victim's
        queued + in-flight requests are captured (prompt + generated
        prefix, the PR 5 replay machinery) and re-dispatched to a live
        sibling — or re-queued on the restarted engine when the tier
        has one replica — resuming byte-identically under greedy, and
        the host spill store survives the restart (detached before
        ``stop_server``, re-attached after, or handed to a survivor).

        Serialized through the SAME busy flag as ``scale_to``: a restart
        racing a scale-down would strand a freshly rebuilt engine
        outside the membership, so an overlapping call returns a
        ``busy`` error instead — the HealthMonitor keeps the replica's
        failure streak and retries next probe, the same contract as a
        refused autoscaler actuation."""
        summary: Dict[str, Any] = {
            "replica": replica_name(rid), "reason": reason,
            "restarted": False, "rescued": 0, "outcome": None,
            "spill_reattached": False, "errors": []}
        with self._scale_lock:
            if self._scaling:
                summary["errors"].append("busy: scale in progress")
                return summary
            self._scaling = True
        try:
            victim = next(
                (r for r in list(self._members) if r.rid == rid), None)
            if victim is None:
                summary["errors"].append(
                    f"{replica_name(rid)}: not a member")
                return summary
            engine = getattr(victim.mgr, "_engine", None)
            spill = None
            if getattr(self.tier, "spill_survive_restart", True) \
                    and hasattr(engine, "detach_spill"):
                spill = engine.detach_spill()
            captured: List[Any] = []
            if getattr(self.tier, "replica_rescue", True) \
                    and hasattr(engine, "capture_requests"):
                captured = engine.capture_requests()
            self._rescue_and_restart(victim, captured, spill, summary)
            return summary
        finally:
            with self._scale_lock:
                self._scaling = False

    def _rescue_and_restart(self, victim: _Replica, captured: List[Any],
                            spill: Any,
                            summary: Dict[str, Any]) -> None:
        """Restart ``victim``'s engine and re-home its captured work
        (busy flag claimed).  Rescue runs FIRST when a sibling lives —
        MTTR is then one capture + adopt, not an engine rebuild — so
        the restart's minutes never sit between a stalled stream and
        its resumption."""
        sibling = None
        if captured:
            for rec in list(self._members):
                if rec is victim or not rec.mgr.is_server_running():
                    continue
                eng = getattr(rec.mgr, "_engine", None)
                if callable(getattr(eng, "adopt_requests", None)):
                    sibling = rec
                    break
            if sibling is not None:
                adopted = sibling.mgr._engine.adopt_requests(captured)
                self._note_rescue(captured, "sibling", sibling.name)
                summary["rescued"] = adopted
                summary["outcome"] = "sibling"
        try:
            victim.mgr.stop_server()
            victim.mgr.start_server()
            summary["restarted"] = True
        except Exception as exc:
            summary["errors"].append(f"{victim.name}: restart: {exc}")
        new_engine = (getattr(victim.mgr, "_engine", None)
                      if summary["restarted"] else None)
        if spill is not None:
            adopt = getattr(new_engine, "adopt_spill", None)
            if callable(adopt) and adopt(spill):
                summary["spill_reattached"] = True
                try:
                    m = (self.obs or get_observability()).m
                    m.spill_reattach.labels(self.name).inc()
                except Exception:
                    pass
            else:
                # The rebuilt engine refused (restart failed, or the
                # geometry changed): hand the warm entries to a
                # survivor through the scale-down handoff path, then
                # stop the orphan store.
                target = self._spill_target(exclude=victim)
                handed = 0
                if target is not None:
                    try:
                        for ids, tiles, nbytes, nb in \
                                spill.export_resident():
                            if target.admit_resident(ids, tiles,
                                                     nbytes, nb):
                                handed += 1
                    except Exception:
                        logger.exception(
                            "tier %s: spill handoff from %s failed",
                            self.name, victim.name)
                summary["spill_handed_off"] = handed
                try:
                    spill.stop()
                except Exception:
                    pass
        if captured and sibling is None:
            adopt_reqs = getattr(new_engine, "adopt_requests", None)
            if callable(adopt_reqs):
                summary["rescued"] = adopt_reqs(captured)
                summary["outcome"] = "requeue"
                self._note_rescue(captured, "requeue", victim.name)
            else:
                fail_captured(captured, self.name)
                summary["outcome"] = "failed"
                self._note_rescue(captured, "failed", victim.name)
        if summary["restarted"]:
            self.breaker.reset(replica_name(victim.rid))
        logger.info(
            "tier %s: replica %s restarted=%s rescued=%d (%s) "
            "spill_reattached=%s (%s)", self.name, victim.name,
            summary["restarted"], summary["rescued"], summary["outcome"],
            summary["spill_reattached"], summary["reason"])

    def _note_rescue(self, captured: List[Any], outcome: str,
                     by: str) -> None:
        """Rescue observability: one counter bump per request plus a
        ``rescue`` span event so flight-recorder entries show who saved
        the request."""
        try:
            m = (self.obs or get_observability()).m
            m.replica_rescues.labels(self.name, outcome).inc(
                len(captured))
        except Exception:
            pass
        for req in captured:
            obs_spans.event(getattr(req, "trace", None), "rescue",
                            tier=self.name, outcome=outcome, by=by)

    # -- dispatch policy ----------------------------------------------------

    def _policy(self) -> str:
        raw = (env_str("DLLM_REPLICA_POLICY") or "").strip().lower()
        if raw in _POLICIES:
            return raw
        return "affinity" if self.tier.replica_affinity else "load"

    def _predicted_waits(self) -> List[Tuple[float, int]]:
        """(predicted queue wait s, inflight) per replica — PR 1's
        admission predictor (queue_depth / slots × EWMA service time)
        read from each replica's own controller."""
        out = []
        for c in self.clients:
            snap = c.admission.snapshot()
            ewma_s = (snap.get("ewma_service_ms") or 0.0) / 1000.0
            wait = (snap["queue_depth"] / max(1, snap["slots"])) * ewma_s
            out.append((wait, int(snap["inflight"])))
        return out

    def _affinity_scores(self, history) -> List[int]:
        """Parked-prefix match tokens per replica: tokenize ONCE with
        the first live engine, peek every live replica's cache with the
        same ids (stopped replicas score 0 — the probe never starts an
        engine)."""
        members = list(self._members)
        scores = [0] * len(members)
        ids = None
        for i, r in enumerate(members):
            engine = getattr(r.mgr, "_engine", None)
            peek = getattr(engine, "prefix_affinity_tokens", None)
            if not callable(peek) \
                    or getattr(engine, "prefix_cache", None) is None:
                continue            # no cache → never pay tokenization
            try:
                if ids is None:
                    ids = engine.affinity_token_ids(history)
                scores[i] = int(peek(ids))
            except Exception:
                scores[i] = 0
        return scores

    def _pick_replica(self, history,
                      members: Optional[List[_Replica]] = None
                      ) -> Tuple[int, str]:
        """(index into the membership snapshot, how) — how ∈ {single,
        affinity, affinity_overridden, least_loaded, random,
        breaker_fallback}.  Callers that must dereference the index
        pass their own snapshot as ``members`` (dispatch does), so a
        concurrent scale event can't shift what the index means."""
        if members is None:
            members = list(self._members)
        n = len(members)
        if n == 1:
            return 0, "single"
        waits = self._predicted_waits()
        if len(waits) < n:
            # A membership change landed between the snapshot and the
            # helper's read: pad — the extra members are brand-new and
            # empty, so zero predicted wait is the truth anyway.
            waits = waits + [(0.0, 0)] * (n - len(waits))
        with self._rr_lock:
            rr = self._rr
            self._rr += 1
            # Drawn under the lock even when unused: Random isn't
            # thread-safe, and drawing unconditionally keeps the
            # sequence deterministic per request index.
            shuffled = self._rng.sample(range(n), n)
        order = sorted(range(n),
                       key=lambda i: (waits[i][0], waits[i][1],
                                      (i - rr) % n))
        how = "least_loaded"
        policy = self._policy()
        if policy == "random":
            order = shuffled
            how = "random"
        elif policy == "affinity":
            scores = self._affinity_scores(history)
            if len(scores) < n:
                scores = scores + [0] * (n - len(scores))
            best = max(range(n), key=lambda i: (scores[i], -waits[i][0]))
            if scores[best] >= self.tier.replica_affinity_min_tokens:
                least = order[0]
                if (waits[best][0] - waits[least][0]
                        <= self.tier.replica_affinity_override_s):
                    order.remove(best)
                    order.insert(0, best)
                    how = "affinity"
                else:
                    # The affine replica is too hot: locality yields to
                    # load — re-prefilling elsewhere beats queuing here.
                    how = "affinity_overridden"
        for idx in order:
            if self.breaker.allow(members[idx].name):
                return idx, (how if idx == order[0]
                             else "breaker_fallback")
        # Every replica's circuit is open within cooldown: dispatch the
        # best candidate anyway — whole-tier shedding is the Router's
        # tier-level breaker's job, and a tier with replicas=1 has no
        # replica gate at all (parity).
        return order[0], "breaker_fallback"

    def _note_route(self, member: _Replica, how: str) -> None:
        obs_spans.annotate(current_trace(), replica=member.name,
                           replica_policy=how)
        try:
            m = (self.obs or get_observability()).m
            m.replica_routed.labels(self.name, how).inc()
        except Exception:
            pass

    def _feed_breaker(self, member, raw: Any) -> None:
        """Sync/setup outcome → the replica breaker.  Admission
        rejections are breaker-neutral (healthy backpressure; the PR 2
        rule) but repay a half-open canary permit.  ``member`` is the
        dispatched record — or a positional index into the current
        membership (the historic call shape tests drive directly)."""
        if isinstance(member, _Replica):
            key = member.name
        else:
            members = list(self._members)
            i = int(member)
            key = (members[i].name if 0 <= i < len(members)
                   else replica_name(i))
        if is_error_shape(raw):
            if "admission rejected" in str(raw.get("error", "")):
                self.breaker.release_probe(key)
            else:
                self.breaker.record(key, False)
        else:
            self.breaker.record(key, True)

    def reset_replica(self, rid: int) -> None:
        """Force-close one replica's circuit (the HealthMonitor calls
        this after successfully restarting that replica's engine)."""
        self.breaker.reset(replica_name(rid))

    def member_manager(self, rid: int) -> Optional[EngineManager]:
        """The EngineManager behind member ``rid``, or None when the rid
        left membership.  The HealthMonitor compares this against its
        probe snapshot by IDENTITY before routing a restart through
        ``restart_replica`` — a probe of one manager must never trigger
        a rescue-restart of a different one (tests swap duck-typed
        manager sets under the same tier client)."""
        for r in list(self._members):
            if r.rid == rid:
                return r.mgr
        return None

    def healthy_replicas(self) -> int:
        """Replicas currently able to serve: running, not draining, not
        watchdog-stalled, circuit not open.  Lock-free advisory reads
        only (the sampler calls this at cadence)."""
        n = 0
        for r in list(self._members):
            if not r.mgr.is_server_running() or r.mgr.draining:
                continue
            if self.breaker.state(r.name) == OPEN:
                continue
            engine = getattr(r.mgr, "_engine", None)
            stall = getattr(engine, "progress_stall_s", None)
            deadline = self.tier.watchdog_stall_s
            if callable(stall) and deadline is not None:
                try:
                    if float(stall()) > deadline:
                        continue
                except Exception:
                    pass
            n += 1
        return n

    # -- request surface (TierClient parity) --------------------------------

    def process(self, history) -> Dict[str, Any]:
        members = list(self._members)
        idx, how = self._pick_replica(history, members=members)
        member = members[min(idx, len(members) - 1)]
        self._note_route(member, how)
        client = member.client
        self._last_client = client
        raw = client.process(history)
        self._feed_breaker(member, raw)
        return raw

    def process_stream(self, history):
        members = list(self._members)
        idx, how = self._pick_replica(history, members=members)
        member = members[min(idx, len(members) - 1)]
        self._note_route(member, how)
        client = member.client
        self._last_client = client
        handle = client.process_stream(history)
        if is_error_shape(handle):
            self._feed_breaker(member, handle)
            return handle
        key = member.name
        return _ReplicaStream(
            handle, lambda ok: self.breaker.record(key, ok))

    def load_snapshot(self) -> Dict[str, Any]:
        """Tier-level load = sum over replicas (the queue-aware perf
        strategy and the cross-host load allgather read ONE row per
        tier; the per-replica split is dispatch's private signal)."""
        out = {"queue_depth": 0, "active_slots": 0, "max_slots": 0}
        for c in self.clients:
            snap = c.load_snapshot()
            for k in out:
                out[k] += int(snap.get(k, 0))
        return out

    @property
    def last_result(self):
        c = self._last_client
        return c.last_result if c is not None else None

    @property
    def admission(self):
        """The last-dispatched replica's controller (back-compat shim
        for tests poking ``tier.admission``); per-replica controllers
        live on each client in ``self.clients``."""
        c = self._last_client or self.clients[0]
        return c.admission
