"""Replicated tiers — N data-parallel engine replicas behind one tier.

Until ISSUE 12 a tier was exactly ONE engine, so aggregate throughput was
capped at one engine's knee and "scale out" meant an architecture change.
``TierConfig.replicas > 1`` makes the tier own N full ``EngineManager``
replicas — the TPU-serving data-parallel shape (per-replica batching over
a mesh axis; the Gemma-on-TPU comparison in PAPERS.md): when the tier's
submesh has enough devices each replica gets its own device slice
(``replicas × tp`` chips, the ``P('batch')`` data-parallel carve), and on
a single-device/CPU box the replicas are process-local engines sharing
the device.  Every replica keeps the WHOLE single-engine machinery it
had before — bounded admission queue + EWMA wait predictor (PR 1),
watchdog (PR 2), drain (PR 5), chunked prefill (PR 9), shared-prefix KV
(PR 10), tick profiler (PR 11) — because each replica IS a TierClient
over an EngineManager, just not the only one.

Dispatch picks a replica by a two-level policy:

1. **Prefix affinity** (``TierConfig.replica_affinity``): the request is
   tokenized ONCE and every live replica's parked-prefix cache is peeked
   with the same ids — the identical ``select_reuse``/longest-match the
   engines reuse blocks by (engine/prefix_cache.py), so the host-side
   "which replica holds this prefix" map is exactly the caches
   themselves, never a second bookkeeping structure that could drift.
   A match of at least ``replica_affinity_min_tokens`` binds the request
   to that replica — a session (or a same-system-prompt sibling) lands
   where its blocks are parked, so the PR 10 dedup/warm-TTFT win
   survives going multi-replica instead of being diluted N ways.
2. **Least-loaded** otherwise: smallest predicted queue wait
   (queue_depth / slots × EWMA service time — PR 1's admission
   predictor), ties broken by in-flight count then round-robin.  An
   affine replica whose predicted wait exceeds the least-loaded's by
   more than ``replica_affinity_override_s`` is OVERRIDDEN — cache
   locality must not starve the other replicas behind one hot queue.

Each replica has its own breaker sub-gate (serving/breaker.py, keyed
``r0..rN-1``, thresholds from the cluster's breaker config): dispatch
skips open replicas, stream/sync verdicts feed back per replica, and
admission rejections stay breaker-neutral (healthy backpressure — the
PR 2 rule).  Tier-level ``health()`` / ``kv_stats()`` / ``slot_stats()``
aggregate across replicas with a per-replica breakdown, and the
HealthMonitor probes/restarts replicas INDIVIDUALLY — one wedged
replica degrades capacity (``healthy_replicas``/``replica_count``)
instead of the tier.

``replicas = 1`` never builds any of this: build_tiers keeps the plain
TierClient/EngineManager path, byte-identical to pre-replica behavior.
"""

from __future__ import annotations

import dataclasses
import logging
import random
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..config import ClusterConfig, TierConfig
from ..config_registry import env_str
from ..engine.manager import EngineManager
from ..obs import get_observability
from ..obs import spans as obs_spans
from ..obs.spans import current_trace
from ..utils.faults import FaultInjector
from .breaker import CircuitBreaker, OPEN
from .errors import is_error_shape
from .tiers import TierClient

logger = logging.getLogger(__name__)

_POLICIES = ("affinity", "load", "random")


def replica_name(i: int) -> str:
    return f"r{i}"


def _split_devices(devices: List, n: int, tp: int) -> List[List]:
    """Per-replica device groups: when the tier's submesh has at least
    ``n × tp`` devices each replica gets its own contiguous ``tp``-chip
    slice (the data-parallel carve — replicas are the 'batch' axis of
    the SNIPPETS.md NamedSharding/P('batch') shape, realized as disjoint
    submeshes because each replica runs its own engine); otherwise every
    replica shares the whole group (process-local replicas — the CPU /
    single-chip box)."""
    per = max(1, tp)
    if len(devices) >= n * per:
        return [devices[i * per:(i + 1) * per] for i in range(n)]
    if per == 1 and devices:
        # Fewer devices than replicas: pin each replica to ONE device
        # round-robin (an unsharded replica must never grow a mesh just
        # because the box is short — extra replicas time-share).
        return [[devices[i % len(devices)]] for i in range(n)]
    return [list(devices) for _ in range(n)]


class ReplicaSetManager:
    """The EngineManager-shaped facade over a tier's N replica managers.

    Everything that used to talk to ``tier.server_manager`` — the bench
    harness's start/stop between configs, Router.drain, GET /health —
    keeps working: lifecycle verbs fan out to every replica, liveness
    reads aggregate, and ``health()``/``kv_stats()``/``slot_stats()``
    return tier-level aggregates carrying a per-replica breakdown.
    Probe-surface methods stay lock-free exactly like EngineManager's
    (each sub-manager's health/is_server_running already are)."""

    def __init__(self, tier: TierConfig, managers: Sequence[EngineManager]):
        self.tier = tier
        self.managers = list(managers)

    # -- replica access -----------------------------------------------------

    def replica_managers(self) -> List[EngineManager]:
        """The per-replica EngineManagers — the HealthMonitor's probe and
        restart targets (one wedged replica restarts alone)."""
        return list(self.managers)

    def live_engines(self) -> List[Tuple[str, Any]]:
        """(replica key, engine) for every RUNNING replica — the obs
        surfaces' iteration point (profiler trace, sampler, /stats).
        Never lazy-starts an engine."""
        out = []
        for i, mgr in enumerate(self.managers):
            engine = getattr(mgr, "_engine", None)
            if engine is not None:
                out.append((replica_name(i), engine))
        return out

    # -- lifecycle (ServerManager surface) ----------------------------------

    def start_server(self, beat=None) -> None:
        """Start every replica (idempotent per replica).  Serial on
        purpose: replica 0's warmup populates the XLA compile cache the
        siblings then hit warm, and concurrent cold compiles of the same
        programs would just contend."""
        for mgr in self.managers:
            mgr.start_server(beat=beat)

    def stop_server(self) -> None:
        for mgr in self.managers:
            mgr.stop_server()

    def drain(self, timeout_s: Optional[float] = None) -> Dict[str, Any]:
        """Drain every replica CONCURRENTLY and wait them all out — the
        tier is drained only when its last replica is (each replica
        stops admitting immediately, so the concurrent fan-out never
        extends the deadline past one replica's drain_timeout_s plus
        join slack).  Returns the aggregate summary with the per-replica
        breakdown."""
        timeout = (timeout_s if timeout_s is not None
                   else self.tier.drain_timeout_s)
        t0 = time.monotonic()
        # Every key pre-populated BEFORE the workers start: a worker
        # abandoned past the join bound may still finish later, and its
        # write must be a value OVERWRITE (safe under the GIL), never a
        # size-changing insert racing the summary's iteration below.
        results: Dict[str, Any] = {
            replica_name(i): {"error": "Request failed: replica drain "
                              "did not return within the join bound"}
            for i in range(len(self.managers))}
        threads = []
        for i, mgr in enumerate(self.managers):
            def _drain(key=replica_name(i), mgr=mgr):
                try:
                    results[key] = mgr.drain(timeout_s=timeout)
                except Exception as exc:   # a dead replica must not
                    results[key] = {"error": f"Request failed: {exc}"}
            t = threading.Thread(target=_drain, daemon=True,
                                 name=f"drain-{self.tier.name}-r{i}")
            threads.append(t)
            t.start()
        deadline = time.monotonic() + max(0.0, float(timeout)) + 30.0
        for t in threads:
            t.join(timeout=max(0.0, deadline - time.monotonic()))
        summary = {
            "draining_started": True,
            "in_flight_at_start": sum(
                int(r.get("in_flight_at_start", 0))
                for r in results.values() if isinstance(r, dict)),
            "drained": sum(int(r.get("drained", 0))
                           for r in results.values()
                           if isinstance(r, dict)),
            "aborted": sum(int(r.get("aborted", 0))
                           for r in results.values()
                           if isinstance(r, dict)),
            "waited_s": round(time.monotonic() - t0, 3),
            "replicas": dict(results),      # snapshot, not the live dict
        }
        return summary

    @property
    def draining(self) -> bool:
        """The TIER is draining only when every replica is: a partially
        drained tier still serves traffic on the survivors."""
        return bool(self.managers) and all(m.draining
                                           for m in self.managers)

    def is_server_running(self) -> bool:
        return any(m.is_server_running() for m in self.managers)

    def engine(self):
        """Single-engine compatibility accessor (bench legs and tests
        that introspect ``server_manager.engine()``): replica 0's
        engine, lazy-started like EngineManager.engine()."""
        return self.managers[0].engine()

    # -- aggregate observability --------------------------------------------

    def health(self) -> Dict[str, Any]:
        """Tier-level health = aggregate over per-replica health():
        ``ok`` while ANY replica serves (one wedged replica is degraded
        capacity, not a dead tier), ``wedged`` only when every replica
        is, capacity counters, and the full per-replica breakdown."""
        reps: Dict[str, Dict[str, Any]] = {}
        for i, mgr in enumerate(self.managers):
            try:
                reps[replica_name(i)] = mgr.health()
            except Exception as exc:
                reps[replica_name(i)] = {"ok": False,  # dllm-lint: disable=error-shape -- health-probe snapshot (GET /health surface), not the tier error path
                                         "error": str(exc)[:200]}
        healthy = sum(1 for h in reps.values() if h.get("ok"))
        running = sum(1 for h in reps.values() if h.get("uptime_s"))
        entry: Dict[str, Any] = {
            "ok": healthy > 0,
            "draining": self.draining,
            "tier": self.tier.name,
            "model": self.tier.model_preset,
            "uptime_s": max((h.get("uptime_s") or 0.0)
                            for h in reps.values()) if reps else 0.0,
            "devices": None,
            "replica_count": len(self.managers),
            "healthy_replicas": healthy,
            "degraded": 0 < healthy < len(self.managers),
            "queue_depth": sum(int(h.get("queue_depth") or 0)
                               for h in reps.values()),
            "active_slots": sum(int(h.get("active_slots") or 0)
                                for h in reps.values()),
            "max_slots": sum(int(h.get("max_slots") or 0)
                             for h in reps.values()),
            "replicas": reps,
        }
        devices = [d for h in reps.values()
                   for d in (h.get("devices") or ())]
        if devices:
            entry["devices"] = devices
        if entry["max_slots"]:
            entry["slot_occupancy"] = round(
                entry["active_slots"] / entry["max_slots"], 3)
        if reps and all(h.get("wedged") for h in reps.values()):
            # Every replica stalled: the tier as a whole is wedged (the
            # per-replica watchdog verdicts still drive the individual
            # restarts — this flag is the operator's summary).
            entry["ok"] = False
            entry["wedged"] = True
        if running and not healthy:
            entry["error"] = "no healthy replica (all wedged or failed)"
        return entry

    def kv_stats(self) -> Optional[Dict[str, Any]]:
        """Summed block-pool picture over the live paged replicas, with
        the per-replica breakdown; None when no live replica has a paged
        pool (sequential engines).  ``dedup_ratio`` reports the MAX
        across replicas — the per-replica ratios are the meaningful
        series (block pools are disjoint; averaging them would hide a
        replica whose pool sharing collapsed)."""
        reps: Dict[str, Dict[str, Any]] = {}
        for key, engine in self.live_engines():
            fn = getattr(engine, "kv_stats", None)
            if callable(fn):
                try:
                    reps[key] = fn()
                except Exception:
                    pass
        if not reps:
            return None
        summed = ("free_blocks", "reclaimable_blocks", "total_blocks",
                  "preempted_total", "prefill_pending_blocks",
                  "prefill_backlog_tokens", "shared_blocks",
                  "pinned_entries")
        out: Dict[str, Any] = {k: sum(int(r.get(k, 0))
                                      for r in reps.values())
                               for k in summed}
        first = next(iter(reps.values()))
        out["block_size"] = first.get("block_size")
        out["dedup_ratio"] = max(float(r.get("dedup_ratio", 1.0))
                                 for r in reps.values())
        # Hierarchical-KV spill tier (ISSUE 14): host-tier occupancy and
        # demote/promote counters sum like the pool fields, but only
        # when some replica actually runs a spill tier — a spill-less
        # tier's aggregate keeps its historical shape.  (Affinity
        # already treats a replica's DEMOTED entries as eligible: the
        # per-engine prefix_affinity_tokens peek consults the spill
        # store, so a session follows its spilled prefix home.)
        spill_keys = ("host_entries", "host_blocks", "host_bytes",
                      "host_budget_bytes", "demotions_total",
                      "promotions_total", "promotion_races_total",
                      "demote_inflight", "promote_backlog_blocks")
        for k in spill_keys:
            if any(k in r for r in reps.values()):
                out[k] = sum(int(r.get(k, 0)) for r in reps.values())
        out["replicas"] = reps
        return out

    def slot_stats(self) -> Dict[str, Any]:
        """Summed occupancy over live replicas with per-replica rows."""
        reps: Dict[str, Dict[str, Any]] = {}
        for key, engine in self.live_engines():
            fn = getattr(engine, "slot_stats", None)
            if callable(fn):
                try:
                    reps[key] = fn()
                except Exception:
                    pass
        summed = ("queue_depth", "active_slots", "max_slots",
                  "preempted_total", "prefill_inflight",
                  "prefill_backlog_tokens")
        out: Dict[str, Any] = {k: sum(int(r.get(k, 0))
                                      for r in reps.values())
                               for k in summed}
        out["slot_occupancy"] = round(
            out["active_slots"] / max(1, out["max_slots"]), 3)
        out["replicas"] = reps
        return out

    def prefix_affinity(self, history) -> int:
        """Best parked-prefix match across the live replicas — the
        tier-level probe the Router's cross-TIER affinity steering
        consults (serving/router.py _apply_prefix_affinity): the tier
        holds a conversation's prefix if ANY replica does.  Tokenizes
        once, peeks each replica (non-destructive)."""
        best = 0
        ids = None
        for _key, engine in self.live_engines():
            peek = getattr(engine, "prefix_affinity_tokens", None)
            if not callable(peek):
                continue
            try:
                if ids is None:
                    ids = engine.affinity_token_ids(history)
                best = max(best, int(peek(ids)))
            except Exception:
                continue
        return best


class _ReplicaStream:
    """Stream wrapper feeding the replica breaker its COMPLETION verdict
    (the same rule as the Router's tier-level on_done: setup only proves
    one primed token, so a mid-decode death must reach the breaker as
    the failure it is; a consumer disconnect is not the replica's
    fault).  Transparent to RoutedStream: iteration and ``.result``
    forward to the tier handle."""

    def __init__(self, handle, on_done):
        self._handle = handle
        self._on_done = on_done
        self._fired = False

    def _fire(self, ok: bool) -> None:
        if not self._fired:
            self._fired = True
            try:
                self._on_done(ok)
            except Exception:
                pass

    def __iter__(self):
        try:
            for delta in self._handle:
                yield delta
        except GeneratorExit:
            self._fire(True)              # client disconnect: replica fine
            raise
        except BaseException:
            self._fire(False)
            raise
        self._fire(True)

    @property
    def result(self):
        return self._handle.result


class ReplicatedTierClient:
    """The tier client over N replica TierClients — same surface as
    TierClient (``process`` / ``process_stream`` / ``load_snapshot`` /
    ``server_manager`` / ``tier`` / ``name``), with dispatch choosing a
    replica per request (module docstring: affinity → least-loaded, with
    the per-replica breaker veto)."""

    def __init__(
        self,
        tier: TierConfig,
        cluster: ClusterConfig,
        mesh=None,
        devices: Optional[List] = None,
        fault_injector: Optional[FaultInjector] = None,
        warmup_on_start: bool = True,
        seed: int = 0,
    ):
        if tier.replicas < 1:
            raise ValueError(f"tier {tier.name}: replicas must be >= 1, "
                             f"got {tier.replicas}")
        if tier.ep > 1 or tier.sp > 1:
            # Replica submeshes are tp-only: silently serving without
            # the configured expert/sequence sharding would look like
            # ep/sp is in effect while it is not (same warn-and-degrade
            # policy as _fit_sp's engine-mismatch rule).
            logger.warning(
                "tier %s: ep=%d sp=%d IGNORED — replicated tiers build "
                "tp-only submeshes per replica (replicas=%d wins); set "
                "replicas=1 to keep expert/sequence parallelism",
                tier.name, tier.ep, tier.sp, tier.replicas)
        self.tier = tier
        self.name = tier.name
        self.faults = fault_injector
        n = tier.replicas
        devs = (list(mesh.devices.flat) if mesh is not None
                else list(devices or []))
        from ..parallel.mesh import requested_tp
        tp_req = requested_tp(tier)       # honors the DLLM_TP override
        groups = _split_devices(devs, n, tp_req)
        self.clients: List[TierClient] = []
        managers: List[EngineManager] = []
        for i in range(n):
            # Replica-suffixed tier identity for the ENGINE side: logs,
            # per-replica metric labels (dllm_decode_tick_ms{tier=
            # "nano/r0"}, the per-replica compiled-programs gauge the
            # bench leg pins), profiler timelines.  The CLIENT keeps the
            # base name: error shapes, fault targeting, and trace spans
            # must stay byte-identical to the single-replica tier.
            rtier = dataclasses.replace(
                tier, name=f"{tier.name}/{replica_name(i)}")
            group = groups[i] if i < len(groups) else devs
            if len(group) > 1:
                from ..parallel.mesh import tp_mesh
                # Multi-device group = this replica's own TP submesh,
                # at the TIER's tp degree (a short box sharing devices
                # must not inflate tp past the config).
                mgr = EngineManager(
                    rtier,
                    mesh=tp_mesh(group, min(max(1, tp_req), len(group))),
                    seed=seed, warmup_on_start=warmup_on_start)
            else:
                mgr = EngineManager(rtier,
                                    devices=(group or None), seed=seed,
                                    warmup_on_start=warmup_on_start)
            client = TierClient(rtier, mgr, fault_injector)
            client.name = tier.name       # base-name error/fault identity
            managers.append(mgr)
            self.clients.append(client)
        self.server_manager = ReplicaSetManager(tier, managers)
        # Per-replica breaker sub-gate: same thresholds as the cluster's
        # tier-level breaker; breaker_failures=0 disables both.  The
        # tier-level breaker (Router) still owns whole-tier shedding —
        # this one only steers dispatch AWAY from a failing replica
        # while the survivors keep the tier closed.
        self.breaker = CircuitBreaker(
            [replica_name(i) for i in range(n)],
            failure_threshold=getattr(cluster, "breaker_failures", 0),
            cooldown_s=getattr(cluster, "breaker_cooldown_s", 30.0))
        self._rr_lock = threading.Lock()
        self._rr = 0
        self._rng = random.Random(seed ^ 0x5EED)
        self._last_client: Optional[TierClient] = None
        # Observability sink, lazily resolved so tests/bench can inject
        # a fresh registry after construction (same pattern as the
        # manager's global fallbacks).
        self.obs = None

    # -- dispatch policy ----------------------------------------------------

    def _policy(self) -> str:
        raw = (env_str("DLLM_REPLICA_POLICY") or "").strip().lower()
        if raw in _POLICIES:
            return raw
        return "affinity" if self.tier.replica_affinity else "load"

    def _predicted_waits(self) -> List[Tuple[float, int]]:
        """(predicted queue wait s, inflight) per replica — PR 1's
        admission predictor (queue_depth / slots × EWMA service time)
        read from each replica's own controller."""
        out = []
        for c in self.clients:
            snap = c.admission.snapshot()
            ewma_s = (snap.get("ewma_service_ms") or 0.0) / 1000.0
            wait = (snap["queue_depth"] / max(1, snap["slots"])) * ewma_s
            out.append((wait, int(snap["inflight"])))
        return out

    def _affinity_scores(self, history) -> List[int]:
        """Parked-prefix match tokens per replica: tokenize ONCE with
        the first live engine, peek every live replica's cache with the
        same ids (stopped replicas score 0 — the probe never starts an
        engine)."""
        scores = [0] * len(self.clients)
        ids = None
        for i, c in enumerate(self.clients):
            engine = getattr(c.server_manager, "_engine", None)
            peek = getattr(engine, "prefix_affinity_tokens", None)
            if not callable(peek) \
                    or getattr(engine, "prefix_cache", None) is None:
                continue            # no cache → never pay tokenization
            try:
                if ids is None:
                    ids = engine.affinity_token_ids(history)
                scores[i] = int(peek(ids))
            except Exception:
                scores[i] = 0
        return scores

    def _pick_replica(self, history) -> Tuple[int, str]:
        """(replica index, how) — how ∈ {single, affinity,
        affinity_overridden, least_loaded, random, breaker_fallback}."""
        n = len(self.clients)
        if n == 1:
            return 0, "single"
        waits = self._predicted_waits()
        with self._rr_lock:
            rr = self._rr
            self._rr += 1
            # Drawn under the lock even when unused: Random isn't
            # thread-safe, and drawing unconditionally keeps the
            # sequence deterministic per request index.
            shuffled = self._rng.sample(range(n), n)
        order = sorted(range(n),
                       key=lambda i: (waits[i][0], waits[i][1],
                                      (i - rr) % n))
        how = "least_loaded"
        policy = self._policy()
        if policy == "random":
            order = shuffled
            how = "random"
        elif policy == "affinity":
            scores = self._affinity_scores(history)
            best = max(range(n), key=lambda i: (scores[i], -waits[i][0]))
            if scores[best] >= self.tier.replica_affinity_min_tokens:
                least = order[0]
                if (waits[best][0] - waits[least][0]
                        <= self.tier.replica_affinity_override_s):
                    order.remove(best)
                    order.insert(0, best)
                    how = "affinity"
                else:
                    # The affine replica is too hot: locality yields to
                    # load — re-prefilling elsewhere beats queuing here.
                    how = "affinity_overridden"
        for idx in order:
            if self.breaker.allow(replica_name(idx)):
                return idx, (how if idx == order[0]
                             else "breaker_fallback")
        # Every replica's circuit is open within cooldown: dispatch the
        # best candidate anyway — whole-tier shedding is the Router's
        # tier-level breaker's job, and a tier with replicas=1 has no
        # replica gate at all (parity).
        return order[0], "breaker_fallback"

    def _note_route(self, idx: int, how: str) -> None:
        obs_spans.annotate(current_trace(), replica=replica_name(idx),
                           replica_policy=how)
        try:
            m = (self.obs or get_observability()).m
            m.replica_routed.labels(self.name, how).inc()
        except Exception:
            pass

    def _feed_breaker(self, idx: int, raw: Any) -> None:
        """Sync/setup outcome → the replica breaker.  Admission
        rejections are breaker-neutral (healthy backpressure; the PR 2
        rule) but repay a half-open canary permit."""
        key = replica_name(idx)
        if is_error_shape(raw):
            if "admission rejected" in str(raw.get("error", "")):
                self.breaker.release_probe(key)
            else:
                self.breaker.record(key, False)
        else:
            self.breaker.record(key, True)

    def reset_replica(self, idx: int) -> None:
        """Force-close one replica's circuit (the HealthMonitor calls
        this after successfully restarting that replica's engine)."""
        self.breaker.reset(replica_name(idx))

    def healthy_replicas(self) -> int:
        """Replicas currently able to serve: running, not draining, not
        watchdog-stalled, circuit not open.  Lock-free advisory reads
        only (the sampler calls this at cadence)."""
        n = 0
        for i, mgr in enumerate(self.server_manager.managers):
            if not mgr.is_server_running() or mgr.draining:
                continue
            if self.breaker.state(replica_name(i)) == OPEN:
                continue
            engine = getattr(mgr, "_engine", None)
            stall = getattr(engine, "progress_stall_s", None)
            deadline = self.tier.watchdog_stall_s
            if callable(stall) and deadline is not None:
                try:
                    if float(stall()) > deadline:
                        continue
                except Exception:
                    pass
            n += 1
        return n

    # -- request surface (TierClient parity) --------------------------------

    def process(self, history) -> Dict[str, Any]:
        idx, how = self._pick_replica(history)
        self._note_route(idx, how)
        client = self.clients[idx]
        self._last_client = client
        raw = client.process(history)
        self._feed_breaker(idx, raw)
        return raw

    def process_stream(self, history):
        idx, how = self._pick_replica(history)
        self._note_route(idx, how)
        client = self.clients[idx]
        self._last_client = client
        handle = client.process_stream(history)
        if is_error_shape(handle):
            self._feed_breaker(idx, handle)
            return handle
        key = replica_name(idx)
        return _ReplicaStream(
            handle, lambda ok: self.breaker.record(key, ok))

    def load_snapshot(self) -> Dict[str, Any]:
        """Tier-level load = sum over replicas (the queue-aware perf
        strategy and the cross-host load allgather read ONE row per
        tier; the per-replica split is dispatch's private signal)."""
        out = {"queue_depth": 0, "active_slots": 0, "max_slots": 0}
        for c in self.clients:
            snap = c.load_snapshot()
            for k in out:
                out[k] += int(snap.get(k, 0))
        return out

    @property
    def last_result(self):
        c = self._last_client
        return c.last_result if c is not None else None

    @property
    def admission(self):
        """The last-dispatched replica's controller (back-compat shim
        for tests poking ``tier.admission``); per-replica controllers
        live on each client in ``self.clients``."""
        c = self._last_client or self.clients[0]
        return c.admission
