"""Health monitor: liveness probing, ICI health allgather, tier failover.

Reference parity + TPU upgrade (SURVEY.md §5.3).  The reference's failure
detection is per-call: a TCP probe + /health poll at bootstrap
(src/models/server_manager.py:20-32,120-134), lazy restart in every
``.process()`` (src/models/nano.py:19-21), and failover on error-shaped
responses (src/router.py:277-282).  All of that survives in TierClient /
EngineManager / Router.  This module adds the pieces a chip-tier deployment
needs on top:

- **Background liveness probing** of every tier at a fixed cadence (the
  reference only probed at bootstrap) with automatic engine restart after
  ``max_consecutive_failures`` — the ServerManager self-healing made
  continuous instead of per-request.  A tier that is merely *stopped*
  (lazy, or deliberately shut down between benchmark configs) is reported
  as "stopped", not failed: only a tier that was seen running and then
  went unhealthy counts toward restart.
- **Cross-host health allgather** (the north star's "perf health signals
  allgathered over ICI"): every mesh participant contributes its local
  perf-window summary row; rows owned by OTHER processes (judged by each
  mesh device's ``process_index``) are folded into the local PerfStrategy
  via ``merge_remote`` (routing/strategies.py).  On a single-process mesh
  every row is local, so nothing is merged — the exchange is a true
  identity, never an echo of our own samples.
- **Snapshot API** feeding GET /stats.
"""

from __future__ import annotations

import logging
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

logger = logging.getLogger(__name__)


class HealthMonitor:
    """Periodically probes tiers, restarts engines that went unhealthy, and
    (when a mesh is given) merges cross-host perf summaries into the
    router's perf strategy."""

    def __init__(
        self,
        router,                              # serving.router.Router
        interval_s: float = 5.0,
        max_consecutive_failures: int = 3,
        mesh=None,                           # jax Mesh for the allgather
        auto_restart: bool = True,
        restart_timeout_s: float = 900.0,
    ):
        self.router = router
        self.interval_s = interval_s
        self.max_failures = max_consecutive_failures
        self.mesh = mesh
        self.auto_restart = auto_restart
        # Bounded wait for an engine restart: a rebuild compiles for
        # minutes on chip (legitimate), but a restart against a WEDGED
        # chip never returns — unbounded, it would hang the monitor loop
        # and end all probing (incl. of the healthy tier).  Past the cap
        # the worker is abandoned (it keeps the manager lock; no second
        # restart stacks while it lives) and probing continues.
        self.restart_timeout_s = restart_timeout_s
        self._fail_counts: Dict[str, int] = {}
        self._seen_running: Dict[str, bool] = {}
        self._last: Dict[str, Dict[str, Any]] = {}
        self._restarts: Dict[str, int] = {}
        # Restart workers abandoned past restart_timeout_s: each one
        # still HOLDS its manager lock (a wedged-chip rebuild never
        # returns), so until now it silently blocked every later restart
        # of that tier with no observable signal — counted and exposed
        # in the per-tier health entries.
        self._restarts_abandoned: Dict[str, int] = {}
        self._restarting: Dict[str, threading.Thread] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()

    # -- probing -----------------------------------------------------------

    def _probe_tier(self, name: str, mgr) -> Tuple[str, Dict[str, Any]]:
        """-> (state, health): state ∈ {running, stopped, failed}."""
        try:
            running = mgr.is_server_running()
            health = mgr.health()
        except Exception as exc:
            return "failed", {"ok": False, "error": str(exc)}  # dllm-lint: disable=error-shape -- health-probe snapshot (GET /health surface: ok+error), not the tier error path
        if health.get("draining"):
            # Graceful drain (EngineManager.drain) is INTENTIONAL
            # shedding: never a failure, never restarted — a restart
            # would resurrect a tier the operator is taking down.
            return "draining", health
        if not running:
            # A DEAD remote is classified failed above (health() raises
            # into the except).  This branch covers the remote that still
            # ANSWERS /health but reports not-ok: a remote-lifecycle tier
            # has no deliberate local stop, so once seen running that
            # also means failure (restart may respawn via spawn_cmd).
            # Local tiers keep the stopped/failed distinction (a lazily-
            # stopped engine between benchmark configs must not be
            # restarted).
            if (getattr(mgr, "remote_lifecycle", False)
                    and self._seen_running.get(name)):
                return "failed", {**health, "ok": False}
            return "stopped", health
        # Running but unhealthy (e.g. a batching engine whose scheduler
        # thread died) counts as failed.
        engine = getattr(mgr, "_engine", None)
        loop_dead = (engine is not None
                     and getattr(engine, "_thread", True) is None
                     and hasattr(engine, "submit"))
        if not health.get("ok") or loop_dead:
            return "failed", {**health, "ok": False}
        return "running", health

    def _account_probe(self, key: str, state: str,
                       health: Dict[str, Any]
                       ) -> Tuple[Dict[str, Any], bool]:
        """Fold one probe result into the failure/restart accounting
        under the monitor lock; returns (snapshot entry, restart?).
        ``key`` is a tier name or a replica key ("nano/r0") — replicas
        carry their own failure streaks and restart counters, so one
        flapping replica never consumes its siblings' probe budget."""
        wedged = bool(health.get("wedged"))
        restart = False
        with self._lock:
            if state == "running":
                self._fail_counts[key] = 0
                self._seen_running[key] = True
            elif state == "failed" and (self._seen_running.get(key)
                                        or wedged):
                if wedged:
                    # Decode watchdog: stalled step progress is
                    # DIRECT wedge evidence (manager health flipped
                    # past tier.watchdog_stall_s) — restart through
                    # the existing bounded path NOW instead of
                    # waiting out probe-count escalation.  (A wedged
                    # engine necessarily ran, so seen_running is not
                    # required.)
                    self._fail_counts[key] = max(
                        self._fail_counts.get(key, 0) + 1,
                        self.max_failures)
                else:
                    self._fail_counts[key] = \
                        self._fail_counts.get(key, 0) + 1
                restart = (self.auto_restart
                           and self._fail_counts[key] >= self.max_failures)
            entry = {**health, "state": state,
                     "consecutive_failures": self._fail_counts.get(key, 0),
                     "restarts": self._restarts.get(key, 0),
                     "restarts_abandoned":
                         self._restarts_abandoned.get(key, 0)}
            self._last[key] = entry
        return entry, restart

    def _probe_replicated(self, name: str, tier, items,
                          breaker, to_restart) -> Dict[str, Any]:
        """Probe a replicated tier's replicas INDIVIDUALLY: each replica
        keeps its own failure streak and restart target, so one wedged
        replica restarts alone while the survivors keep serving — the
        tier-level entry aggregates (ok while any replica runs).  A
        successful replica restart force-closes only THAT replica's
        breaker sub-gate (ReplicatedTierClient.reset_replica); the
        tier-level breaker recovers through its own canary.

        ``items`` is a (rid, manager) snapshot: rids are the STABLE
        replica ids (never reused under dynamic membership), so probe
        keys, failure streaks, and restart targets keep meaning the
        same engine across scale events."""
        reps: Dict[str, Dict[str, Any]] = {}
        states: List[str] = []
        for rid, sub in items:
            rkey = f"{name}/r{rid}"
            state, health = self._probe_tier(rkey, sub)
            entry, restart = self._account_probe(rkey, state, health)
            if restart:
                def _on_restarted(tc=tier, rid=rid):
                    fn = getattr(tc, "reset_replica", None)
                    if callable(fn):
                        fn(rid)
                # Crash-rescue restart (ISSUE 20): route through the
                # tier client's restart_replica — in-flight rescue,
                # spill survival, and the scale busy flag (a restart
                # racing a scale-down is REFUSED; the raise below
                # keeps the failure streak so the next probe retries,
                # same as a refused autoscaler actuation).  Only when
                # the probed manager IS the member the client would
                # restart — duck-typed manager sets swapped under the
                # tier (tests) fall back to the direct stop/start.
                restart_fn = None
                rescue = getattr(tier, "restart_replica", None)
                member_of = getattr(tier, "member_manager", None)
                if (callable(rescue) and callable(member_of)
                        and member_of(rid) is sub):
                    def restart_fn(tc=tier, rid=rid):
                        summary = tc.restart_replica(
                            rid, reason="health probe")
                        if not summary.get("restarted"):
                            errs = (summary.get("errors")
                                    or ["restart failed"])
                            raise RuntimeError(str(errs[0]))
                to_restart.append((rkey, sub, _on_restarted,
                                   restart_fn))
            reps[rkey] = entry
            states.append(state)
        # Retired replicas (scale-down) leave the per-key bookkeeping:
        # their streak/restart state must not resurrect if the rid's
        # slot pattern ever matched a later snapshot key, and /health
        # must not keep showing a replica membership dropped.
        with self._lock:
            prefix = f"{name}/r"
            for key in [k for k in self._last
                        if k.startswith(prefix) and k not in reps]:
                self._last.pop(key, None)
                self._fail_counts.pop(key, None)
                self._seen_running.pop(key, None)
        running = sum(1 for s in states if s == "running")
        if running:
            tier_state = "running"
        elif states and all(s == "draining" for s in states):
            tier_state = "draining"
        elif states and all(s == "stopped" for s in states):
            tier_state = "stopped"
        else:
            tier_state = "failed"
        tier_entry = {
            "ok": running > 0,
            "state": tier_state,
            "healthy_replicas": running,
            "replica_count": len(items),
            "degraded": 0 < running < len(items),
            "replicas": reps,
        }
        with self._lock:
            self._last[name] = tier_entry
        if breaker is not None and tier_state != "draining":
            try:
                breaker.note_probe(name, running > 0)
            except Exception:
                pass
        return tier_entry

    def probe_once(self) -> Dict[str, Dict[str, Any]]:
        """One liveness pass.  Restarts (outside the lock — it can compile
        for tens of seconds) only tiers that were seen running and then
        failed ``max_consecutive_failures`` probes in a row; replicated
        tiers probe and restart per replica."""
        snapshot: Dict[str, Dict[str, Any]] = {}
        # (key, manager, on-restarted callback or None, rescue restart
        # fn or None — replicated tiers route restarts through
        # ReplicatedTierClient.restart_replica when set)
        to_restart: List[Tuple[str, Any, Any, Any]] = []

        breaker = getattr(self.router, "breaker", None)
        for name, tier in self.router.tiers.items():
            mgr = tier.server_manager
            items_fn = getattr(mgr, "replica_items", None)
            subs = getattr(mgr, "replica_managers", None)
            if callable(items_fn):
                snapshot[name] = self._probe_replicated(
                    name, tier, items_fn(), breaker, to_restart)
                continue
            if callable(subs):
                # Duck-typed replica sets without stable ids (tests):
                # positional fallback, the pre-dynamic behavior.
                snapshot[name] = self._probe_replicated(
                    name, tier, list(enumerate(subs())), breaker,
                    to_restart)
                continue
            state, health = self._probe_tier(name, mgr)
            entry, restart = self._account_probe(name, state, health)
            if restart:
                def _on_restarted(n=name, b=breaker):
                    # A successful restart voids the failure streak that
                    # opened the tier's circuit: force-close so traffic
                    # returns without waiting out the cooldown.
                    if b is not None:
                        try:
                            b.reset(n)
                        except Exception:
                            pass
                to_restart.append((name, mgr, _on_restarted, None))
            snapshot[name] = entry
            # Half-open probing rides the liveness cadence: a healthy
            # probe of an OPEN tier past its cooldown advances the
            # breaker to half-open, so recovery doesn't need a client
            # request to discover the cooldown expired.
            if breaker is not None and state != "draining":
                # Draining is intentional: feeding it to the breaker as
                # either verdict would misrepresent deliberate shedding.
                try:
                    breaker.note_probe(name, state == "running")
                except Exception:
                    pass

        for name, mgr, on_restarted, restart_fn in to_restart:
            prev = self._restarting.get(name)
            if prev is not None and prev.is_alive():
                logger.warning("tier %s restart still in flight — not "
                               "stacking another", name)
                continue
            logger.warning("tier %s unhealthy after %d probes — restarting",
                           name, self.max_failures)

            def _restart(name=name, mgr=mgr, on_restarted=on_restarted,
                         restart_fn=restart_fn):
                try:
                    if restart_fn is not None:
                        # Rescue-capable path: a busy refusal (restart
                        # racing a scale) raises, landing in the except
                        # below — fail counts KEEP the streak, so the
                        # next probe retries the restart.
                        restart_fn()
                    else:
                        mgr.stop_server()
                        mgr.start_server()
                    with self._lock:
                        self._restarts[name] = self._restarts.get(name, 0) + 1
                        self._fail_counts[name] = 0
                        if name in self._last:
                            self._last[name]["restarts"] = \
                                self._restarts[name]
                    # A successful restart voids the failure streak: the
                    # callback force-closes the right circuit (the tier's
                    # for flat tiers, only THAT replica's sub-gate for a
                    # replicated tier) so traffic returns without waiting
                    # out the cooldown.
                    if on_restarted is not None:
                        try:
                            on_restarted()
                        except Exception:
                            pass
                except Exception as exc:
                    logger.error("tier %s restart failed: %s", name, exc)

            worker = threading.Thread(target=_restart, daemon=True,
                                      name=f"restart-{name}")
            self._restarting[name] = worker
            worker.start()
            # Synchronous in the healthy case (tests and the dryrun rely
            # on probe_once returning with the restart done); bounded so
            # a wedged-chip rebuild can't end all probing.
            worker.join(self.restart_timeout_s)
            if worker.is_alive():
                logger.error("tier %s restart exceeded %.0fs — abandoning "
                             "the worker and continuing to probe",
                             name, self.restart_timeout_s)
                with self._lock:
                    self._restarts_abandoned[name] = \
                        self._restarts_abandoned.get(name, 0) + 1
                    if name in self._last:
                        self._last[name]["restarts_abandoned"] = \
                            self._restarts_abandoned[name]
        return snapshot

    # -- cross-host perf exchange ------------------------------------------

    def _perf_strategy(self):
        strategy = getattr(self.router.query_router, "router", None)
        if strategy is not None and hasattr(strategy, "merge_remote"):
            return strategy           # PerfStrategy only (hybrid has none)
        return None

    def _participants(self) -> Tuple[int, np.ndarray]:
        """(row count, remote mask) along the mesh's first axis: row i is
        remote iff the device at index i along that axis belongs to another
        process (multi-host pod)."""
        axis = self.mesh.axis_names[0]
        n = self.mesh.shape[axis]
        # Devices along the first axis, holding other axes at index 0.
        lead = np.moveaxis(self.mesh.devices,
                           self.mesh.axis_names.index(axis), 0)
        lead = lead.reshape(n, -1)[:, 0]
        me = jax.process_index()
        remote = np.array([d.process_index != me for d in lead])
        return n, remote

    def exchange_health(self) -> Optional[Dict[str, np.ndarray]]:
        """All-gather each tier's perf summary over the mesh; fold rows
        owned by other processes into the local perf strategy.  Returns the
        gathered rows per tier (None without a mesh or perf strategy).

        When the strategy is queue-aware, each tier's live load row
        ([queue_depth, active_slots, max_slots], serving/tiers.py
        load_snapshot) rides the same ICI allgather, and the local
        strategy scores the cluster-wide totals — a tier saturated on
        ANY host sheds traffic everywhere.  On a single host the Router
        feeds the local snapshot directly (serving/router.py
        _feed_perf_load); this exchange only adds the cross-host sum."""
        perf = self._perf_strategy()
        if self.mesh is None or perf is None:
            return None
        # Imported lazily: the mesh collectives need jax.shard_map, which
        # some deployment jaxlibs lack — liveness probing, the decode
        # watchdog, and restart/breaker plumbing must keep working there
        # (the cross-host perf exchange is the only piece that needs it).
        from ..parallel.collectives import (allgather_health,
                                            summarize_perf_window)
        n, remote_mask = self._participants()
        gathered: Dict[str, np.ndarray] = {}
        for tier_name, samples in perf.samples.items():
            row = summarize_perf_window(list(samples))
            rows = np.tile(row, (n, 1))   # every participant contributes its
            out = allgather_health(self.mesh, rows)   # own row in its slot
            gathered[tier_name] = out
            self._merge_gathered(perf, tier_name, out, remote_mask)
        self._exchange_load(perf, n, remote_mask)
        return gathered

    def _exchange_load(self, perf, n: int,
                       remote_mask: Sequence[bool]) -> None:
        """Allgather queue/slot load rows and feed the cluster-wide
        totals into the queue-aware perf strategy (no-op when the
        strategy isn't queue-aware).  Same participant convention as
        the perf-window merge: each row along the mesh's first axis is
        one contributor; remote rows sum on top of the local
        snapshot."""
        if not (getattr(perf, "queue_aware", False)
                and hasattr(perf, "update_load")):
            return
        from ..parallel.collectives import allgather_health
        # Iterate the STRATEGY's fixed tier set (nano+orin on every
        # host) and always run the allgather, contributing a zero row
        # when the local tier has no load to report (remote-endpoint
        # tier, or a transient snapshot failure): a mesh collective's
        # call count must be identical on every participant, or this
        # tick's load exchange pairs against another host's perf-window
        # exchange and corrupts both (or hangs the mesh).
        for name in perf.samples:
            tier = self.router.tiers.get(name)
            snap = None
            snap_fn = getattr(tier, "load_snapshot", None)
            if snap_fn is not None:
                try:
                    snap = snap_fn()
                except Exception:
                    snap = None
            row = (np.array([snap["queue_depth"], snap["active_slots"],
                             snap["max_slots"]], np.float32)
                   if snap is not None else np.zeros(3, np.float32))
            rows = np.tile(row, (n, 1))
            out = allgather_health(self.mesh, rows)
            # Remote rows ONLY: the local part is fed per-decision by
            # the Router (_feed_perf_load) — summing it here too would
            # double-count, and storing local+remote under one key would
            # let the next local refresh clobber the remote view.
            remote = np.zeros(3, np.float32)
            for i, r in enumerate(out):
                if remote_mask[i]:
                    remote += r
            perf.update_load(name, queue_depth=float(remote[0]),
                             active_slots=float(remote[1]),
                             max_slots=max(1.0, float(remote[2])),
                             remote=True)

    @staticmethod
    def _merge_gathered(perf, tier_name: str, rows: np.ndarray,
                        remote_mask: Sequence[bool]) -> None:
        """Fold REMOTE rows only (mask True) into the perf strategy as
        representative samples."""
        for i, row in enumerate(rows):
            if not remote_mask[i]:
                continue
            lat, tok, ok_count, n_samples = row
            n_samples = int(n_samples)
            if n_samples <= 0:
                continue
            k = min(n_samples, 5)         # cap synthetic samples per host
            mean_lat = float(lat) / n_samples
            mean_tok = max(1, int(tok) // n_samples)
            ok_true = round(float(ok_count) / n_samples * k)
            samples: List[Tuple[float, int, bool]] = [
                (mean_lat, mean_tok, j < ok_true) for j in range(k)]
            perf.merge_remote(tier_name, samples)

    # -- lifecycle ---------------------------------------------------------

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.probe_once()
                self.exchange_health()
            except Exception:
                logger.exception("health monitor tick failed")
            self._stop.wait(self.interval_s)

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="health-monitor")
        self._thread.start()

    def stop(self) -> None:
        if self._thread is None:
            return
        self._stop.set()
        self._thread.join(timeout=2 * self.interval_s)
        self._thread = None

    def snapshot(self) -> Dict[str, Dict[str, Any]]:
        with self._lock:
            return {k: dict(v) for k, v in self._last.items()}
