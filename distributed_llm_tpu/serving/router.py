"""Router — the serving orchestration pipeline.

Reference parity: src/router.py.  Same constructor signature, same
``route_query(history) -> (response_dict, tokens, device)`` contract, same
response-dict keys, and the same pipeline stages:

  0) response-cache check (production mode only; key = strategy + query text,
     deliberately context-independent — reference: src/router.py:57-59,179)
  1) routing decision via QueryRouter, with context-size threshold fallback
     if the routing engine raises (src/router.py:258-270)
  2) tier inference + one-shot failover to the other tier on an error-shaped
     response (src/router.py:277-282)
  3) text normalization + token count
  4) perf feedback into the perf strategy (src/router.py:292-295)
  5) response-cache store

What changed underneath: tiers are in-process TPU engines on chip submeshes
(serving/tiers.py) instead of SSH-tunneled Jetson boards, so `_run_device`
is a function call, not an HTTP POST.
"""

from __future__ import annotations

import hashlib
import logging
import random
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax

from ..config import (ClusterConfig, bench_cluster, resolve_config,
                      tiny_cluster)
from ..config_registry import env_float, env_int, env_str
from ..obs import Observability, get_observability
from ..obs import spans as obs_spans
from ..obs.metrics import breaker_state_value
from ..obs.sampler import SystemStateSampler
from ..obs.slo import SLOMonitor
from ..obs.spans import current_trace, use_trace
from ..routing.engine import QueryRouter
from ..routing.token_counter import TokenCounter
from ..utils.faults import FaultInjector
from .errors import is_error_shape
from .tenants import DEFAULT_TENANT
from .tiers import TierClient, build_tiers

logger = logging.getLogger(__name__)

# Error-shape substrings the bounded retry treats as TRANSIENT (a fresh
# attempt on the same tier plausibly succeeds in milliseconds): connection-
# level races and an engine that shut down mid-flight.  Deliberately NOT
# timeouts — a timed-out call already consumed its whole request budget,
# and retrying it would double the client's wait for the same outcome —
# and NOT admission rejections, where the queue is full and immediate
# re-entry would only re-reject (failover is the productive move).
_TRANSIENT_MARKERS = (
    "connection refused",
    "connection reset",
    "reset by peer",
    "temporarily unavailable",
    "engine returned no result",
    "(transient)",
)


def default_cluster(cpu_bench: bool = False) -> ClusterConfig:
    """Bench-sized tiers on an accelerator.  On host CPU: the tiny test
    tiers — unless ``cpu_bench`` is set (the headline bench opts in),
    where the quality-asymmetric cpu_bench pair (mini_bench under
    nano_bench-as-orin, config.cpu_bench_cluster) serves when both
    presets have published checkpoints, so the chipless headline runs
    genuinely trained, premise-consistent tiers (VERDICT r4 #2).  The
    opt-in is an explicit parameter, not ambient state: the ~26M/130M
    pair would make the unit suite's hundreds of default Routers
    unusably slow on one core.  Either way the tiers serve published
    pretrained weights when ``checkpoints/<preset>`` exists
    (training/pretrain.py)."""
    from ..config import (cpu_bench_cluster, default_checkpoint,
                          tiny_batched_cluster, with_default_checkpoints)
    if jax.default_backend() != "cpu":
        return with_default_checkpoints(bench_cluster())
    if cpu_bench:
        cpu_pair = cpu_bench_cluster()
        if all(default_checkpoint(t.model_preset)
               for t in cpu_pair.tiers()):
            return with_default_checkpoints(cpu_pair)
    # Concurrent-by-default even on the tiny CPU fallback: serving entry
    # points and the chipless bench get batched tiers (the unit suite
    # builds tiny_cluster() directly and keeps the cheaper sequential
    # warmup).
    return with_default_checkpoints(tiny_batched_cluster())


class Router:
    def __init__(
        self,
        strategy: str = "hybrid",
        config: Optional[Dict[str, Any]] = None,
        threshold_fallback: int = 100,
        benchmark_mode: bool = False,
        cluster: Optional[ClusterConfig] = None,
        devices: Optional[Sequence[jax.Device]] = None,
        fault_injector: Optional[FaultInjector] = None,
        observability: Optional[Observability] = None,
    ):
        """strategy: "token" | "semantic" | "heuristic" | "hybrid" | "perf"
        benchmark_mode: True → BENCHMARK_CFG (cache off), False →
        PRODUCTION_CFG, unless ``config`` overrides (src/router.py:37-40).
        observability: metric/trace/flight-recorder bundle (obs/); None =
        the process-global default — injectable so bench legs and tests
        read registries no other traffic writes to."""
        self.token_counter = TokenCounter()
        self.obs = (observability if observability is not None
                    else get_observability())
        self.threshold_fallback = threshold_fallback
        self.benchmark_mode = benchmark_mode
        self.config = resolve_config(config, benchmark_mode)

        self.cluster = cluster or default_cluster()
        self.faults = fault_injector
        self.tiers: Dict[str, TierClient] = build_tiers(
            self.cluster, devices=devices, fault_injector=fault_injector)
        # Reference attribute surface (tester uses router.nano.server_manager)
        self.nano = self.tiers["nano"]
        self.orin = self.tiers["orin"]

        self.query_router = QueryRouter(strategy=strategy, config=self.config)

        # Per-tier circuit breaker (serving/breaker.py): consulted before
        # dispatch so an OPEN tier sheds traffic in microseconds instead
        # of each request discovering the outage via a timeout.
        # breaker_failures=0 in the cluster disables it (pure reference
        # per-call failover semantics).
        self.breaker = None
        if getattr(self.cluster, "breaker_failures", 0):
            from .breaker import CircuitBreaker
            self.breaker = CircuitBreaker(
                [t.name for t in self.cluster.tiers()],
                failure_threshold=self.cluster.breaker_failures,
                cooldown_s=self.cluster.breaker_cooldown_s,
                on_transition=self._obs_breaker_transition)
            # Export a closed (0) state sample per tier up front: a
            # dashboard must read 0 for a healthy breaker, not "no
            # series" — absence would be indistinguishable from the
            # breaker being disabled.
            for t in self.cluster.tiers():
                self.obs.m.breaker_state.labels(t.name).set(0)
        # Bounded retry for transient error shapes (_TRANSIENT_MARKERS):
        # budgeted against the dispatching tier's request_timeout_s so
        # retry + failover never exceed the reference's per-request cap.
        self.retry_attempts = max(0, int(getattr(self.cluster,
                                                 "retry_attempts", 0)))
        self.retry_backoff_s = float(getattr(self.cluster,
                                             "retry_backoff_s", 0.05))
        self.degraded_served = 0       # both-tiers-open responses served
        # Graceful drain (drain()): once True the serving edge
        # (serving/app.py) answers 503 + retry_after_s and no new request
        # enters the pipeline; in-flight requests finish normally.
        self.draining = False

        # SLO goodput monitor (obs/slo.py): per-(strategy, tier) sliding-
        # window goodput + overload incidents, fed ONLY from
        # _finish_request (the obs_discipline lint pins the single feed
        # site).  Targets come from each tier's slo_ttft_ms/slo_tbt_ms,
        # globally overridable via DLLM_SLO_TTFT_MS / DLLM_SLO_TBT_MS.
        self.slo = SLOMonitor(self._slo_targets(), metrics=self.obs.m,
                              recorder=self.obs.recorder,
                              timeline=self._timeline_tail)
        # Continuous system-state timeline (obs/sampler.py): a lazy
        # daemon thread (started at first request, stopped by drain())
        # sampling per-tier queue/slot/KV/breaker/tick state every
        # DLLM_OBS_SAMPLE_MS into a bounded ring; '0' disables it.
        self.sampler: Optional[SystemStateSampler] = None
        sample_ms = env_float("DLLM_OBS_SAMPLE_MS", 250.0)
        if sample_ms > 0:
            self.sampler = SystemStateSampler(
                self._sampler_collect, metrics=self.obs.m,
                period_s=sample_ms / 1000.0,
                capacity=env_int("DLLM_OBS_TIMELINE_SAMPLES", 240))

        # Bounded per-(tier, strategy, session) cost ledger (ISSUE 11):
        # the GET /stats-inspectable aggregate of the attribution the
        # _finish_request exit feeds to the dllm_device_time_ms_total /
        # dllm_kv_block_ticks_total families.  Insertion-ordered with
        # oldest-key eviction past the cap, so a session flood cannot
        # grow it without bound (the metric families keep the full
        # label space; this is the one-call operator view).
        self._cost_lock = threading.Lock()
        self._cost_ledger: "Dict[Tuple[str, str, str], Dict[str, float]]" \
            = {}
        self._cost_ledger_cap = 256
        # Session METRIC-LABEL guard: session_id is client-controlled
        # at the /chat edge, and a Prometheus label value mints a
        # permanent counter child — without a bound, one adversarial
        # client (or just organic session churn) grows the registry and
        # the /metrics payload forever.  First N distinct sessions keep
        # their own label (truncated); the rest aggregate under
        # "~overflow".  The ledger evicts; label children cannot.
        self._session_labels: set = set()
        self._session_label_cap = 256
        # Tenant over-quota incident edge (ISSUE 17): the FIRST quota
        # rejection for a tenant opens a flight-recorder incident naming
        # it (the over-quota tenant that triggered shedding is exactly
        # what the noisy-neighbor post-mortem needs); a later ADMITTED
        # request from the same tenant finalizes it with the rejection
        # count absorbed meanwhile.  Bounded: at most
        # ``_session_label_cap`` distinct open-tenant slots ever.
        self._tenant_incidents: Dict[str, Dict[str, Any]] = {}

        self.enable_response_cache = (
            not benchmark_mode
            and bool(self.config.get("enable_response_cache", False)))
        self.cache_last_k = int(self.config.get("cache_last_k", 6))
        self.enable_failover = bool(self.config.get("enable_failover", True))
        # Prefix-affinity routing (production only, beyond-reference): a
        # low-confidence decision is steered to the tier that already
        # holds this conversation's parked KV prefix — a cold re-prefill
        # elsewhere throws away an O(history) cache the engines worked
        # to keep.  Labeled-accuracy benchmarks keep reference semantics
        # (off in benchmark_mode and in BENCHMARK_CFG).
        self.enable_prefix_affinity = (
            not benchmark_mode
            and bool(self.config.get("enable_prefix_affinity", False)))
        self.prefix_affinity_min_confidence = float(
            self.config.get("prefix_affinity_min_confidence", 0.75))
        self.prefix_affinity_min_tokens = int(
            self.config.get("prefix_affinity_min_tokens", 32))
        self.prefix_affinity_overrides = 0
        self._response_store: Dict[str, Dict[str, Any]] = {}

        # Continuous liveness probing + ICI health exchange (serving/
        # health.py) — off by default to keep bench runs deterministic.
        self.health_monitor = None
        if self.config.get("enable_health_monitor", False):
            from .health import HealthMonitor
            self.health_monitor = HealthMonitor(
                self,
                interval_s=float(self.config.get("health_interval_s", 5.0)),
                mesh=self.config.get("health_mesh"))
            self.health_monitor.start()

        # Elastic capacity (serving/autoscaler.py, ISSUE 18): one
        # control loop per ARMED tier (TierConfig.autoscale), actuating
        # replica membership from the SLO/queue/shed signals above.
        # The DLLM_AUTOSCALE=0 kill switch — or simply no armed tier —
        # builds nothing: the static PR 12 membership path stays
        # byte-identical (pinned by test).
        self.autoscalers: Dict[str, Any] = {}
        if (env_str("DLLM_AUTOSCALE", "1") or "1") != "0":
            from .autoscaler import ReplicaAutoscaler
            for t in self.cluster.tiers():
                client = self.tiers.get(t.name)
                if (getattr(t, "autoscale", False)
                        and callable(getattr(client, "scale_to", None))):
                    scaler = ReplicaAutoscaler(
                        t.name, t, client, self.slo, metrics=self.obs.m)
                    scaler.start()
                    self.autoscalers[t.name] = scaler

    # -- back-compat (src/router.py:65-67) ---------------------------------

    def set_threshold(self, threshold: int) -> None:
        self.threshold_fallback = threshold

    # -- graceful drain ----------------------------------------------------

    def drain_retry_after_s(self) -> float:
        """Client retry hint while draining: the longest tier drain
        deadline (past it the process is gone or restarted)."""
        vals = []
        for tier in self.tiers.values():
            cfg = getattr(tier, "tier", None)
            val = getattr(cfg, "drain_timeout_s", None)
            if val:
                vals.append(float(val))
        return round(max(vals), 2) if vals else 30.0

    def drain(self, timeout_s: Optional[float] = None) -> Dict[str, Any]:
        """Graceful shutdown of the whole router (SIGTERM path): flip the
        serving edge to 503 (serving/app.py checks ``draining``), stop
        the health monitor (a drain must not race an auto-restart), then
        drain every tier concurrently — each stops admitting, lets its
        in-flight requests finish under ``drain_timeout_s``, and stops.
        Idempotent; returns the per-tier drain summaries."""
        self.draining = True
        if self.health_monitor is not None:
            try:
                self.health_monitor.stop()
            except Exception:
                pass
        # Autoscalers stop BEFORE the tier drains fan out: a controller
        # mid-tick must not actuate membership against a draining tier.
        for scaler in getattr(self, "autoscalers", {}).values():
            try:
                scaler.stop()
            except Exception:
                pass
        # The state sampler dies with the router: a drained process must
        # not keep a timeline thread alive (it is a daemon either way,
        # but stop() makes the shutdown clean and testable).
        if self.sampler is not None:
            try:
                self.sampler.stop()
            except Exception:
                pass
        results: Dict[str, Any] = {}
        cap = (timeout_s if timeout_s is not None
               else self.drain_retry_after_s()) + 30.0
        threads = []
        for name, tier in self.tiers.items():
            fn = getattr(tier.server_manager, "drain", None)
            if not callable(fn):
                # Managers without a drain (remote tiers) still get
                # STOPPED: the pre-drain shutdown path killed their
                # spawned processes, and graceful must not leak them.
                fn, label = tier.server_manager.stop_server, "stopped"
            else:
                label = None

            def _drain(name=name, fn=fn, label=label):
                try:
                    out = fn() if label else fn(timeout_s)
                    results[name] = (out if label is None
                                     else {"draining_started": False,
                                           label: True})
                except Exception as exc:
                    results[name] = {"error": f"Request failed: {exc}"}

            t = threading.Thread(target=_drain, daemon=True,
                                 name=f"drain-{name}")
            threads.append(t)
            t.start()
        deadline = time.monotonic() + cap
        for t in threads:
            # Bounded even against a wedged stop_server: the process is
            # exiting, and a hung drain must not block the signal path.
            t.join(timeout=max(0.0, deadline - time.monotonic()))
        logger.info("router drain complete: %s", results)
        return results

    # -- observability plumbing (obs/) -------------------------------------

    def _obs_breaker_transition(self, tier: str, old: str, new: str) -> None:
        """Breaker state changes → transition counter + state gauge."""
        m = self.obs.m
        m.breaker_transitions.labels(tier, new).inc()
        m.breaker_state.labels(tier).set(breaker_state_value(new))

    def _slo_targets(self) -> Dict[str, Tuple[Optional[float],
                                              Optional[float]]]:
        """Per-tier (slo_ttft_ms, slo_tbt_ms) targets for the goodput
        monitor: the tier's configured values, with the DLLM_SLO_* env
        overrides winning globally when set (an operator re-judging a
        live box against a tighter SLO must not need a config rebuild)."""
        def parse(raw: Optional[str]) -> Optional[float]:
            if raw is None or not str(raw).strip():
                return None
            try:
                return float(raw)
            except ValueError:
                return None                  # garbage never loses the run

        o_ttft = parse(env_str("DLLM_SLO_TTFT_MS"))
        o_tbt = parse(env_str("DLLM_SLO_TBT_MS"))
        return {
            t.name: (o_ttft if o_ttft is not None
                     else getattr(t, "slo_ttft_ms", None),
                     o_tbt if o_tbt is not None
                     else getattr(t, "slo_tbt_ms", None))
            for t in self.cluster.tiers()
        }

    def _ensure_sampler(self) -> None:
        """Lazy sampler start at first request: routers that never serve
        (the unit suite builds hundreds) must not each spawn a thread."""
        s = self.sampler
        if s is not None and not s.running and not self.draining:
            s.start()

    def _timeline_tail(self, n: int = 40) -> list:
        s = self.sampler
        return s.tail(n) if s is not None else []

    def timeline_snapshot(self) -> list:
        """The GET /stats?timeline=1 body: the full timeline ring,
        sampling once on demand when the ring is empty (an idle router
        still answers with its CURRENT state, not an empty list)."""
        s = self.sampler
        if s is None:
            return []
        if not len(s):
            try:
                s.sample_once()
            except Exception:
                pass
        return s.snapshot()

    def _sampler_collect(self) -> Dict[str, Dict[str, Any]]:  # dllm-lint: hot-path
        """One timeline sample's per-tier state.  Lock-free / own-locked
        in-memory reads ONLY (load_snapshot, kv_stats, the tick ring,
        the draining flag) — never manager.health(), and never anything
        touching the lifecycle lock a mid-compile engine holds for
        minutes: the sampler must keep sampling THROUGH the states it
        exists to explain.  Hot-path root for the transfer lint (the
        callback is invoked through a callable value, which the static
        call graph cannot follow — so it is annotated in its own
        right)."""
        out: Dict[str, Dict[str, Any]] = {}
        breaker_snap = (self.breaker.snapshot()
                        if self.breaker is not None else {})
        for name, tier in self.tiers.items():
            st: Dict[str, Any] = {}
            snap_fn = getattr(tier, "load_snapshot", None)
            if callable(snap_fn):
                try:
                    st.update(snap_fn())
                except Exception:
                    pass
            mgr = tier.server_manager
            subs = getattr(mgr, "live_engines", None)
            if callable(subs):
                # Replicated tier (ISSUE 12): the tier-level entry reads
                # the AGGREGATE kv picture from the ReplicaSetManager
                # (summed pools, max dedup) plus the healthy-capacity
                # fraction; each replica then gets its OWN entry keyed
                # "tier/rN" so every gauge family grows a per-replica
                # series and the timeline carries the breakdown.  ONE
                # kv_stats pass per sample: the aggregate call already
                # returns the per-replica breakdown, which the replica
                # entries reuse instead of re-reading each pool.
                agg_kv = None
                kv_fn = getattr(mgr, "kv_stats", None)
                if callable(kv_fn):
                    try:
                        agg_kv = kv_fn()
                    except Exception:
                        agg_kv = None
                st.update(self._collect_engine_state(mgr, kv=agg_kv))
                healthy_fn = getattr(tier, "healthy_replicas", None)
                if callable(healthy_fn):
                    try:
                        st["replica_healthy"] = int(healthy_fn())
                    except Exception:
                        pass
                # Keyed by replica NAME, not position: dynamic
                # membership (ISSUE 18) removes members mid-run, and a
                # positional lookup would pin the wrong manager's
                # draining flag on the survivors.
                items_fn = getattr(mgr, "replica_items", None)
                mgr_by_key = ({f"r{rid}": sub for rid, sub in items_fn()}
                              if callable(items_fn)
                              else {f"r{i}": sub for i, sub in
                                    enumerate(mgr.replica_managers())})
                rb = getattr(tier, "breaker", None)
                st["replica_count"] = len(mgr_by_key)
                rep_kv = (agg_kv or {}).get("replicas") or {}
                for key, engine in subs():
                    rst = self._collect_engine_state(
                        engine, kv=rep_kv.get(key))
                    slots_fn = getattr(engine, "slot_stats", None)
                    if callable(slots_fn):
                        try:
                            ss = slots_fn()
                            rst["queue_depth"] = ss.get("queue_depth")
                            rst["active_slots"] = ss.get("active_slots")
                            rst["max_slots"] = ss.get("max_slots")
                        except Exception:
                            pass
                    sub = mgr_by_key.get(key)
                    if sub is not None:
                        rst["draining"] = bool(sub.draining)
                    if rb is not None:
                        rst["breaker"] = rb.state(key)
                    out[f"{name}/{key}"] = rst
            else:
                engine = getattr(mgr, "_engine", None)
                st.update(self._collect_engine_state(engine))
            st["draining"] = bool(getattr(mgr, "draining", False))
            b = breaker_snap.get(name)
            if b is not None:
                st["breaker"] = b.get("state")
            out[name] = st
        return out

    _KV_FETCH = object()      # sentinel: "read kv_stats off the engine"

    @staticmethod
    def _collect_engine_state(engine, kv=_KV_FETCH
                              ) -> Dict[str, Any]:  # dllm-lint: hot-path
        """One engine's (or a ReplicaSetManager aggregate's) sampler
        fields — the per-entry half of ``_sampler_collect``, shared by
        the flat tier path, the replicated tier-level aggregate, and the
        per-replica entries.  ``kv`` overrides the kv_stats read with a
        precomputed dict (or None = no pool) so the replicated path pays
        ONE pool read per sample, not two.  Same lock-free discipline:
        advisory own-locked reads only, never the lifecycle lock."""
        st: Dict[str, Any] = {}
        ks = None
        if kv is Router._KV_FETCH:
            kv_fn = getattr(engine, "kv_stats", None)
            if callable(kv_fn):
                try:
                    ks = kv_fn()
                except Exception:
                    ks = None
        else:
            ks = kv
        if isinstance(ks, dict) and ks:
            try:
                st["kv_free_blocks"] = ks.get("free_blocks")
                st["kv_reclaimable_blocks"] = ks.get(
                    "reclaimable_blocks")
                # Shared-prefix KV (ISSUE 10): physical blocks with
                # multiple holders and the dedup factor — the
                # dllm_kv_shared_blocks / dllm_kv_dedup_ratio
                # gauges' source series.
                st["kv_shared_blocks"] = ks.get("shared_blocks", 0)
                st["kv_dedup_ratio"] = ks.get("dedup_ratio", 1.0)
                st["preempted_total"] = ks.get("preempted_total", 0)
                # Hierarchical-KV spill tier (ISSUE 14): host-side
                # occupancy + promotion backlog ride the timeline so a
                # degraded warm-hit rate is diagnosable from the same
                # flight-recorder slice as the pool pressure it caused.
                if "host_blocks" in ks:
                    st["kv_host_blocks"] = ks.get("host_blocks")
                    st["kv_host_bytes"] = ks.get("host_bytes")
                    st["kv_promote_backlog"] = ks.get(
                        "promote_backlog_blocks", 0)
                # Chunked-prefill backlog (PR 9): prompt tokens of
                # the in-flight prefill not yet absorbed — the
                # dllm_prefill_backlog gauge's source series.
                st["prefill_backlog_tokens"] = ks.get(
                    "prefill_backlog_tokens", 0)
            except Exception:
                pass
        tick_fn = getattr(engine, "tick_stats", None)
        if callable(tick_fn):
            try:
                st["decode_tick_p50_ms"] = tick_fn().get("p50_ms")
            except Exception:
                pass
        # Batched speculation (ISSUE 15): the engine-lifetime acceptance
        # ratio — the dllm_spec_accept_ratio gauge's source series
        # (absent until the first draft so the gauge never fakes a 0).
        spec_fn = getattr(engine, "spec_stats", None)
        if callable(spec_fn):
            try:
                ss = spec_fn()
                if ss.get("enabled") and ss.get("accept_ratio") is not None:
                    st["spec_accept_ratio"] = ss["accept_ratio"]
            except Exception:
                pass
        # Tick-phase profiler (ISSUE 11): per-phase p50 self-times
        # over the ring's recent tail + the coverage fraction —
        # advisory ring reads, bounded to the last 128 records so
        # the sampler's <1 ms budget holds as rings grow.
        prof = getattr(engine, "profiler", None)
        if prof is not None and getattr(prof, "enabled", False):
            try:
                ps = prof.phase_stats(last=128)
                st["tick_phases"] = {
                    name: s.get("p50_ms")
                    for name, s in ps["phases"].items()}
                st["profile_coverage"] = ps.get("coverage")
            except Exception:
                pass
        return st

    def _session_label(self, raw: Any) -> str:
        """The bounded metric-label form of a client session id: '-'
        when absent, truncated to 64 chars, and capped at
        ``_session_label_cap`` DISTINCT values per router — later
        sessions aggregate under '~overflow' so a label-minting client
        cannot grow the metric registry without bound."""
        if not raw:
            return "-"
        s = str(raw)[:64]
        with self._cost_lock:
            if s in self._session_labels:
                return s
            if len(self._session_labels) < self._session_label_cap:
                self._session_labels.add(s)
                return s
        return "~overflow"

    def _note_cost(self, tier: str, strategy: str, session: str,
                   tenant: str, device_ms: float, kv_ticks: float) -> None:
        """Fold one finished request's attributed cost into the bounded
        ledger (oldest key evicted past the cap — dict insertion order
        is the age order; a re-charged key keeps its slot)."""
        key = (tier, strategy, session, tenant)
        with self._cost_lock:
            entry = self._cost_ledger.get(key)
            if entry is None:
                while len(self._cost_ledger) >= self._cost_ledger_cap:
                    self._cost_ledger.pop(
                        next(iter(self._cost_ledger)))
                entry = self._cost_ledger[key] = {
                    "device_time_ms": 0.0, "kv_block_ticks": 0.0,
                    "requests": 0}
            entry["device_time_ms"] += device_ms
            entry["kv_block_ticks"] += kv_ticks
            entry["requests"] += 1

    def autoscaler_snapshot(self) -> Optional[Dict[str, Any]]:
        """The GET /stats ``autoscaler`` block: per armed tier, the
        bounds/windows, live membership, streak state, event counters,
        and the bounded decision ledger.  None when no tier arms the
        autoscaler (static configs keep their historical /stats shape)."""
        if not getattr(self, "autoscalers", None):
            return None
        return {name: scaler.snapshot()
                for name, scaler in self.autoscalers.items()}

    def cost_snapshot(self) -> List[Dict[str, Any]]:
        """The GET /stats ``cost`` block: attributed device time and KV
        block-ticks per (tier, strategy, session, tenant), most
        expensive first."""
        with self._cost_lock:
            rows = [
                {"tier": k[0], "strategy": k[1], "session": k[2],
                 "tenant": k[3],
                 "device_time_ms": round(v["device_time_ms"], 3),
                 "kv_block_ticks": round(v["kv_block_ticks"], 3),
                 "requests": int(v["requests"])}
                for k, v in self._cost_ledger.items()]
        rows.sort(key=lambda r: r["device_time_ms"], reverse=True)
        return rows

    def profiler_trace(self) -> Dict[str, Any]:
        """The GET /debug/trace body: every live engine's tick-phase
        ring + compile/host-sync events rendered as one Chrome-trace/
        Perfetto JSON document (obs/profiler.chrome_trace).  Advisory
        ring snapshots — never the lifecycle lock; tiers without a
        profiler (remote, sequential, DLLM_PROFILE=0) contribute
        nothing."""
        from ..obs import profiler as obs_profiler
        by_tier: Dict[str, Dict[str, Any]] = {}
        for name, tier in self.tiers.items():
            mgr = tier.server_manager
            subs = getattr(mgr, "live_engines", None)
            if callable(subs):
                # Replicated tier: one synthetic trace thread PER
                # REPLICA ("nano/r0", "nano/r1", ...) so Perfetto shows
                # the replicas' tick timelines side by side.
                engines = [(f"{name}/{key}", eng) for key, eng in subs()]
            else:
                engines = [(name, getattr(mgr, "_engine", None))]
            for label, engine in engines:
                prof = getattr(engine, "profiler", None)
                if prof is not None and getattr(prof, "enabled", False):
                    try:
                        by_tier[label] = prof.snapshot()
                    except Exception:
                        pass
        return obs_profiler.chrome_trace(by_tier)

    def _obs_state_snapshot(self) -> Dict[str, Any]:
        """Cheap serving-state snapshot attached to flight-recorder
        entries: per-tier load counters + breaker states.  Deliberately
        NOT manager.health() — that takes the lifecycle lock, which a
        mid-compile engine can hold for minutes."""
        snap: Dict[str, Any] = {}
        try:
            tiers: Dict[str, Any] = {}
            for name, tier in self.tiers.items():
                fn = getattr(tier, "load_snapshot", None)
                if fn is not None:
                    tiers[name] = fn()
            snap["tiers"] = tiers
            if self.breaker is not None:
                snap["breaker"] = self.breaker.snapshot()
            snap["degraded_served"] = self.degraded_served
            # System TRAJECTORY, not just the point snapshot: the last
            # few seconds of the state timeline ride with every flight-
            # recorder entry (was the queue growing or draining when
            # this request failed?).
            timeline = self._timeline_tail(16)
            if timeline:
                snap["timeline"] = timeline
        except Exception:                 # snapshot must never kill a reply
            pass
        return snap

    def _finish_request(self, trace, which: Optional[str], ok: bool,
                        degraded: bool = False, raw: Any = None) -> None:
        """Close a request trace and derive its metrics + (for failed /
        degraded / slow requests) its flight-recorder entry.  Called
        exactly once per request, on every exit path of both pipelines."""
        trace.finish(ok=ok)
        m = self.obs.m
        strategy = trace.attrs.get("strategy") or "unknown"
        outcome = "degraded" if degraded else ("ok" if ok else "error")
        m.requests.labels(strategy, which or "none", outcome).inc()
        dur = trace.duration_ms
        if dur is not None:
            m.request_ms.labels(strategy).observe(dur)
        # Engine-true per-request timing rides in the raw dict (additive
        # keys, serving/tiers.py).  Cache hits skip the latency
        # histograms: a cached reply's raw carries the ORIGINAL
        # generation's timings, and its own TTFT is ~0 — both would
        # poison the engine-latency distributions.
        cache_hit = bool(trace.attrs.get("cache_hit"))
        ttft = tbt_p95 = None
        if not cache_hit:
            if isinstance(raw, dict):
                for key in ("ttft_ms", "total_ms", "gen_tokens"):
                    val = raw.get(key)
                    if val is not None:
                        trace.annotate(**{key: val})
            ttft = trace.ttft_ms()
            if ttft is not None:
                m.ttft_ms.labels(strategy).observe(ttft)
            tbt = trace.tbt_ms()
            if tbt is not None:
                m.tbt_ms.labels(strategy).observe(tbt)
            tbt_p95 = trace.tbt_p95_ms()
        qw = trace.attrs.get("queue_wait_ms")
        if qw is not None and which:
            m.queue_wait_ms.labels(which).observe(float(qw))
        # SLO goodput feed — the ONLY sanctioned record_request site
        # (obs_discipline lint): this exit runs exactly once per request
        # on every path of both pipelines, so goodput counts requests,
        # never attempts.  Degraded service is not goodput even when the
        # stale-cache reply carried ok=True.
        tenant_raw = trace.attrs.get("tenant") or DEFAULT_TENANT
        tenant = self.obs.tenant_labels.label(tenant_raw)
        self.slo.record_request(strategy, which, ok=ok and not degraded,
                                ttft_ms=ttft, tbt_p95_ms=tbt_p95,
                                cache_hit=cache_hit, tenant=tenant)
        # A completed (admitted) request is the falling edge of this
        # tenant's over-quota incident, if one is open; a tenant-quota
        # rejection is not completion.
        if not (isinstance(raw, dict)
                and "tenant '" in str(raw.get("error", ""))):
            self._tenant_incident_edge(tenant_raw, rejected=False)
        # Per-request cost attribution (ISSUE 11): the batched engine
        # charged decode device time + KV block-ticks onto the trace;
        # this exactly-once exit aggregates them per (tier, strategy,
        # session) — the metric families quotas (ROADMAP 4) and
        # goodput-per-replica-second economics (ROADMAP 5) bill
        # against, plus the bounded /stats cost ledger.
        dev_ms = getattr(trace, "device_time_ms", 0.0)
        kv_ticks = getattr(trace, "kv_block_ticks", 0.0)
        if dev_ms or kv_ticks:
            session = self._session_label(trace.attrs.get("session"))
            m.device_time.labels(which or "none", strategy,
                                 session).inc(dev_ms)
            m.kv_block_ticks.labels(which or "none", strategy,
                                    session).inc(kv_ticks)
            m.tenant_device_time.labels(which or "none", tenant).inc(dev_ms)
            m.tenant_kv_block_ticks.labels(which or "none",
                                           tenant).inc(kv_ticks)
            self._note_cost(which or "none", strategy, session, tenant,
                            dev_ms, kv_ticks)
            # Post-paid quota billing (ISSUE 17): debit the serving
            # tier's per-tenant token bucket with the MEASURED device
            # time — quotas enforce observed cost, not declared cost.
            # No-op when the tier runs quotas-off (tenants is None).
            tier_client = self.tiers.get(which) if which else None
            tq = getattr(tier_client, "tenants", None)
            if tq is not None:
                try:
                    tq.debit(tenant_raw, dev_ms)
                except Exception:
                    pass
        reason = self.obs.recorder.classify(ok, degraded, dur)
        if reason is not None:
            m.flight_records.labels(reason).inc()
            self.obs.recorder.record(reason, trace,
                                     self._obs_state_snapshot())

    # -- helpers -----------------------------------------------------------

    def _apply_prefix_affinity(self, device: str, confidence: float,
                               method: str, reasoning: str, history
                               ) -> Tuple[str, str, str]:
        """Steer a LOW-confidence decision to the tier already holding
        this conversation's parked KV prefix (cache-locality-aware
        routing — beyond the reference, production only).

        Probes are non-destructive (PrefixCache.peek through
        engine.prefix_affinity), touch only ALREADY-RUNNING local
        engines (never starts one, never crosses hosts), and only
        override when the other tier's match beats the chosen tier's by
        at least ``prefix_affinity_min_tokens`` — a confident routing
        decision or a trivial prefix never flips.

        UPGRADE-ONLY: affinity may steer toward a STRONGER tier (later
        in the cluster's declaration order — the reference's nano<orin
        topology), never downgrade.  Locality must not cost capability:
        a complex follow-up whose early small-talk parked the
        conversation on nano still belongs on orin (measured: the
        symmetric rule dragged orin-labeled queries to nano and cost
        the semantic/hybrid cache-on legs ~0.17 accuracy; the reference
        resolves every such tie toward orin too — threshold fallback,
        heavy-context override)."""
        if (not self.enable_prefix_affinity
                or confidence >= self.prefix_affinity_min_confidence):
            return device, method, reasoning
        order = [t.name for t in self.cluster.tiers()]
        scores: Dict[str, int] = {}
        for name, tier in self.tiers.items():
            if (name not in order or device not in order
                    or order.index(name) <= order.index(device)):
                continue                 # upgrade-only: skip weaker tiers
            probe = self._tier_affinity_probe(tier)
            if callable(probe):
                try:
                    scores[name] = int(probe(history))
                except Exception:
                    scores[name] = 0
        if not scores:
            return device, method, reasoning
        # The chosen tier's own match sets the bar the upgrade must beat.
        own_probe = self._tier_affinity_probe(self.tiers[device])
        own = 0
        if callable(own_probe):
            try:
                own = int(own_probe(history))
            except Exception:
                own = 0
        best = max(scores, key=scores.get)
        if (best != device
                and scores[best] >= own + self.prefix_affinity_min_tokens):
            reasoning = (f"prefix affinity: {best} holds a "
                         f"{scores[best]}-token parked prefix of this "
                         f"conversation (decision was {device} at "
                         f"confidence {confidence:.2f}); {reasoning}")
            self.prefix_affinity_overrides += 1
            self.obs.m.cache_hits.labels("prefix_affinity").inc()
            obs_spans.event(current_trace(), "prefix_affinity_override",
                            to=best, match_tokens=scores[best])
            return best, f"{method}+prefix_affinity", reasoning
        return device, method, reasoning

    @staticmethod
    def _tier_affinity_probe(tier):
        """The tier's prefix-affinity probe: the ReplicaSetManager's
        best-across-replicas view for replicated tiers (a tier holds a
        prefix if ANY replica does), else the single engine's — never
        starts an engine either way."""
        mgr = tier.server_manager
        probe = getattr(mgr, "prefix_affinity", None)
        if callable(probe):
            return probe
        engine = getattr(mgr, "_engine", None)
        return getattr(engine, "prefix_affinity", None)

    @staticmethod
    def _extract_text(response: Any) -> Optional[str]:
        """Normalize any tier response shape to a plain string
        (src/router.py:73-102)."""
        if response is None:
            return None
        if isinstance(response, str):
            return response.strip() or None
        if isinstance(response, dict):
            for key in ("response", "content", "message"):
                val = response.get(key)
                if isinstance(val, str) and val.strip():
                    return val.strip()
                if isinstance(val, dict):
                    inner = val.get("content")
                    if isinstance(inner, str) and inner.strip():
                        return inner.strip()
            if "error" in response:
                parts = [str(response.get(k, "")).strip()
                         for k in ("error", "detail", "body")]
                combined = " ".join(p for p in parts if p)
                return combined[:300] if combined else None
        return None

    def _history_to_query_and_context(
        self, history: List[Dict[str, Any]]
    ) -> Tuple[str, Optional[str], str]:
        """Split history into (last user query, prior-turn context string,
        sha256[:16] hash of the last-k turns) — src/router.py:104-147."""
        if not history:
            return "", None, "nohist"

        last_user = None
        for i in range(len(history) - 1, -1, -1):
            m = history[i]
            if isinstance(m, dict) and m.get("role") == "user":
                last_user = i
                break

        if last_user is None:
            query, ctx_msgs = "", history
        else:
            query = (history[last_user].get("content") or "").strip()
            ctx_msgs = history[:last_user]

        lines = [
            f"{(m.get('role') or '').strip()}: {(m.get('content') or '').strip()}"
            for m in ctx_msgs
            if isinstance(m, dict) and (m.get("content") or "").strip()
        ]
        context = "\n".join(lines) if lines else None

        compact = "\n".join(
            f"{m.get('role', '')}:{(m.get('content') or '').strip()}"
            for m in ctx_msgs[-self.cache_last_k:]
            if isinstance(m, dict))
        ctx_hash = hashlib.sha256(compact.encode("utf-8")).hexdigest()[:16]
        return query, context, ctx_hash

    @staticmethod
    def _is_error(raw: Any) -> bool:
        # Delegates to the single error-shape schema (serving/errors.py)
        # that the `error-shape` lint checker enforces on every literal.
        return is_error_shape(raw)

    @staticmethod
    def _is_transient_error(raw: Any) -> bool:
        """Error shapes worth one quick same-tier retry (connection races,
        engine shut down mid-flight) — see _TRANSIENT_MARKERS."""
        if not (isinstance(raw, dict) and "error" in raw):
            return False
        msg = str(raw.get("error", "")).lower()
        return any(m in msg for m in _TRANSIENT_MARKERS)

    @staticmethod
    def _other(device: str) -> str:
        return "orin" if device == "nano" else "nano"

    def _tier_timeout_s(self, device: str) -> Optional[float]:
        """The tier's per-request wall budget (TierConfig.request_timeout_s
        locally, the read timeout for a remote tier); None = unbounded."""
        tier = self.tiers.get(device)
        cfg = getattr(tier, "tier", None)
        if cfg is not None and cfg.request_timeout_s:
            return float(cfg.request_timeout_s)
        read_timeout = getattr(tier, "read_timeout", None)
        return float(read_timeout) if read_timeout else None

    @staticmethod
    def _is_admission_rejection(raw: Any) -> bool:
        return (isinstance(raw, dict)
                and "admission rejected" in str(raw.get("error", "")))

    def _note_admission_rejection(self, raw: Any, which: str) -> None:
        """Admission-rejection metrics: every rejection counts, and the
        KV-pressure subset gets its own counter (the signal the pressure
        chaos leg and dashboards key on).  Tenant-quota rejections
        (ISSUE 17; reason names the tenant) additionally feed the
        per-tenant shed counter and the over-quota incident edge."""
        if not self._is_admission_rejection(raw):
            return
        self.obs.m.admission_rejected.labels(which).inc()
        err = str(raw.get("error", ""))
        if "KV demand" in err:
            self.obs.m.kv_admission_rejected.labels(which).inc()
        if "tenant '" in err:
            trace = current_trace()
            tenant = (trace.attrs.get("tenant")
                      if trace is not None else None) or DEFAULT_TENANT
            self.obs.m.tenant_rejected.labels(
                which, self.obs.tenant_labels.label(tenant)).inc()
            self._tenant_incident_edge(tenant, rejected=True,
                                       which=which, reason=err)

    def _tenant_incident_edge(self, tenant: str, rejected: bool,
                              which: Optional[str] = None,
                              reason: str = "") -> None:
        """Over-quota incident lifecycle (ISSUE 17): a tenant's FIRST
        quota rejection opens a flight-recorder incident naming it
        (rising edge — post-mortem survives a crash mid-shed, same
        contract as the SLO overload incidents); subsequent rejections
        only bump its count; the tenant's next COMPLETED request
        finalizes it.  At most ``_session_label_cap`` distinct tenants
        tracked — past that, rejections still count in metrics but mint
        no new incidents."""
        if rejected:
            with self._cost_lock:
                st = self._tenant_incidents.get(tenant)
                if st is not None:
                    st["rejections"] += 1
                    return
                if len(self._tenant_incidents) >= self._session_label_cap:
                    return
                st = {"entry": None, "rejections": 1}
                self._tenant_incidents[tenant] = st
            info = {"tenant": tenant, "tier": which or "none",
                    "first_reason": (reason or "")[:200],
                    "start_unix": round(time.time(), 3), "open": True}
            try:
                st["entry"] = self.obs.recorder.record_incident(
                    "tenant_overquota", info)
                self.obs.m.flight_records.labels("tenant_overquota").inc()
            except Exception:
                pass
            return
        with self._cost_lock:
            st = self._tenant_incidents.pop(tenant, None)
        if st is None:
            return
        entry = st.get("entry")
        if entry is not None:
            try:
                self.obs.recorder.update_incident(
                    entry, open=False, end_unix=round(time.time(), 3),
                    rejections_while_open=int(st["rejections"]))
            except Exception:
                pass

    # -- context-overflow policy (serving edge) ----------------------------

    def _apply_overflow_policy(self, device: str,
                               history: List[Dict[str, Any]]
                               ) -> Tuple[List[Dict[str, Any]],
                                          Optional[Dict[str, Any]], int]:
        """Per-tier policy for prompts exceeding ``max_seq_len -
        max_new_tokens`` (estimated with the router's token counter):
        ``reject`` fails fast with the reference error shape naming the
        policy; ``truncate_left`` (default) drops oldest turns until the
        estimate fits — the engine would silently keep the tail anyway
        (prepare_prompt), so this makes the choice explicit serving
        policy and surfaces it in the response.  The final (newest)
        message always survives.  Returns (history, error_raw | None,
        dropped_messages)."""
        tier = self.tiers.get(device)
        cfg = getattr(tier, "tier", None)
        if cfg is None or not isinstance(history, list):
            return history, None, 0
        try:
            limit = max(1, cfg.model().max_seq_len - cfg.max_new_tokens)
        except Exception:
            return history, None, 0
        est = self.token_counter.get_context_size(history)
        if est <= limit:
            return history, None, 0
        policy = getattr(cfg, "overflow_policy", "truncate_left")
        if policy == "reject":
            self.obs.m.overflow.labels(device, "rejected").inc()
            obs_spans.event(current_trace(), "overflow_rejected",
                            tier=device, est_tokens=est, limit=limit)
            logger.warning("%s: prompt ~%d tokens over the %d-token "
                           "context budget — overflow_policy=reject",
                           device, est, limit)
            return history, {"error": (
                f"Request failed: prompt of ~{est} tokens exceeds "
                f"{device}'s context budget of {limit} tokens "
                f"(max_seq_len - decode budget; "
                f"overflow_policy=reject)")}, 0
        trimmed = list(history)
        dropped = 0
        while len(trimmed) > 1 and est > limit:
            dropped += 1
            est -= self.token_counter.count_tokens(trimmed.pop(0))
        self.obs.m.overflow.labels(device, "truncated").inc()
        obs_spans.event(current_trace(), "overflow_truncated",
                        tier=device, dropped_messages=dropped,
                        est_tokens=est, limit=limit)
        logger.info("%s: dropped %d oldest turn(s) to fit the %d-token "
                    "context budget (overflow_policy=truncate_left)",
                    device, dropped, limit)
        return trimmed, None, dropped

    def _breaker_record(self, device: str, ok: bool,
                        raw: Any = None) -> None:
        """Feed a dispatch outcome to the breaker.  Admission rejections
        are NEITHER success nor failure: they are healthy backpressure
        (the queue-aware perf penalty's job), and counting them would
        open the circuit on a tier that is merely at capacity — a burst
        could then cascade both tiers into degraded fail-fast while both
        engines are healthy and draining."""
        if self.breaker is None:
            return
        if not ok and self._is_admission_rejection(raw):
            # Still repay a half-open canary permit: the rejection proves
            # the engine is up and draining — holding the permit would
            # shed the tier for another whole cooldown.
            self.breaker.release_probe(device)
            return
        self.breaker.record(device, ok)

    def _breaker_record_stream_setup(self, device: str, handle: Any) -> None:
        """Breaker feedback for a stream SETUP result: only FAILURES
        (error dicts, minus admission rejections) count here.  A
        successful setup proves one primed token, nothing more — a tier
        that wedges MID-decode (the round-5 mode) passes setup every
        time, and recording that as success would reset the failure
        streak each request and keep the circuit closed forever on a
        streaming-only workload.  ALL success verdicts come from stream
        completion (``on_done``)."""
        if self.breaker is None:
            return
        if self._is_error(handle):
            self._breaker_record(device, False, handle)

    def _run_device(self, device: str,
                    history: List[Dict[str, Any]]) -> Tuple[Any, str, float]:
        tier = self.tiers.get(device, self.nano)
        logger.info("Processing query on %s", tier.name)
        t0 = time.perf_counter()
        with obs_spans.span(current_trace(), "dispatch", tier=tier.name):
            raw = tier.process(history)
        self._note_admission_rejection(raw, tier.name)
        return raw, tier.name, (time.perf_counter() - t0) * 1000.0

    def _run_device_retrying(self, device: str, history: List[Dict[str, Any]],
                             deadline: Optional[float] = None
                             ) -> Tuple[Any, str, float]:
        """``_run_device`` plus bounded retry with jittered exponential
        backoff for TRANSIENT error shapes.  ``deadline`` (monotonic) is
        the retry layer's wall budget — the dispatching tier's
        request_timeout_s from dispatch start: no retry STARTS past it
        (a timed-out call has no retry budget left by construction).
        Each attempt is still individually capped by the tier's own
        timeout, so the theoretical worst case is budget + one per-call
        cap — reachable only by a transient failure surfacing at the
        budget's edge; in practice the retried shapes (connection
        refused/reset) fail in milliseconds."""
        raw, which, lat_ms = self._run_device(device, history)
        for attempt in range(self.retry_attempts):
            if not self._is_transient_error(raw):
                break
            backoff = (self.retry_backoff_s * (2 ** attempt)
                       * (0.5 + random.random()))
            if (deadline is not None
                    and time.monotonic() + backoff >= deadline):
                logger.warning("%s transient error but no retry budget "
                               "left — giving up the retry", which)
                break
            logger.warning("%s transient error (%.80s) — retry %d/%d after "
                           "%.0fms", which, raw.get("error", ""),
                           attempt + 1, self.retry_attempts, backoff * 1000)
            self.obs.m.retries.labels(which).inc()
            obs_spans.event(current_trace(), "retry", tier=which,
                            attempt=attempt + 1)
            time.sleep(backoff)
            raw2, _, lat2 = self._run_device(device, history)
            lat_ms += lat2
            raw = raw2
        return raw, which, lat_ms

    # -- response cache (src/router.py:179-193) ----------------------------

    def _response_cache_key(self, ctx_hash: str, query: str) -> str:
        # Deliberately context-independent (reference intent, router.py:57-59)
        return f"{self.query_router.strategy}|{query.lower().strip()}"

    def _degraded_response(self, query: str, ctx_hash: str, method: str,
                           confidence: float, overhead_ms: float,
                           device: str) -> Tuple[Dict[str, Any], int, str]:
        """Both tiers' circuits are open: serve a response-cache hit if
        one exists (stale beats dead), else fail FAST with the reference
        error shape plus a retry-after hint — never dispatch into a
        known-dead cluster and burn a serving thread on a timeout."""
        cached = self._response_store.get(
            self._response_cache_key(ctx_hash, query))
        # Skip error-shaped entries: the store keeps every reply
        # (reference behavior), and re-serving a cached ERROR as an
        # ok=True "degraded hit" would report a failure as an answer.
        if cached is not None and self._is_error(cached.get("raw")):
            cached = None
        if cached is not None:
            text = cached.get("text", "")
            which = cached.get("device", device)
            tokens = self.token_counter.count_tokens(
                {"role": "assistant", "content": text})
            self.degraded_served += 1
            self.obs.m.degraded.inc()
            self.obs.m.cache_hits.labels("response_degraded").inc()
            obs_spans.annotate(current_trace(), degraded=True,
                               cache_hit="response_degraded")
            return {
                "response": text,
                "raw": cached.get("raw"),
                "cache_hit": True,
                "degraded": True,
                "routing_method": "response_cache_degraded",
                "routing_confidence": 1.0,
                "routing_reasoning": ("all tiers' circuits open -> stale "
                                      f"response-cache hit ({which})"),
                "routing_overhead_ms": round(overhead_ms, 2),
                "ok": True,
            }, tokens, which
        retry_after = (self.breaker.retry_after_s()
                       if self.breaker is not None else 0.0)
        raw = {"error": ("Request failed: all tiers unavailable (circuit "
                         f"open); retry in {retry_after:.1f}s")}
        text = self._extract_text(raw) or "No response available"
        tokens = self.token_counter.count_tokens(
            {"role": "assistant", "content": text})
        self.degraded_served += 1
        self.obs.m.degraded.inc()
        obs_spans.event(current_trace(), "degraded_fail_fast",
                        retry_after_s=round(retry_after, 2))
        obs_spans.annotate(current_trace(), degraded=True)
        logger.warning("degraded fail-fast: all circuits open "
                       "(retry_after=%.1fs)", retry_after)
        return {
            "response": text,
            "raw": raw,
            "cache_hit": False,
            "degraded": True,
            "retry_after_s": round(retry_after, 2),
            "benchmark_mode": self.benchmark_mode,
            "routing_method": f"{method}+breaker_degraded",
            "routing_confidence": round(confidence, 4),
            "routing_reasoning": ("all tiers' circuits open; shedding "
                                  "without dispatch"),
            "routing_overhead_ms": round(overhead_ms, 2),
            "ok": False,
        }, tokens, device

    # -- main pipeline -----------------------------------------------------

    def _feed_perf_load(self) -> None:
        """Queue-aware routing input: push each tier's live load
        (admission queue depth + batch slot occupancy) into the active
        strategy before it decides.  Cheap in-memory counters; skipped
        entirely unless the strategy consumes them (perf only)."""
        if (self.breaker is not None
                and hasattr(getattr(self.query_router, "router", None),
                            "update_breaker")):
            # Breaker state reaches the strategies too (perf scores an
            # OPEN tier a whole fail_penalty), so shedding starts at the
            # DECISION, before the Router's dispatch-time veto.  Gated on
            # the ACTIVE strategy consuming it — same pattern as
            # wants_load: no per-request breaker lock/snapshot for the
            # strategies that ignore the feed.
            for name, st in self.breaker.snapshot().items():
                try:
                    self.query_router.update_breaker(
                        name, st["state"] == "open")
                except Exception:
                    pass
        if not getattr(self.query_router, "wants_load", False):
            return
        for name, tier in self.tiers.items():
            snap_fn = getattr(tier, "load_snapshot", None)
            if snap_fn is None:
                continue                     # remote tiers: no local load
            try:
                self.query_router.update_load(name, **snap_fn())
            except Exception:
                pass

    def _decide(self, query: str, context: str, ctx_hash: str,
                history: List[Dict[str, Any]]):
        """The routing-decision stage shared by the sync and streaming
        pipelines: QueryRouter decision with the reference's ctx-size
        fallback on engine failure (src/router.py:258-270).  Returns
        (device, method, confidence, reasoning, cache_hit, overhead_ms)."""
        t0 = time.perf_counter()
        with obs_spans.span(current_trace(), "route") as route_sp:
            self._feed_perf_load()
            device = "nano"
            method, confidence, reasoning = "unknown", 0.0, ""
            cache_hit = False
            try:
                decision = self.query_router.route_query(
                    query=query, context=context, context_key=ctx_hash)
                device = decision.device
                method = decision.method
                confidence = float(decision.confidence)
                reasoning = decision.reasoning
                cache_hit = bool(decision.cache_hit)
                logger.info("[%s] routing: %s | method=%s conf=%.3f",
                            "BENCH" if self.benchmark_mode else "PROD",
                            device.upper(), method, confidence)
            except Exception as exc:
                ctx_size = self.token_counter.get_context_size(history)
                device = ("orin" if ctx_size > self.threshold_fallback
                          else "nano")
                method = "fallback_ctx_size"
                confidence = 0.2
                reasoning = (f"router failed: {exc}; ctx_size={ctx_size}, "
                             f"threshold_fallback={self.threshold_fallback}")
                logger.warning("routing failed (%s); ctx fallback -> %s",
                               exc, device)
            route_sp.annotate(device=device, method=method,
                              confidence=round(confidence, 4))
            if cache_hit:
                self.obs.m.cache_hits.labels("routing").inc()
        overhead_ms = (time.perf_counter() - t0) * 1000.0
        return device, method, confidence, reasoning, cache_hit, overhead_ms

    def route_query(self, history: List[Dict[str, Any]],
                    session_id: Optional[str] = None,
                    tenant_id: Optional[str] = None
                    ) -> Tuple[Dict[str, Any], int, str]:
        """Instrumented entry: creates the request's span tree (obs/),
        binds it for this thread (tiers/engines pick it up via
        ``current_trace``), runs the pipeline, then derives the
        request's metrics and — when failed/degraded/slow — its flight-
        recorder entry.  The pipeline itself is ``_route_query_inner``;
        the reference contract (return shape, error semantics) is
        untouched.  ``session_id`` (optional, additive — the serving
        edge passes its /chat session) keys the per-session cost
        attribution; None aggregates under '-'.  ``tenant_id``
        (ISSUE 17; validated at the serving edge) rides the trace into
        the tier quota layer and keys per-tenant billing; None bills to
        the shared default tenant."""
        self._ensure_sampler()
        trace = self.obs.trace(strategy=self.query_router.strategy)
        if session_id:
            trace.annotate(session=str(session_id))
        if tenant_id:
            trace.annotate(tenant=str(tenant_id))
        with use_trace(trace):
            try:
                response, tokens, which = self._route_query_inner(
                    trace, history)
            except BaseException as exc:
                trace.annotate(error=f"{type(exc).__name__}: {exc}"[:200])
                self._finish_request(trace, None, ok=False)
                raise
        self._finish_request(trace, which,
                             ok=bool(response.get("ok", True)),
                             degraded=bool(response.get("degraded")),
                             raw=response.get("raw"))
        return response, tokens, which

    def _route_query_inner(self, trace, history: List[Dict[str, Any]]
                           ) -> Tuple[Dict[str, Any], int, str]:
        query, context, ctx_hash = self._history_to_query_and_context(history)

        # 0) response cache
        if self.enable_response_cache:
            with trace.span("cache_lookup"):
                cached = self._response_store.get(
                    self._response_cache_key(ctx_hash, query))
            if cached is not None:
                text = cached.get("text", "")
                which = cached.get("device", "nano")
                tokens = self.token_counter.count_tokens(
                    {"role": "assistant", "content": text})
                self.obs.m.cache_hits.labels("response").inc()
                trace.annotate(cache_hit="response")
                return {
                    "response": text,
                    "raw": cached.get("raw"),
                    "cache_hit": True,
                    "routing_method": "response_cache",
                    "routing_confidence": 1.0,
                    "routing_reasoning": f"response cache hit -> {which}",
                    "routing_overhead_ms": 0.0,
                    "ok": True,
                }, tokens, which

        # 1) routing decision
        (device, method, confidence, reasoning,
         cache_hit, overhead_ms) = self._decide(query, context, ctx_hash,
                                                history)
        device, method, reasoning = self._apply_prefix_affinity(
            device, confidence, method, reasoning, history)

        # 1.6) circuit-breaker veto: an OPEN tier sheds traffic BEFORE
        # dispatch (before its admission queue even sees the request);
        # both tiers open → the degraded path (cache hit or fast fail
        # with a retry-after hint) instead of a doomed dispatch.
        if self.breaker is not None and not self.breaker.allow(device):
            other = self._other(device)
            if device in self.tiers and self.breaker.allow(other):
                reasoning = (f"circuit open on {device} -> rerouted to "
                             f"{other}; {reasoning}")
                method = f"{method}+breaker"
                trace.event("breaker_veto", vetoed=device, to=other)
                device = other
            else:
                return self._degraded_response(query, ctx_hash, method,
                                               confidence, overhead_ms,
                                               device)

        # 1.8) context-overflow policy for the dispatching tier: an over-
        # budget prompt either fails fast here (policy "reject") or loses
        # its oldest turns ("truncate_left"), with the choice surfaced.
        history, overflow_err, overflow_dropped = \
            self._apply_overflow_policy(device, history)
        if overflow_err is not None:
            text = self._extract_text(overflow_err) or "No response available"
            tokens = self.token_counter.count_tokens(
                {"role": "assistant", "content": text})
            return {
                "response": text,
                "raw": overflow_err,
                "cache_hit": False,
                "benchmark_mode": self.benchmark_mode,
                "routing_overhead_ms": round(overhead_ms, 2),
                "routing_method": f"{method}+overflow_reject",
                "routing_confidence": round(confidence, 4),
                "routing_reasoning": (f"prompt exceeds {device}'s context "
                                      f"budget (overflow_policy=reject); "
                                      f"{reasoning}"),
                "ok": False,
            }, tokens, device

        # 2) inference + bounded transient retry + failover.  The retry
        # layer is budgeted against the primary tier's request_timeout_s
        # from dispatch start (retries never extend the reference cap).
        timeout_s = self._tier_timeout_s(device)
        deadline = (time.monotonic() + timeout_s
                    if timeout_s is not None else None)
        raw, which, lat_ms = self._run_device_retrying(device, history,
                                                       deadline)
        self._breaker_record(which, not self._is_error(raw), raw)
        if self.enable_failover and self._is_error(raw):
            other = self._other(which)
            # Record the PRIMARY's failure before switching: the
            # reference feeds perf only for the device that ultimately
            # served (router.py:292-295), so failover masked every
            # failure from the perf strategy — yet its fail_penalty
            # exists precisely to steer traffic off flaky devices.
            # Divergence documented in PARITY.md; especially load-bearing
            # for request timeouts (a wedged tier must lose traffic).
            try:
                self.query_router.update_perf(which, lat_ms, 0, ok=False)
            except Exception:
                pass
            # Failover keeps the reference's one-shot semantics — it
            # fires even after a full wall timeout (a wedged tier's
            # request MUST still reach the survivor; that is the round-5
            # scenario this layer exists for).  The deadline bounds only
            # the RETRY layer: the failover attempt runs retry-free when
            # the budget is spent.  Repeated timeout+failover cost is the
            # BREAKER's job — after breaker_failures of these, the wedged
            # tier sheds pre-dispatch and nobody pays the timeout again.
            # Only an open circuit on the survivor suppresses failover.
            if self.breaker is None or self.breaker.allow(other):
                logger.warning("%s failed — failing over to %s", which, other)
                self.obs.m.failovers.labels(which, "sync").inc()
                trace.event("failover", failed=which, to=other)
                raw2, which2, lat2 = self._run_device_retrying(
                    other, history, deadline)
                self._breaker_record(which2, not self._is_error(raw2), raw2)
                if not self._is_error(raw2):
                    raw, which, lat_ms = raw2, which2, lat2
            else:
                logger.warning("%s failed and %s's circuit is open — "
                               "no failover target", which, other)

        # 3) normalize + count
        text = self._extract_text(raw) or "No response available"
        tokens = self.token_counter.count_tokens(
            {"role": "assistant", "content": text})
        ok = not self._is_error(raw)

        # 4) perf feedback
        try:
            self.query_router.update_perf(which, lat_ms, tokens, ok=ok)
        except Exception:
            pass

        # 5) response-cache store
        if self.enable_response_cache:
            self._response_store[self._response_cache_key(ctx_hash, query)] = {
                "text": text,
                "raw": raw,
                "device": which,
                "routing_confidence": round(confidence, 4),
            }

        out = {
            "response": text,
            "raw": raw,
            "cache_hit": False,
            "benchmark_mode": self.benchmark_mode,
            "routing_overhead_ms": round(overhead_ms, 2),
            "routing_method": method,
            "routing_confidence": round(confidence, 4),
            "routing_reasoning": reasoning,
            "ok": ok,
        }
        if overflow_dropped:
            # Surface the truncate_left choice (additive keys, like the
            # per-request timing fields).
            out["overflow_truncated"] = True
            out["overflow_dropped_messages"] = overflow_dropped
        return out, tokens, which

    def route_query_stream(self, history: List[Dict[str, Any]],
                           session_id: Optional[str] = None,
                           tenant_id: Optional[str] = None
                           ) -> "RoutedStream":
        """Streaming twin of ``route_query``: same decision stage
        (``_decide`` incl. the ctx-size fallback), the same circuit-
        breaker veto, one-shot tier failover at stream SETUP, plus
        MID-STREAM failover — a stream whose decode loop dies after the
        first token is re-issued on the surviving tier with the already-
        emitted prefix replayed silently (RoutedStream) — and the same
        perf feedback, fired when the stream completes.  The response
        cache does not participate: a streamed reply is consumed as it
        is produced.  Raises RuntimeError if no tier can start a stream
        (message carries a retry-after hint when every circuit is
        open)."""
        self._ensure_sampler()
        trace = self.obs.trace(strategy=self.query_router.strategy,
                               stream=True)
        if session_id:
            trace.annotate(session=str(session_id))
        if tenant_id:
            trace.annotate(tenant=str(tenant_id))
        with use_trace(trace):
            try:
                return self._route_stream_inner(trace, history)
            except BaseException as exc:
                trace.annotate(error=f"{type(exc).__name__}: {exc}"[:200])
                self._finish_request(trace, None, ok=False,
                                     degraded=bool(
                                         trace.attrs.get("degraded")))
                raise

    def _route_stream_inner(self, trace,
                            history: List[Dict[str, Any]]) -> "RoutedStream":
        query, context, ctx_hash = self._history_to_query_and_context(history)
        (device, method, confidence, reasoning,
         cache_hit, overhead_ms) = self._decide(query, context, ctx_hash,
                                                history)
        device, method, reasoning = self._apply_prefix_affinity(
            device, confidence, method, reasoning, history)

        # Circuit-breaker veto, mirroring the sync path: shed an open
        # tier pre-dispatch; both open → fail fast with a retry hint.
        if self.breaker is not None and not self.breaker.allow(device):
            other = self._other(device)
            if self.breaker.allow(other):
                reasoning = (f"circuit open on {device} -> rerouted to "
                             f"{other}; {reasoning}")
                method = f"{method}+breaker"
                trace.event("breaker_veto", vetoed=device, to=other)
                device = other
            else:
                self.degraded_served += 1
                self.obs.m.degraded.inc()
                trace.annotate(degraded=True)
                raise RuntimeError(
                    "Request failed: all tiers unavailable (circuit "
                    f"open); retry in {self.breaker.retry_after_s():.1f}s")

        # Context-overflow policy, mirroring the sync path: reject raises
        # (the SSE layer splices the error tail), truncate_left trims and
        # flags the meta.
        history, overflow_err, overflow_dropped = \
            self._apply_overflow_policy(device, history)
        if overflow_err is not None:
            raise RuntimeError(overflow_err["error"])

        t0 = time.perf_counter()
        tier = self.tiers.get(device, self.nano)
        # Stream setup primes the first token (prefill runs inside), so
        # this span IS the stream's TTFT-critical section.
        with trace.span("stream_setup", tier=tier.name):
            handle = tier.process_stream(history)
        which = tier.name
        self._note_admission_rejection(handle, which)
        self._breaker_record_stream_setup(which, handle)
        if self._is_error(handle) and self.enable_failover:
            other = self._other(which)
            logger.warning("%s stream setup failed — failing over to %s",
                           which, other)
            # Same as the sync path: the primary's failure must reach
            # the perf strategy even though failover will serve.
            try:
                self.query_router.update_perf(
                    which, (time.perf_counter() - t0) * 1000.0, 0, ok=False)
            except Exception:
                pass
            if self.breaker is None or self.breaker.allow(other):
                self.obs.m.failovers.labels(which, "stream_setup").inc()
                trace.event("failover", failed=which, to=other,
                            kind="stream_setup")
                with trace.span("stream_setup", tier=other):
                    alt = self.tiers[other].process_stream(history)
                self._note_admission_rejection(alt, other)
                self._breaker_record_stream_setup(other, alt)
                if not self._is_error(alt):
                    handle, which = alt, other
        if self._is_error(handle):
            raise RuntimeError(handle.get("error", "stream setup failed"))

        # Shared mutable view of the live (handle, device): mid-stream
        # failover swaps both, and the completion callback must attribute
        # the final result to the tier that ACTUALLY finished the stream.
        state: Dict[str, Any] = {"handle": handle, "device": which}

        def on_done(ok: bool) -> None:
            # The stream's COMPLETION is the breaker's verdict for the
            # serving tier (setup only primes one token — see
            # _breaker_record_stream_setup): a half-open canary closes
            # the circuit only by finishing its stream.
            self._breaker_record(state["device"], ok)
            result = getattr(state["handle"], "result", None)
            # Engine-true generation time, NOT wall time to exhaustion: a
            # slow SSE consumer would otherwise poison the perf strategy's
            # latency window for a healthy tier.
            if result is not None and result.total_ms > 0:
                lat_ms = result.total_ms
            else:
                lat_ms = (time.perf_counter() - t0) * 1000.0
            tokens = result.gen_tokens if result else 0
            try:
                self.query_router.update_perf(state["device"], lat_ms,
                                              tokens, ok=ok)
            except Exception:
                pass
            # Trace completion: engine-true timings preferred (token-
            # timeline stamps are the fallback for engines that report
            # no GenerationResult).  Fires exactly once via _fire.
            if result is not None:
                trace.annotate(ttft_ms=result.ttft_ms,
                               total_ms=result.total_ms,
                               gen_tokens=result.gen_tokens)
            self._finish_request(trace, state["device"], ok=ok)

        def resume_mid_stream(emitted_chars: int, exc: BaseException):
            """Mid-stream failover: the live stream died after emitting
            ``emitted_chars`` chars.  Re-issue the SAME request on the
            surviving tier and return an iterator that silently replays
            (skips) the already-delivered prefix, or None when no tier
            can take over (the caller then surfaces the original
            failure).  The replacement tier re-generates from scratch —
            its first ``emitted_chars`` chars are dropped, so the client
            sees one seamless stream (prefix replay; the spliced suffix
            may of course diverge in wording from what the dead tier
            WOULD have said — it is a different model)."""
            if not self.enable_failover:
                return None
            dying = state["device"]
            other = self._other(dying)
            logger.warning("%s stream died mid-decode after %d chars (%s) "
                           "— re-issuing on %s", dying, emitted_chars, exc,
                           other)
            # On every None return below, on_done(False) fires for the
            # still-current state["device"] (the dying tier) — so the
            # dying tier's breaker/perf failure is recorded HERE only on
            # the success path, where on_done will credit the SURVIVOR
            # instead.  Recording in both places would double-count one
            # stream death and trip the breaker at half its threshold.
            if self.breaker is not None and not self.breaker.allow(other):
                return None
            # Counted at the ATTEMPT, like the sync and stream_setup
            # kinds — a takeover whose survivor also fails must not be
            # invisible in the failover rate.
            self.obs.m.failovers.labels(dying, "mid_stream").inc()
            trace.event("mid_stream_failover", failed=dying, to=other,
                        replayed_chars=emitted_chars)
            # The resume hook runs on the CONSUMER's thread (SSE drain),
            # outside the request's original context — re-bind the trace
            # so the replacement setup's spans land in the same tree.
            with use_trace(trace), \
                    trace.span("stream_setup", tier=other, resume=True):
                alt = self.tiers[other].process_stream(history)
            self._breaker_record_stream_setup(other, alt)
            if self._is_error(alt):
                logger.warning("mid-stream failover target %s also failed "
                               "(%s)", other, alt.get("error"))
                return None
            self._breaker_record(dying, False)
            try:
                self.query_router.update_perf(
                    dying, (time.perf_counter() - t0) * 1000.0, 0, ok=False)
            except Exception:
                pass
            state["handle"], state["device"] = alt, other

            def replayed():
                skip = emitted_chars
                for delta in alt:
                    if skip > 0:
                        if len(delta) <= skip:
                            skip -= len(delta)
                            continue
                        delta = delta[skip:]
                        skip = 0
                    yield delta

            return replayed()

        meta = {
            "device": which,
            "method": method,
            "confidence": round(confidence, 4),
            "reasoning": reasoning,
            # Same meaning as /chat's cache_hit (response cache) — streams
            # never serve from it, so always False; the routing-decision
            # cache hit is its own field (it also shows as "*_cached" in
            # method, matching the sync path's convention).
            "cache_hit": False,
            "routing_cache_hit": cache_hit,
            "routing_overhead_ms": round(overhead_ms, 2),
        }
        if overflow_dropped:
            meta["overflow_truncated"] = True
            meta["overflow_dropped_messages"] = overflow_dropped
        return RoutedStream(state, meta, on_done,
                            resume=resume_mid_stream)


class RoutedStream:
    """A routed token stream: iterate for text deltas; ``.result`` holds
    the GenerationResult once exhausted.  Fires the router's perf-feedback
    callback exactly once, whether the stream completes, errors, or is
    abandoned mid-iteration (client disconnect).

    ``resume`` is the Router's mid-stream failover hook: when the LIVE
    stream raises between deltas (decode-loop death after the first
    token — setup-time failover can no longer help), it is called once
    with the number of chars already delivered; a non-None return is an
    iterator over the surviving tier's stream with that prefix already
    skipped (prefix replay), and iteration continues seamlessly.  A None
    return (failover disabled, no surviving tier, its circuit open)
    surfaces the original failure — the SSE layer splices the
    error-shaped tail event."""

    def __init__(self, state: Dict[str, Any], meta: Dict[str, Any],
                 on_done, resume=None):
        self._state = state
        self.meta = meta
        self._on_done = on_done
        self._resume = resume
        self._resumed = False
        self._fired = False

    @property
    def device(self) -> str:
        """The tier currently (or finally) serving this stream — updated
        if mid-stream failover switched tiers."""
        return self._state["device"]

    def _fire(self, ok: bool) -> None:
        if not self._fired:
            self._fired = True
            self._on_done(ok)

    def __iter__(self):
        emitted_chars = 0
        it = iter(self._state["handle"])
        while True:
            try:
                delta = next(it)
            except StopIteration:
                break
            except BaseException as exc:   # producer (engine/stream) death
                if self._resume is not None and not self._resumed:
                    self._resumed = True   # one-shot, like setup failover
                    alt = None
                    try:
                        alt = self._resume(emitted_chars, exc)
                    except Exception:
                        logger.exception("mid-stream failover hook failed")
                    if alt is not None:
                        it = alt
                        continue
                self._fire(False)
                raise
            try:
                yield delta
            except GeneratorExit:
                # Consumer abandoned the stream (client disconnect) — the
                # TIER was healthy as far as it was consumed; an ok=False
                # sample here would let disconnecting clients poison the
                # perf strategy against a healthy tier.
                self._fire(True)
                raise
            emitted_chars += len(delta)
        self._fire(True)

    @property
    def result(self):
        return self._state["handle"].result
