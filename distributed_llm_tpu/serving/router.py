"""Router — the serving orchestration pipeline.

Reference parity: src/router.py.  Same constructor signature, same
``route_query(history) -> (response_dict, tokens, device)`` contract, same
response-dict keys, and the same pipeline stages:

  0) response-cache check (production mode only; key = strategy + query text,
     deliberately context-independent — reference: src/router.py:57-59,179)
  1) routing decision via QueryRouter, with context-size threshold fallback
     if the routing engine raises (src/router.py:258-270)
  2) tier inference + one-shot failover to the other tier on an error-shaped
     response (src/router.py:277-282)
  3) text normalization + token count
  4) perf feedback into the perf strategy (src/router.py:292-295)
  5) response-cache store

What changed underneath: tiers are in-process TPU engines on chip submeshes
(serving/tiers.py) instead of SSH-tunneled Jetson boards, so `_run_device`
is a function call, not an HTTP POST.
"""

from __future__ import annotations

import hashlib
import logging
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax

from ..config import (ClusterConfig, bench_cluster, resolve_config,
                      tiny_cluster)
from ..routing.engine import QueryRouter
from ..routing.token_counter import TokenCounter
from ..utils.faults import FaultInjector
from .tiers import TierClient, build_tiers

logger = logging.getLogger(__name__)


def default_cluster(cpu_bench: bool = False) -> ClusterConfig:
    """Bench-sized tiers on an accelerator.  On host CPU: the tiny test
    tiers — unless ``cpu_bench`` is set (the headline bench opts in),
    where the quality-asymmetric cpu_bench pair (mini_bench under
    nano_bench-as-orin, config.cpu_bench_cluster) serves when both
    presets have published checkpoints, so the chipless headline runs
    genuinely trained, premise-consistent tiers (VERDICT r4 #2).  The
    opt-in is an explicit parameter, not ambient state: the ~26M/130M
    pair would make the unit suite's hundreds of default Routers
    unusably slow on one core.  Either way the tiers serve published
    pretrained weights when ``checkpoints/<preset>`` exists
    (training/pretrain.py)."""
    from ..config import (cpu_bench_cluster, default_checkpoint,
                          tiny_batched_cluster, with_default_checkpoints)
    if jax.default_backend() != "cpu":
        return with_default_checkpoints(bench_cluster())
    if cpu_bench:
        cpu_pair = cpu_bench_cluster()
        if all(default_checkpoint(t.model_preset)
               for t in cpu_pair.tiers()):
            return with_default_checkpoints(cpu_pair)
    # Concurrent-by-default even on the tiny CPU fallback: serving entry
    # points and the chipless bench get batched tiers (the unit suite
    # builds tiny_cluster() directly and keeps the cheaper sequential
    # warmup).
    return with_default_checkpoints(tiny_batched_cluster())


class Router:
    def __init__(
        self,
        strategy: str = "hybrid",
        config: Optional[Dict[str, Any]] = None,
        threshold_fallback: int = 100,
        benchmark_mode: bool = False,
        cluster: Optional[ClusterConfig] = None,
        devices: Optional[Sequence[jax.Device]] = None,
        fault_injector: Optional[FaultInjector] = None,
    ):
        """strategy: "token" | "semantic" | "heuristic" | "hybrid" | "perf"
        benchmark_mode: True → BENCHMARK_CFG (cache off), False →
        PRODUCTION_CFG, unless ``config`` overrides (src/router.py:37-40)."""
        self.token_counter = TokenCounter()
        self.threshold_fallback = threshold_fallback
        self.benchmark_mode = benchmark_mode
        self.config = resolve_config(config, benchmark_mode)

        self.cluster = cluster or default_cluster()
        self.faults = fault_injector
        self.tiers: Dict[str, TierClient] = build_tiers(
            self.cluster, devices=devices, fault_injector=fault_injector)
        # Reference attribute surface (tester uses router.nano.server_manager)
        self.nano = self.tiers["nano"]
        self.orin = self.tiers["orin"]

        self.query_router = QueryRouter(strategy=strategy, config=self.config)

        self.enable_response_cache = (
            not benchmark_mode
            and bool(self.config.get("enable_response_cache", False)))
        self.cache_last_k = int(self.config.get("cache_last_k", 6))
        self.enable_failover = bool(self.config.get("enable_failover", True))
        # Prefix-affinity routing (production only, beyond-reference): a
        # low-confidence decision is steered to the tier that already
        # holds this conversation's parked KV prefix — a cold re-prefill
        # elsewhere throws away an O(history) cache the engines worked
        # to keep.  Labeled-accuracy benchmarks keep reference semantics
        # (off in benchmark_mode and in BENCHMARK_CFG).
        self.enable_prefix_affinity = (
            not benchmark_mode
            and bool(self.config.get("enable_prefix_affinity", False)))
        self.prefix_affinity_min_confidence = float(
            self.config.get("prefix_affinity_min_confidence", 0.75))
        self.prefix_affinity_min_tokens = int(
            self.config.get("prefix_affinity_min_tokens", 32))
        self.prefix_affinity_overrides = 0
        self._response_store: Dict[str, Dict[str, Any]] = {}

        # Continuous liveness probing + ICI health exchange (serving/
        # health.py) — off by default to keep bench runs deterministic.
        self.health_monitor = None
        if self.config.get("enable_health_monitor", False):
            from .health import HealthMonitor
            self.health_monitor = HealthMonitor(
                self,
                interval_s=float(self.config.get("health_interval_s", 5.0)),
                mesh=self.config.get("health_mesh"))
            self.health_monitor.start()

    # -- back-compat (src/router.py:65-67) ---------------------------------

    def set_threshold(self, threshold: int) -> None:
        self.threshold_fallback = threshold

    # -- helpers -----------------------------------------------------------

    def _apply_prefix_affinity(self, device: str, confidence: float,
                               method: str, reasoning: str, history
                               ) -> Tuple[str, str, str]:
        """Steer a LOW-confidence decision to the tier already holding
        this conversation's parked KV prefix (cache-locality-aware
        routing — beyond the reference, production only).

        Probes are non-destructive (PrefixCache.peek through
        engine.prefix_affinity), touch only ALREADY-RUNNING local
        engines (never starts one, never crosses hosts), and only
        override when the other tier's match beats the chosen tier's by
        at least ``prefix_affinity_min_tokens`` — a confident routing
        decision or a trivial prefix never flips.

        UPGRADE-ONLY: affinity may steer toward a STRONGER tier (later
        in the cluster's declaration order — the reference's nano<orin
        topology), never downgrade.  Locality must not cost capability:
        a complex follow-up whose early small-talk parked the
        conversation on nano still belongs on orin (measured: the
        symmetric rule dragged orin-labeled queries to nano and cost
        the semantic/hybrid cache-on legs ~0.17 accuracy; the reference
        resolves every such tie toward orin too — threshold fallback,
        heavy-context override)."""
        if (not self.enable_prefix_affinity
                or confidence >= self.prefix_affinity_min_confidence):
            return device, method, reasoning
        order = [t.name for t in self.cluster.tiers()]
        scores: Dict[str, int] = {}
        for name, tier in self.tiers.items():
            if (name not in order or device not in order
                    or order.index(name) <= order.index(device)):
                continue                 # upgrade-only: skip weaker tiers
            engine = getattr(tier.server_manager, "_engine", None)
            probe = getattr(engine, "prefix_affinity", None)
            if callable(probe):
                try:
                    scores[name] = int(probe(history))
                except Exception:
                    scores[name] = 0
        if not scores:
            return device, method, reasoning
        # The chosen tier's own match sets the bar the upgrade must beat.
        own_engine = getattr(self.tiers[device].server_manager, "_engine",
                             None)
        own_probe = getattr(own_engine, "prefix_affinity", None)
        own = 0
        if callable(own_probe):
            try:
                own = int(own_probe(history))
            except Exception:
                own = 0
        best = max(scores, key=scores.get)
        if (best != device
                and scores[best] >= own + self.prefix_affinity_min_tokens):
            reasoning = (f"prefix affinity: {best} holds a "
                         f"{scores[best]}-token parked prefix of this "
                         f"conversation (decision was {device} at "
                         f"confidence {confidence:.2f}); {reasoning}")
            self.prefix_affinity_overrides += 1
            return best, f"{method}+prefix_affinity", reasoning
        return device, method, reasoning

    @staticmethod
    def _extract_text(response: Any) -> Optional[str]:
        """Normalize any tier response shape to a plain string
        (src/router.py:73-102)."""
        if response is None:
            return None
        if isinstance(response, str):
            return response.strip() or None
        if isinstance(response, dict):
            for key in ("response", "content", "message"):
                val = response.get(key)
                if isinstance(val, str) and val.strip():
                    return val.strip()
                if isinstance(val, dict):
                    inner = val.get("content")
                    if isinstance(inner, str) and inner.strip():
                        return inner.strip()
            if "error" in response:
                parts = [str(response.get(k, "")).strip()
                         for k in ("error", "detail", "body")]
                combined = " ".join(p for p in parts if p)
                return combined[:300] if combined else None
        return None

    def _history_to_query_and_context(
        self, history: List[Dict[str, Any]]
    ) -> Tuple[str, Optional[str], str]:
        """Split history into (last user query, prior-turn context string,
        sha256[:16] hash of the last-k turns) — src/router.py:104-147."""
        if not history:
            return "", None, "nohist"

        last_user = None
        for i in range(len(history) - 1, -1, -1):
            m = history[i]
            if isinstance(m, dict) and m.get("role") == "user":
                last_user = i
                break

        if last_user is None:
            query, ctx_msgs = "", history
        else:
            query = (history[last_user].get("content") or "").strip()
            ctx_msgs = history[:last_user]

        lines = [
            f"{(m.get('role') or '').strip()}: {(m.get('content') or '').strip()}"
            for m in ctx_msgs
            if isinstance(m, dict) and (m.get("content") or "").strip()
        ]
        context = "\n".join(lines) if lines else None

        compact = "\n".join(
            f"{m.get('role', '')}:{(m.get('content') or '').strip()}"
            for m in ctx_msgs[-self.cache_last_k:]
            if isinstance(m, dict))
        ctx_hash = hashlib.sha256(compact.encode("utf-8")).hexdigest()[:16]
        return query, context, ctx_hash

    @staticmethod
    def _is_error(raw: Any) -> bool:
        return isinstance(raw, dict) and "error" in raw

    def _run_device(self, device: str,
                    history: List[Dict[str, Any]]) -> Tuple[Any, str, float]:
        tier = self.tiers.get(device, self.nano)
        logger.info("Processing query on %s", tier.name)
        t0 = time.perf_counter()
        raw = tier.process(history)
        return raw, tier.name, (time.perf_counter() - t0) * 1000.0

    # -- response cache (src/router.py:179-193) ----------------------------

    def _response_cache_key(self, ctx_hash: str, query: str) -> str:
        # Deliberately context-independent (reference intent, router.py:57-59)
        return f"{self.query_router.strategy}|{query.lower().strip()}"

    # -- main pipeline -----------------------------------------------------

    def _feed_perf_load(self) -> None:
        """Queue-aware routing input: push each tier's live load
        (admission queue depth + batch slot occupancy) into the active
        strategy before it decides.  Cheap in-memory counters; skipped
        entirely unless the strategy consumes them (perf only)."""
        if not getattr(self.query_router, "wants_load", False):
            return
        for name, tier in self.tiers.items():
            snap_fn = getattr(tier, "load_snapshot", None)
            if snap_fn is None:
                continue                     # remote tiers: no local load
            try:
                self.query_router.update_load(name, **snap_fn())
            except Exception:
                pass

    def _decide(self, query: str, context: str, ctx_hash: str,
                history: List[Dict[str, Any]]):
        """The routing-decision stage shared by the sync and streaming
        pipelines: QueryRouter decision with the reference's ctx-size
        fallback on engine failure (src/router.py:258-270).  Returns
        (device, method, confidence, reasoning, cache_hit, overhead_ms)."""
        t0 = time.perf_counter()
        self._feed_perf_load()
        device = "nano"
        method, confidence, reasoning = "unknown", 0.0, ""
        cache_hit = False
        try:
            decision = self.query_router.route_query(
                query=query, context=context, context_key=ctx_hash)
            device = decision.device
            method = decision.method
            confidence = float(decision.confidence)
            reasoning = decision.reasoning
            cache_hit = bool(decision.cache_hit)
            logger.info("[%s] routing: %s | method=%s conf=%.3f",
                        "BENCH" if self.benchmark_mode else "PROD",
                        device.upper(), method, confidence)
        except Exception as exc:
            ctx_size = self.token_counter.get_context_size(history)
            device = "orin" if ctx_size > self.threshold_fallback else "nano"
            method = "fallback_ctx_size"
            confidence = 0.2
            reasoning = (f"router failed: {exc}; ctx_size={ctx_size}, "
                         f"threshold_fallback={self.threshold_fallback}")
            logger.warning("routing failed (%s); ctx fallback -> %s", exc, device)
        overhead_ms = (time.perf_counter() - t0) * 1000.0
        return device, method, confidence, reasoning, cache_hit, overhead_ms

    def route_query(self, history: List[Dict[str, Any]]
                    ) -> Tuple[Dict[str, Any], int, str]:
        query, context, ctx_hash = self._history_to_query_and_context(history)

        # 0) response cache
        if self.enable_response_cache:
            cached = self._response_store.get(
                self._response_cache_key(ctx_hash, query))
            if cached is not None:
                text = cached.get("text", "")
                which = cached.get("device", "nano")
                tokens = self.token_counter.count_tokens(
                    {"role": "assistant", "content": text})
                return {
                    "response": text,
                    "raw": cached.get("raw"),
                    "cache_hit": True,
                    "routing_method": "response_cache",
                    "routing_confidence": 1.0,
                    "routing_reasoning": f"response cache hit -> {which}",
                    "routing_overhead_ms": 0.0,
                    "ok": True,
                }, tokens, which

        # 1) routing decision
        (device, method, confidence, reasoning,
         cache_hit, overhead_ms) = self._decide(query, context, ctx_hash,
                                                history)
        device, method, reasoning = self._apply_prefix_affinity(
            device, confidence, method, reasoning, history)

        # 2) inference + failover
        raw, which, lat_ms = self._run_device(device, history)
        if self.enable_failover and self._is_error(raw):
            other = "orin" if which == "nano" else "nano"
            logger.warning("%s failed — failing over to %s", which, other)
            # Record the PRIMARY's failure before switching: the
            # reference feeds perf only for the device that ultimately
            # served (router.py:292-295), so failover masked every
            # failure from the perf strategy — yet its fail_penalty
            # exists precisely to steer traffic off flaky devices.
            # Divergence documented in PARITY.md; especially load-bearing
            # for request timeouts (a wedged tier must lose traffic).
            try:
                self.query_router.update_perf(which, lat_ms, 0, ok=False)
            except Exception:
                pass
            raw2, which2, lat2 = self._run_device(other, history)
            if not self._is_error(raw2):
                raw, which, lat_ms = raw2, which2, lat2

        # 3) normalize + count
        text = self._extract_text(raw) or "No response available"
        tokens = self.token_counter.count_tokens(
            {"role": "assistant", "content": text})
        ok = not self._is_error(raw)

        # 4) perf feedback
        try:
            self.query_router.update_perf(which, lat_ms, tokens, ok=ok)
        except Exception:
            pass

        # 5) response-cache store
        if self.enable_response_cache:
            self._response_store[self._response_cache_key(ctx_hash, query)] = {
                "text": text,
                "raw": raw,
                "device": which,
                "routing_confidence": round(confidence, 4),
            }

        return {
            "response": text,
            "raw": raw,
            "cache_hit": False,
            "benchmark_mode": self.benchmark_mode,
            "routing_overhead_ms": round(overhead_ms, 2),
            "routing_method": method,
            "routing_confidence": round(confidence, 4),
            "routing_reasoning": reasoning,
            "ok": ok,
        }, tokens, which

    def route_query_stream(self, history: List[Dict[str, Any]]
                           ) -> "RoutedStream":
        """Streaming twin of ``route_query``: same decision stage
        (``_decide`` incl. the ctx-size fallback), same one-shot tier
        failover — applied at stream SETUP, where a clean switch is still
        possible — and the same perf feedback, fired when the stream
        completes.  The response cache does not participate: a streamed
        reply is consumed as it is produced.  Raises RuntimeError if no
        tier can start a stream."""
        query, context, ctx_hash = self._history_to_query_and_context(history)
        (device, method, confidence, reasoning,
         cache_hit, overhead_ms) = self._decide(query, context, ctx_hash,
                                                history)
        device, method, reasoning = self._apply_prefix_affinity(
            device, confidence, method, reasoning, history)

        t0 = time.perf_counter()
        tier = self.tiers.get(device, self.nano)
        handle = tier.process_stream(history)
        which = tier.name
        if self._is_error(handle) and self.enable_failover:
            other = "orin" if which == "nano" else "nano"
            logger.warning("%s stream setup failed — failing over to %s",
                           which, other)
            # Same as the sync path: the primary's failure must reach
            # the perf strategy even though failover will serve.
            try:
                self.query_router.update_perf(
                    which, (time.perf_counter() - t0) * 1000.0, 0, ok=False)
            except Exception:
                pass
            alt = self.tiers[other].process_stream(history)
            if not self._is_error(alt):
                handle, which = alt, other
        if self._is_error(handle):
            raise RuntimeError(handle.get("error", "stream setup failed"))

        def on_done(result, ok: bool) -> None:
            # Engine-true generation time, NOT wall time to exhaustion: a
            # slow SSE consumer would otherwise poison the perf strategy's
            # latency window for a healthy tier.
            if result is not None and result.total_ms > 0:
                lat_ms = result.total_ms
            else:
                lat_ms = (time.perf_counter() - t0) * 1000.0
            tokens = result.gen_tokens if result else 0
            try:
                self.query_router.update_perf(which, lat_ms, tokens, ok=ok)
            except Exception:
                pass

        meta = {
            "device": which,
            "method": method,
            "confidence": round(confidence, 4),
            "reasoning": reasoning,
            # Same meaning as /chat's cache_hit (response cache) — streams
            # never serve from it, so always False; the routing-decision
            # cache hit is its own field (it also shows as "*_cached" in
            # method, matching the sync path's convention).
            "cache_hit": False,
            "routing_cache_hit": cache_hit,
            "routing_overhead_ms": round(overhead_ms, 2),
        }
        return RoutedStream(handle, which, meta, on_done)


class RoutedStream:
    """A routed token stream: iterate for text deltas; ``.result`` holds
    the GenerationResult once exhausted.  Fires the router's perf-feedback
    callback exactly once, whether the stream completes, errors, or is
    abandoned mid-iteration (client disconnect)."""

    def __init__(self, handle, device: str, meta: Dict[str, Any], on_done):
        self._handle = handle
        self.device = device
        self.meta = meta
        self._on_done = on_done
        self._fired = False

    def _fire(self, ok: bool) -> None:
        if not self._fired:
            self._fired = True
            self._on_done(self._handle.result, ok)

    def __iter__(self):
        try:
            for delta in self._handle:
                yield delta
        except GeneratorExit:
            # Consumer abandoned the stream (client disconnect) — the TIER
            # was healthy as far as it was consumed; an ok=False sample
            # here would let disconnecting clients poison the perf
            # strategy against a healthy tier.
            self._fire(True)
            raise
        except BaseException:        # real engine/stream failure
            self._fire(False)
            raise
        self._fire(True)

    @property
    def result(self):
        return self._handle.result
