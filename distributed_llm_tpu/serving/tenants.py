"""Per-tenant quota enforcement (ISSUE 17).

One ``TenantQuotas`` registry per quota-ON tier (``TierConfig.
tenant_quotas`` is not None), consulted by ``TierClient`` alongside the
PR 1 ``AdmissionController``: where the controller bounds the TIER
(slots, queue, predicted wait, pool pressure), this registry bounds each
TENANT's share of it — concurrent requests, a device-time-rate token
bucket debited from the measured PR 11 ``device_time_ms`` bill, and the
resident-KV block budget the engine bills at 1/refcount.

Billing is post-paid: a request admits against the bucket's CURRENT
level and its measured device time is debited at the router's
exactly-once ``_finish_request`` exit, so a tenant that burned more than
its rate allows goes negative and is rejected until the refill catches
up — enforcement from measured cost, not declared cost.

Rejections return a reason string the tier client wraps in the
reference error shape with ``retry_after_s`` (the bucket's
time-to-positive, or the admission EWMA) so Router failover and the
perf penalty fire exactly as for tier-level rejections.  Thread
discipline: every mutable field is guarded by ``_lock`` — admissions
run on serving threads, releases/debits on tier worker threads (the
lock-mixed-guard lint pins this).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, Optional

from ..config import TenantQuota, TierConfig
from ..config_registry import env_float, env_int

# The tenant a request without a tenant_id field bills to (serving/
# app.py): tenant-less clients share one identity, so quotas-on
# deployments can bound them collectively while tenant-aware clients
# are billed individually.
DEFAULT_TENANT = "default"


def default_quota() -> TenantQuota:
    """The quota tenants absent from ``TierConfig.tenant_quotas`` get,
    assembled from the ``DLLM_TENANT_*`` env defaults (unset or zero =
    that criterion off)."""
    return TenantQuota(
        max_inflight=env_int("DLLM_TENANT_MAX_INFLIGHT", 0) or None,
        max_queued=env_int("DLLM_TENANT_MAX_QUEUED", 0) or None,
        device_ms_per_s=env_float("DLLM_TENANT_DEVICE_MS_PER_S",
                                  0.0) or None,
        kv_blocks=env_int("DLLM_TENANT_KV_BLOCKS", 0) or None,
        spec_gamma_max=env_int("DLLM_TENANT_GAMMA_MAX", 0) or None,
    )


class TenantQuotas:
    """Per-tenant admission budgets for ONE tier.

    ``try_admit`` / ``release`` bracket each request exactly like the
    ``AdmissionController`` pair (the caller owns exactly-once release);
    ``debit`` feeds the token bucket from the measured device-time bill.
    A tier with ``tenant_quotas=None`` never constructs this class —
    the quotas-off byte-identity contract.
    """

    def __init__(self, tier: TierConfig, now=time.monotonic):
        self.tier = tier
        self._now = now
        self._quotas: Dict[str, TenantQuota] = dict(tier.tenant_quotas
                                                    or {})
        self._default = default_quota()
        self._lock = threading.Lock()
        # tenant -> requests admitted against the quota, not released.
        self._active: Dict[str, int] = {}
        # tenant -> [level_ms, last_refill_t]; levels go NEGATIVE on
        # post-paid debit and refill at quota.device_ms_per_s.
        self._buckets: Dict[str, list] = {}
        self.admitted = 0
        self.rejected = 0

    def quota(self, tenant: str) -> TenantQuota:
        return self._quotas.get(tenant, self._default)

    def weight(self, tenant: str) -> float:
        return max(1e-6, float(self.quota(tenant).weight))

    def gamma_cap(self, tenant: str) -> Optional[int]:
        return self.quota(tenant).spec_gamma_max

    def kv_budget(self, tenant: str) -> Optional[int]:
        return self.quota(tenant).kv_blocks

    def _burst_ms(self, q: TenantQuota) -> float:
        if q.device_ms_burst is not None:
            return float(q.device_ms_burst)
        return 2.0 * float(q.device_ms_per_s or 0.0)

    def _bucket_level(self, tenant: str, q: TenantQuota) -> Optional[float]:
        """Refill-then-read the tenant's token bucket (callers hold
        ``_lock``); None when the tenant has no rate budget."""
        if not q.device_ms_per_s:
            return None
        t = self._now()
        bucket = self._buckets.get(tenant)
        if bucket is None:
            bucket = [self._burst_ms(q), t]
            self._buckets[tenant] = bucket
        level, last = bucket
        level = min(self._burst_ms(q),
                    level + (t - last) * float(q.device_ms_per_s))
        bucket[0] = level
        bucket[1] = t
        return level

    def try_admit(self, tenant: str,
                  kv_bill: Optional[float] = None) -> Optional[str]:
        """None = admitted (caller MUST ``release(tenant)`` exactly
        once); else the rejection reason.  ``kv_bill`` is the tenant's
        current resident-KV bill in 1/refcount blocks (from the tier
        engine's ``tenant_kv_blocks``) and arms the per-tenant KV gate:
        a tenant over its block budget has its COLD admissions shed
        with a 'KV demand'-shaped reason until the bill drops."""
        q = self.quota(tenant)
        with self._lock:
            active = self._active.get(tenant, 0)
            if q.max_inflight is not None:
                cap = q.max_inflight + (q.max_queued or 0)
                if active >= cap:
                    self.rejected += 1
                    return (f"tenant '{tenant}' queue full ({active} in "
                            f"flight/waiting, cap {cap})")
            level = self._bucket_level(tenant, q)
            if level is not None and level <= 0.0:
                self.rejected += 1
                return (f"tenant '{tenant}' device-time budget exhausted "
                        f"(bucket {level:.0f} ms at "
                        f"{q.device_ms_per_s:g} ms/s)")
            if (kv_bill is not None and q.kv_blocks is not None
                    and kv_bill > q.kv_blocks):
                self.rejected += 1
                return (f"tenant '{tenant}' projected KV demand over "
                        f"budget (resident bill {kv_bill:.1f} blocks, "
                        f"budget {q.kv_blocks})")
            self._active[tenant] = active + 1
            self.admitted += 1
        self._set_inflight(tenant)
        return None

    def release(self, tenant: str) -> None:
        with self._lock:
            n = self._active.get(tenant, 0)
            if n <= 1:
                self._active.pop(tenant, None)
            else:
                self._active[tenant] = n - 1
        self._set_inflight(tenant)

    def debit(self, tenant: str, device_ms: float) -> None:
        """Charge the measured device-time bill against the tenant's
        token bucket (router ``_finish_request``, exactly once per
        request).  No-op for tenants without a rate budget."""
        if not device_ms:
            return
        q = self.quota(tenant)
        with self._lock:
            level = self._bucket_level(tenant, q)
            if level is None:
                return
            self._buckets[tenant][0] = level - float(device_ms)

    def retry_after_s(self, tenant: str) -> float:
        """Client retry hint for a tenant rejection: the bucket's
        time-to-positive when the rate budget is the binding limit,
        else a 1 s floor (queue/KV rejections clear when a request
        finishes — EWMA territory the tier client already owns)."""
        q = self.quota(tenant)
        with self._lock:
            level = self._bucket_level(tenant, q)
        if level is not None and level < 0.0 and q.device_ms_per_s:
            return max(0.1, round(-level / float(q.device_ms_per_s), 2))
        return 1.0

    def _set_inflight(self, tenant: str) -> None:
        try:
            # No injection path here (engine-counter pattern): the
            # process-global registry, tenant label bounded through the
            # shared per-registry BoundedLabels set.
            from ..obs import get_observability
            obs = get_observability()
            with self._lock:
                n = self._active.get(tenant, 0)
            obs.m.tenant_inflight_g.labels(
                self.tier.name, obs.tenant_labels.label(tenant)).set(n)
        except Exception:
            pass

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            buckets = {t: round(b[0], 2) for t, b in self._buckets.items()}
            return {
                "tenants": sorted(set(self._quotas) | set(self._active)),
                "active": dict(self._active),
                "bucket_ms": buckets,
                "admitted": self.admitted,
                "rejected": self.rejected,
            }
