"""Flask chat API — reference contract preserved verbatim.

Reference parity: src/app.py.  Endpoints and JSON fields are identical so
the reference's React frontend points at this server unchanged:

  POST /chat       {message, strategy, session_id} ->
                   {reply, device, reasoning, method, confidence,
                    cache_hit, tokens}
  GET  /history    ?session_id=...   -> [messages]
  DELETE /history  ?session_id=...   -> {"cleared": session_id}

Behavioral details kept: UI strategy name "token-counting" maps to "token"
(app.py:37-38); strategy switches go through QueryRouter.change_strategy so
cache + perf state survive (app.py:46-53); per-session history capped at the
last 10 messages (app.py:23); the just-appended user message is rolled back
if routing raises (app.py:96-97).  Fixed (documented drift): session state
lives behind a lock — the reference's bare globals are a known hazard under
a threaded server (SURVEY.md §5.2).
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Any, Dict, List, Optional

from ..config import ClusterConfig
from ..utils.http_compat import (Flask, enable_cors, jsonify, request,
                                 sse_done_event, sse_event, static_response,
                                 streaming_response)
from .router import Router

logger = logging.getLogger(__name__)

HISTORY_LIMIT = 10

# Input-hardening cap on one /chat message, in characters.  Far above any
# real prompt (every tier's context truncates earlier — overflow_policy /
# prepare_prompt), low enough that a hostile body can't make the session
# store or the tokenizer chew megabytes before the edge says no.
MAX_MESSAGE_CHARS = 65536

# Same defaults the reference app passes (src/app.py:9-14).
BASE_CONFIG: Dict[str, Any] = {
    "cache_enabled": True,
    "enable_response_cache": True,
    "enable_failover": True,
    "weights": {"token": 0.25, "semantic": 0.45, "heuristic": 0.30},
}


def create_app(router: Optional[Router] = None,
               cluster: Optional[ClusterConfig] = None) -> Flask:
    app = Flask("distributed_llm_tpu")
    enable_cors(app)

    state_lock = threading.Lock()
    if router is None:
        router = Router(strategy="hybrid", config=dict(BASE_CONFIG),
                        cluster=cluster)
    state = {
        "router": router,
        "strategy": router.query_router.strategy,
        "histories": {},      # session_id -> List[message]
    }
    app.extensions["dllm_state"] = state

    @app.route("/chat", methods=["POST"])
    def chat():
        err, turn, requested, session_id, tenant_id, history, snapshot = \
            _begin_chat_turn()
        if err is not None:
            return err

        try:
            response_data, tokens, device = state["router"].route_query(
                snapshot, session_id=session_id, tenant_id=tenant_id)

            if isinstance(response_data, dict):
                reply = response_data.get("response", "")
                reasoning = response_data.get(
                    "routing_reasoning", f"Method: {requested}")
                method = response_data.get("routing_method", requested)
                confidence = response_data.get("routing_confidence", 0.0)
                cache_hit = response_data.get("cache_hit", False)
            else:
                reply = str(response_data)
                reasoning, method = "Direct response", requested
                confidence, cache_hit = 0.0, False

            _commit_assistant_turn(history, session_id, reply)

            return jsonify({
                "reply": reply,
                "device": device,
                "reasoning": reasoning,
                "method": method,
                "confidence": confidence,
                "cache_hit": cache_hit,
                "tokens": tokens,
            })

        except Exception as exc:
            logger.exception("Error during routing")
            _rollback_user_turn(history, turn)
            return jsonify({
                "reply": "System Error: The router encountered an issue.",
                "device": "error",
                "reasoning": str(exc),
                "method": requested,
                "confidence": 0.0,
                "cache_hit": False,
                "tokens": 0,
            }), 500

    def _bad_request(msg: str):
        """One 400 shape for every input-hardening rejection (reference
        error dict, like the original missing-message branch)."""
        return ((jsonify({"error": msg}), 400),
                None, None, None, None, None, None)

    def _begin_chat_turn():
        """Shared /chat + /chat/stream front half: parse AND VALIDATE the
        request, hot-swap the strategy, append the user turn.  Returns
        (error_response | None, user_input, requested, session_id,
        tenant_id, history, snapshot).

        Input hardening: bad JSON / non-object bodies, non-string or
        oversized messages, and non-string strategy/session_id/tenant_id
        are all 400 with the reference error shape — before this, only a
        missing message was caught and a non-string one crashed
        downstream in the tokenizer.  ``tenant_id`` (ISSUE 17, additive
        field) is capped at 64 chars and must be printable — it becomes
        a metric label and a quota key; absent means the shared
        ``default`` tenant, so tenant-less clients are unchanged."""
        if getattr(state["router"], "draining", False):
            # Graceful drain: the edge stops admitting FIRST.  503 + the
            # sanctioned retry hint; in-flight requests keep finishing.
            return ((jsonify({
                "error": "Request failed: server is draining "
                         "(graceful shutdown in progress)",
                "retry_after_s": state["router"].drain_retry_after_s(),
            }), 503), None, None, None, None, None, None)
        data = request.get_json(silent=True)
        if data is None:
            return _bad_request("Request failed: body must be valid JSON")
        if not isinstance(data, dict):
            return _bad_request("Request failed: body must be a JSON "
                                "object")
        user_input = data.get("message", "")
        requested = data.get("strategy", "hybrid")
        session_id = data.get("session_id", "default")
        tenant_id = data.get("tenant_id", "default")
        if not isinstance(user_input, str):
            return _bad_request("Request failed: 'message' must be a "
                                "string")
        if len(user_input) > MAX_MESSAGE_CHARS:
            return _bad_request(f"Request failed: 'message' exceeds "
                                f"{MAX_MESSAGE_CHARS} characters")
        if not isinstance(requested, str) or not isinstance(session_id,
                                                            str):
            return _bad_request("Request failed: 'strategy' and "
                                "'session_id' must be strings")
        if not isinstance(tenant_id, str) or not tenant_id:
            return _bad_request("Request failed: 'tenant_id' must be a "
                                "non-empty string")
        if len(tenant_id) > 64:
            return _bad_request("Request failed: 'tenant_id' exceeds "
                                "64 characters")
        if any(ord(c) < 32 or ord(c) == 127 for c in tenant_id):
            return _bad_request("Request failed: 'tenant_id' must not "
                                "contain control characters")
        if requested == "token-counting":   # UI dropdown name
            requested = "token"
        if not user_input.strip():
            return _bad_request("No message provided")
        with state_lock:
            if requested != state["strategy"]:
                logger.info("Switching strategy: %s -> %s",
                            state["strategy"], requested)
                try:
                    state["router"].query_router.change_strategy(requested)
                    state["strategy"] = requested
                except Exception as exc:
                    return ((jsonify({"error":
                                      f"Failed to switch strategy: {exc}"}),
                             500), None, None, None, None, None, None)
            history = state["histories"].setdefault(session_id, [])
            turn = {"role": "user", "content": user_input}
            history.append(turn)
            snapshot = list(history)
        return (None, turn, requested, session_id, tenant_id, history,
                snapshot)

    def _rollback_user_turn(history, turn):
        """Remove THIS request's user turn by identity — popping the tail
        would delete a different request's turn when two land on the same
        session concurrently (streams hold the window open for seconds)."""
        with state_lock:
            for i in range(len(history) - 1, -1, -1):
                if history[i] is turn:
                    del history[i]
                    break

    def _commit_assistant_turn(history, session_id, reply):
        """Append the assistant turn and trim IN PLACE: replacing the list
        object would orphan the reference every other in-flight request on
        this session holds — and NO re-bind, which would resurrect a
        session cleared (or replaced) while this request was in flight."""
        with state_lock:
            history.append({"role": "assistant", "content": reply})
            if len(history) > HISTORY_LIMIT:
                del history[:len(history) - HISTORY_LIMIT]

    @app.route("/chat/stream", methods=["POST"])
    def chat_stream():
        """SSE chat: one ``meta`` event with the routing decision, then
        ``delta`` events as tokens decode, then ``done``.  The reference
        API is non-streaming (stream:false, src/devices/nano_api.py:67);
        this is the TTFT-native extension of /chat, built on
        Router.route_query_stream — the SAME decision stage, setup-time
        failover, fault model, and perf feedback as the sync path.  The
        response cache does not participate (a stream is consumed as it
        is produced)."""
        err, turn, requested, session_id, tenant_id, history, snapshot = \
            _begin_chat_turn()
        if err is not None:
            return err

        try:
            routed = state["router"].route_query_stream(
                snapshot, session_id=session_id, tenant_id=tenant_id)
        except Exception as exc:
            logger.exception("stream routing failed")
            _rollback_user_turn(history, turn)
            return jsonify({"error": f"Routing failed: {exc}"}), 500

        def events():
            pieces: List[str] = []
            committed = False
            try:
                yield sse_event({"meta": True, **routed.meta})
                for delta in routed:
                    pieces.append(delta)
                    yield sse_event({"delta": delta})
                _commit_assistant_turn(history, session_id, "".join(pieces))
                committed = True
                yield sse_done_event(routed.result)
            except Exception as exc:
                logger.exception("stream failed mid-flight")
                yield sse_event({"error": str(exc)})
            finally:
                # Covers errors AND client disconnects (GeneratorExit
                # skips except-Exception): an uncommitted turn must not
                # leave the session history with this request's dangling
                # user message.
                if not committed:
                    _rollback_user_turn(history, turn)

        return streaming_response(events())

    # -- frontend (reference: fyp-chat-frontend, served here dependency-
    # free — same /chat contract, so the original React app also works
    # pointed at this server) --------------------------------------------
    frontend_dir = os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))), "frontend")
    ui_files = {                  # fixed allowlist: no path traversal
        "/ui": ("index.html", "text/html; charset=utf-8"),
        "/ui/app.js": ("app.js", "application/javascript; charset=utf-8"),
        "/ui/style.css": ("style.css", "text/css; charset=utf-8"),
    }

    def _serve_ui(route: str):
        fname, ctype = ui_files[route]
        path = os.path.join(frontend_dir, fname)
        if not os.path.exists(path):
            return jsonify({"error": "frontend not bundled"}), 404
        with open(path, "rb") as f:
            return static_response(f.read(), ctype)

    def _make_ui_view(route: str):
        def view():
            return _serve_ui(route)
        # Distinct names: real Flask derives its endpoint from __name__.
        view.__name__ = "ui_" + ui_files[route][0].replace(".", "_")
        return view

    for route in ui_files:
        app.route(route, methods=["GET"])(_make_ui_view(route))

    @app.route("/health", methods=["GET"])
    def health():
        """Process-level liveness for load balancers and drain
        orchestration: ``status`` is ``draining`` (503) once a graceful
        drain started, else ``ok``.  Per-tier snapshots ride along —
        manager.health() is lock-free, so this never blocks behind a
        mid-compile lifecycle lock."""
        router_ = state["router"]
        draining = bool(getattr(router_, "draining", False))
        tiers = {}
        for name, tier in router_.tiers.items():
            try:
                tiers[name] = tier.server_manager.health()
            except Exception as exc:
                tiers[name] = {"ok": False, "detail": str(exc)[:200]}
        payload = {"status": "draining" if draining else "ok",
                   "draining": draining,
                   "tiers": tiers}
        if draining:
            payload["retry_after_s"] = router_.drain_retry_after_s()
            return jsonify(payload), 503
        return jsonify(payload)

    @app.route("/metrics", methods=["GET"])
    def metrics():
        """Prometheus text exposition of the serving metric registry
        (obs/metrics.py): TTFT/TBT/queue-wait histograms, admission
        rejects, breaker transitions + state, watchdog wedges, cache
        hits, degraded count.  Scrape-friendly twin of GET /stats."""
        body = state["router"].obs.metrics.render().encode("utf-8")
        return static_response(
            body, "text/plain; version=0.0.4; charset=utf-8")

    @app.route("/debug/trace", methods=["GET"])
    def debug_trace():
        """Chrome-trace/Perfetto JSON of every live engine's tick-phase
        profiler ring (obs/profiler.py): ticks as slices, phases as
        nested child slices with self-times, compile/host-sync instants
        stitched in.  Load it in chrome://tracing or ui.perfetto.dev —
        the "why did that tick cost 40 ms" surface.  Empty traceEvents
        when no profiler is live (DLLM_PROFILE=0, sequential tiers)."""
        router_ = state["router"]
        fn = getattr(router_, "profiler_trace", None)
        body = fn() if callable(fn) else {"traceEvents": []}
        return jsonify(body)

    @app.route("/stats", methods=["GET"])
    def stats():
        """Observability snapshot (SURVEY.md §5.5): routing-cache health,
        per-tier engine state + phase timings, device memory.  With
        ``?debug=1``: the flight recorder's ring — full span trees +
        serving-state snapshots of the last N failed/degraded/slow
        requests (obs/recorder.py) — for post-mortems."""
        from ..utils.telemetry import device_memory_snapshot
        with state_lock:
            router_ = state["router"]
            strategy = state["strategy"]
            sessions = len(state["histories"])
        tiers = {}
        for name, tier in router_.tiers.items():
            mgr = tier.server_manager
            entry = dict(mgr.health())
            # Peek without lazy-starting; remote tiers' managers
            # (serving/remote.py) have no local engine at all.
            from ..utils.telemetry import engine_stats
            subs = getattr(mgr, "live_engines", None)
            if callable(subs):
                # Replicated tier (ISSUE 12): per-replica engine stats
                # nested under their replica keys, plus the manager's
                # summed kv picture at tier level.
                entry["replica_engines"] = {
                    key: engine_stats(engine) for key, engine in subs()}
                kv_fn = getattr(mgr, "kv_stats", None)
                agg = kv_fn() if callable(kv_fn) else None
                if agg:
                    entry["kv"] = agg
            else:
                entry.update(engine_stats(getattr(mgr, "_engine", None)))
            # Per-tenant quota state (ISSUE 17): active counts, token-
            # bucket levels, admit/reject totals — quota-ON tiers only.
            tq = getattr(tier, "tenants", None)
            if tq is not None:
                try:
                    entry["tenants"] = tq.snapshot()
                except Exception:
                    pass
            tiers[name] = entry
        try:
            cache_stats = router_.query_router.get_cache_stats()
        except Exception:
            cache_stats = None
        # Measurement provenance: which measured tables steer serving on
        # THIS backend (attention dispatch, tier tuning) — "none" means
        # the corresponding defaults are in effect.
        import jax as _jax
        backend = _jax.default_backend()
        provenance = {"backend": backend}
        try:
            from ..ops.attention import dispatch_provenance
            disp = dispatch_provenance()
            if disp["active"]:
                provenance["dispatch"] = disp["backend"]
                # A table measured against older kernels still dispatches
                # (re-measuring needs hardware) but must read as
                # provisional (VERDICT r4 #8).
                provenance["dispatch_kernel_gen"] = disp["kernel_gen"]
                provenance["dispatch_stale_kernel_gen"] = (
                    disp["stale_kernel_gen"])
            elif disp["backend"] is not None:
                provenance["dispatch"] = f"ignored ({disp['backend']})"
            else:
                provenance["dispatch"] = "none"
        except Exception:
            provenance["dispatch"] = "none"
        try:
            from ..bench.tune import load_tuning
            provenance["tuning"] = (backend if load_tuning(backend)
                                    else "none")
        except Exception:
            provenance["tuning"] = "none"
        payload = {
            "strategy": strategy,
            "sessions": sessions,
            "cache": cache_stats,
            "tiers": tiers,
            "devices": device_memory_snapshot(),
            "measured_tables": provenance,
            "prefix_affinity_overrides": getattr(
                router_, "prefix_affinity_overrides", 0),
            # Fault-tolerance observability (serving/breaker.py): per-tier
            # circuit state + how many requests the degraded path served.
            "breaker": (router_.breaker.snapshot()
                        if getattr(router_, "breaker", None) is not None
                        else None),
            "degraded_served": getattr(router_, "degraded_served", 0),
            # Degradation cause in ONE call: per-tier draining flags next
            # to the breaker states, and the SLO monitor's windowed
            # goodput + incident state (obs/slo.py) — an operator seeing
            # goodput collapse reads WHY (circuit open? draining? queue?)
            # without a second scrape.
            "draining": {
                name: bool(getattr(t.server_manager, "draining", False))
                for name, t in router_.tiers.items()},
            "slo": (router_.slo.snapshot()
                    if getattr(router_, "slo", None) is not None
                    else None),
            # Elastic capacity (ISSUE 18, serving/autoscaler.py): live
            # membership, streak/cooldown state, and the bounded
            # decision ledger per armed tier — why capacity moved, next
            # to the goodput/breaker evidence that moved it.  None when
            # no tier arms the autoscaler (or DLLM_AUTOSCALE=0).
            "autoscaler": (router_.autoscaler_snapshot()
                           if callable(getattr(router_,
                                               "autoscaler_snapshot",
                                               None))
                           else None),
            # Per-(tier, strategy, session) attributed cost (ISSUE 11):
            # decode device time + KV block-ticks from the bounded
            # ledger _finish_request feeds — who pays for the ticks,
            # in one call.
            "cost": (router_.cost_snapshot()
                     if callable(getattr(router_, "cost_snapshot", None))
                     else None),
        }
        if request.args.get("timeline") == "1":
            # The system-state timeline ring (obs/sampler.py): per-tier
            # queue/slot/KV/breaker/tick trajectory at the sampler's
            # cadence — samples once on demand for an idle router.
            fn = getattr(router_, "timeline_snapshot", None)
            payload["timeline"] = fn() if callable(fn) else []
            sampler = getattr(router_, "sampler", None)
            if sampler is not None:
                payload["timeline_meta"] = {
                    "period_s": sampler.period_s,
                    "capacity": sampler.capacity,
                    "samples_total": sampler.samples_total,
                    "sample_cost_ms": (round(sampler.sample_cost_ms, 4)
                                       if sampler.sample_cost_ms is not None
                                       else None),
                    "running": sampler.running,
                }
        if request.args.get("debug") == "1":
            obs = getattr(router_, "obs", None)
            if obs is not None:
                payload["flight_recorder"] = obs.recorder.snapshot()
                payload["flight_recorded_total"] = \
                    obs.recorder.recorded_total
        return jsonify(payload)

    @app.route("/history", methods=["GET"])
    def get_history():
        session_id = request.args.get("session_id", "default")
        with state_lock:
            return jsonify(state["histories"].get(session_id, []))

    @app.route("/history", methods=["DELETE"])
    def clear_history():
        session_id = request.args.get("session_id", "default")
        with state_lock:
            state["histories"].pop(session_id, None)
        return jsonify({"cleared": session_id})

    return app


def install_drain_handler(router: Router, exit_after: bool = True) -> bool:
    """SIGTERM → graceful drain (shared by the API server and the CLI):
    stop admitting (the edge 503s, /health flips to ``draining``), let
    in-flight requests finish under each tier's ``drain_timeout_s``, stop
    the engines, then exit.  Returns False when no handler could be
    installed (non-main thread — e.g. an app built inside a test
    worker)."""
    import signal

    def _on_sigterm(signum, frame):
        logger.warning("SIGTERM: draining before exit")
        try:
            router.drain()
        finally:
            if exit_after:
                raise SystemExit(0)

    try:
        signal.signal(signal.SIGTERM, _on_sigterm)
        return True
    except ValueError:            # not the main thread: caller's problem
        return False


def main() -> None:
    logging.basicConfig(level=logging.INFO)
    router = Router(strategy="hybrid", config=dict(BASE_CONFIG))
    app = create_app(router=router)
    install_drain_handler(router)
    print("🚀 API running on http://0.0.0.0:8000")
    app.run(host="0.0.0.0", port=8000, threaded=True)


if __name__ == "__main__":
    main()
