"""Per-tier circuit breaker — failure isolation ahead of the admission queue.

Round 5's on-chip run died wedged (VERDICT.md: 228/228 failed probes) and
until now the only recovery mechanism was the Router's one-shot failover,
applied per request at dispatch time: a flapping tier kept receiving (and
timing out) its full share of traffic, each failed request burning a
serving thread for up to ``request_timeout_s`` before failover fired.

The breaker makes failure isolation stateful (the classic three-state
machine, cf. APEX/HybridGen's backend-failure isolation in PAPERS.md):

- **closed** — traffic flows; consecutive error-shaped results are
  counted (any success resets the count).
- **open** — after ``failure_threshold`` consecutive failures the tier
  sheds ALL traffic for ``cooldown_s``: the Router re-routes to the
  other tier before dispatch, so an outage costs a dict lookup instead
  of a timeout, and the admission queue never fills with doomed work.
- **half-open** — past the cooldown, exactly ONE request (or a
  HealthMonitor liveness probe) is let through as a canary; success
  closes the breaker, failure re-opens it for another cooldown.

Thresholds live in ``ClusterConfig`` (breaker_failures /
breaker_cooldown_s); ``breaker_failures=0`` disables the breaker
entirely (reference per-call semantics).  All transitions are
thread-safe — production serving records results from concurrent HTTP
threads.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Dict, Iterable, Optional

logger = logging.getLogger(__name__)

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"


class CircuitBreaker:
    """One state machine per tier, keyed by tier name."""

    def __init__(self, tiers: Iterable[str], failure_threshold: int = 5,
                 cooldown_s: float = 30.0, clock=time.monotonic,
                 on_transition=None):
        """``on_transition(tier, old_state, new_state)`` fires on every
        state change (the Router wires the obs/ transition counter and
        state gauge through it).  Called WHILE HOLDING the breaker lock,
        so implementations must be cheap and must never call back into
        the breaker; exceptions are swallowed (observability must not
        change breaker behavior)."""
        self.failure_threshold = int(failure_threshold)
        self.cooldown_s = float(cooldown_s)
        self._clock = clock
        self._on_transition = on_transition
        self._lock = threading.Lock()
        names = list(tiers)
        self._state: Dict[str, str] = {t: CLOSED for t in names}
        self._consecutive: Dict[str, int] = {t: 0 for t in names}
        self._opened_at: Dict[str, float] = {}
        # Half-open admits ONE canary at a time: without the in-flight
        # flag, every request racing past the cooldown edge would be
        # "the" probe and a still-down tier would eat a thundering herd.
        # The permit carries a timestamp: a canary whose outcome never
        # comes back (stream handle abandoned unconsumed) expires after
        # another cooldown_s, so a lost canary can't starve the tier of
        # probe windows forever.
        self._probe_inflight: Dict[str, bool] = {t: False for t in names}
        self._probe_started: Dict[str, float] = {}
        self.opened_total: Dict[str, int] = {t: 0 for t in names}

    @property
    def enabled(self) -> bool:
        return self.failure_threshold > 0

    def _set_state(self, tier: str, new: str) -> None:
        """State write + transition notification (caller holds the lock).
        No-op (and no notification) when the state doesn't change."""
        old = self._state[tier]
        if old == new:
            return
        self._state[tier] = new
        if self._on_transition is not None:
            try:
                self._on_transition(tier, old, new)
            except Exception:
                pass

    # -- routing-time consultation ----------------------------------------

    def allow(self, tier: str) -> bool:
        """May traffic be sent to ``tier``?  A True from an open breaker
        means THIS caller holds the half-open canary permit — it must
        dispatch and then ``record`` the outcome (the Router always
        records after dispatch, so the permit is repaid)."""
        if not self.enabled or tier not in self._state:
            return True
        with self._lock:
            st = self._state[tier]
            if st == CLOSED:
                return True
            if st == OPEN:
                opened = self._opened_at.get(tier, 0.0)
                if self._clock() - opened < self.cooldown_s:
                    return False
                self._set_state(tier, HALF_OPEN)
                self._probe_inflight[tier] = True
                self._probe_started[tier] = self._clock()
                logger.info("breaker %s: cooldown expired -> half-open "
                            "(this request is the canary)", tier)
                return True
            # HALF_OPEN: one canary at a time — unless the outstanding
            # permit is stale (its outcome never came back), in which
            # case a fresh canary takes over.
            if (self._probe_inflight[tier]
                    and self._clock() - self._probe_started.get(tier, 0.0)
                    < self.cooldown_s):
                return False
            self._probe_inflight[tier] = True
            self._probe_started[tier] = self._clock()
            return True

    def retry_after_s(self, tier: Optional[str] = None) -> float:
        """Seconds until the next half-open probe window — the
        retry-after hint for the degraded both-tiers-open response.
        Without a tier: the SOONEST window across open tiers."""
        with self._lock:
            now = self._clock()
            remaining = [
                max(0.0, self.cooldown_s - (now - self._opened_at.get(t, now)))
                for t, st in self._state.items()
                if st == OPEN and (tier is None or t == tier)]
        return min(remaining) if remaining else 0.0

    # -- outcome recording --------------------------------------------------

    def record(self, tier: str, ok: bool) -> None:
        """Feed one request's outcome (ok = not error-shaped)."""
        if not self.enabled or tier not in self._state:
            return
        with self._lock:
            self._probe_inflight[tier] = False
            if ok:
                if self._state[tier] != CLOSED:
                    logger.info("breaker %s: probe succeeded -> closed", tier)
                self._set_state(tier, CLOSED)
                self._consecutive[tier] = 0
                return
            self._consecutive[tier] += 1
            st = self._state[tier]
            if st == HALF_OPEN or (st == CLOSED and self._consecutive[tier]
                                   >= self.failure_threshold):
                if st != OPEN:
                    self.opened_total[tier] += 1
                    logger.warning(
                        "breaker %s: OPEN after %d consecutive failures "
                        "(cooldown %.1fs)", tier, self._consecutive[tier],
                        self.cooldown_s)
                self._set_state(tier, OPEN)
                self._opened_at[tier] = self._clock()

    def note_probe(self, tier: str, healthy: bool) -> None:
        """A HealthMonitor liveness probe's verdict: a healthy probe on
        an OPEN tier past its cooldown advances it to half-open (the next
        real request is the canary) — recovery doesn't have to sacrifice
        a client request to discover the cooldown expired.  An unhealthy
        probe leaves the state alone (probe cadence must not re-arm the
        cooldown and starve the canary window)."""
        if not self.enabled or tier not in self._state:
            return
        with self._lock:
            if (healthy and self._state[tier] == OPEN
                    and self._clock() - self._opened_at.get(tier, 0.0)
                    >= self.cooldown_s):
                self._set_state(tier, HALF_OPEN)
                self._probe_inflight[tier] = False
                logger.info("breaker %s: healthy liveness probe past "
                            "cooldown -> half-open", tier)

    def release_probe(self, tier: str) -> None:
        """Repay a half-open canary permit WITHOUT a verdict (the
        dispatch never produced failure evidence — e.g. an admission
        rejection): the next request becomes the canary immediately
        instead of waiting out the stale-permit expiry."""
        if tier not in self._state:
            return
        with self._lock:
            self._probe_inflight[tier] = False

    def reset(self, tier: str) -> None:
        """Force-close (a successful engine restart by the HealthMonitor
        makes the old failure streak meaningless)."""
        if tier not in self._state:
            return
        with self._lock:
            self._set_state(tier, CLOSED)
            self._consecutive[tier] = 0
            self._probe_inflight[tier] = False

    # -- dynamic membership (serving/replicas.py scale_to) ------------------

    def ensure(self, tier: str) -> None:
        """Mint state for a key added AFTER construction — dynamic
        replica membership (ISSUE 18): a replica that goes live mid-run
        needs its own sub-gate, and without a key here ``allow`` would
        wave it through unconditionally while ``record`` dropped its
        verdicts.  New keys start CLOSED; idempotent, never resets an
        existing key's state."""
        with self._lock:
            if tier in self._state:
                return
            self._state[tier] = CLOSED
            self._consecutive[tier] = 0
            self._probe_inflight[tier] = False
            self.opened_total.setdefault(tier, 0)

    def forget(self, tier: str) -> None:
        """Drop a retired key's live state (scale-down removed the
        replica; replica ids are never reused, so without this every
        scale cycle would leak a dict entry).  ``opened_total`` keeps
        its count — it is history, not live state."""
        with self._lock:
            self._state.pop(tier, None)
            self._consecutive.pop(tier, None)
            self._probe_inflight.pop(tier, None)
            self._opened_at.pop(tier, None)
            self._probe_started.pop(tier, None)

    # -- observability ------------------------------------------------------

    def state(self, tier: str) -> str:
        with self._lock:
            return self._state.get(tier, CLOSED)

    def all_open(self) -> bool:
        """True iff every tier is open AND none is ready for a canary.
        Observability/test helper MIRRORING the Router's degraded gate —
        the gate itself is the allow(device)/allow(other) pair in
        route_query (which must consume the canary permit when one is
        available; this read-only view cannot)."""
        if not self.enabled:
            return False
        with self._lock:
            now = self._clock()
            for t, st in self._state.items():
                if st == CLOSED:
                    return False
                if st == OPEN and (now - self._opened_at.get(t, now)
                                   >= self.cooldown_s):
                    return False
                if st == HALF_OPEN and not self._probe_inflight[t]:
                    return False
            return True

    def snapshot(self) -> Dict[str, Dict[str, Any]]:
        with self._lock:
            now = self._clock()
            return {
                t: {
                    "state": st,
                    "consecutive_failures": self._consecutive[t],
                    "opened_total": self.opened_total[t],
                    "cooldown_remaining_s": (
                        round(max(0.0, self.cooldown_s
                                  - (now - self._opened_at.get(t, now))), 2)
                        if st == OPEN else 0.0),
                }
                for t, st in self._state.items()
            }
