"""Tier clients — the device-client layer over in-process TPU engines.

Reference parity: src/models/nano.py / src/models/orin.py.  A TierClient has
the same surface (``.process(history)`` returning {"response": text} or an
error dict, plus ``.server_manager``) but dispatches to an InferenceEngine on
a chip submesh instead of POSTing through an SSH tunnel.  A registry replaces
the reference's two hard-coded classes, so tiers are config, not code.

Error-dict shapes match the reference client exactly (src/models/nano.py:
30-40) so Router failover and `_is_error` behave identically; faults come
from the injectable fault model (utils/faults.py) since there is no network
to fail naturally.
"""

from __future__ import annotations

import logging
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import jax

from ..config import ClusterConfig, TierConfig
from ..engine.inference import GenerationResult
from ..engine.manager import EngineManager
from ..parallel.mesh import carve_tier_meshes
from ..utils.faults import FaultInjector
from .turns import ClippedStream, clip_turn

logger = logging.getLogger(__name__)

History = Union[str, List[Dict[str, Any]]]


class TierClient:
    def __init__(
        self,
        tier: TierConfig,
        manager: EngineManager,
        fault_injector: Optional[FaultInjector] = None,
    ):
        self.tier = tier
        self.name = tier.name
        self.server_manager = manager          # name matches reference surface
        self.faults = fault_injector
        self.last_result: Optional[GenerationResult] = None
        # Serializes the sequential engines once request timeouts can
        # abandon a still-running worker thread (engines without
        # ``concurrent_safe`` assume serialized callers); the batched
        # engine opts out via that attribute.
        self._engine_lock = threading.Lock()
        # Abandoned-worker accounting: while a timed-out worker is still
        # running (wedged chip), new sync requests on a serialized engine
        # would only queue behind it — fail them fast instead of growing
        # an unbounded daemon-thread backlog that drains serially on
        # recovery, each running a generation nobody reads.
        self._abandoned_lock = threading.Lock()
        self._abandoned = 0

    def process(self, history: History) -> Dict[str, Any]:
        """Run inference; error dicts mirror the reference client shapes.

        ``tier.request_timeout_s`` mirrors the reference clients' HTTP
        read timeout (src/models/nano.py:28, timeout=(5, 180)): the
        engine call runs in a worker thread, and past the cap this
        returns the reference error-dict shape — so Router failover and
        the perf strategy's failure penalty fire even though an
        in-process call on a wedged chip can never be cancelled.  The
        abandoned worker finishes (or hangs) in the background, exactly
        like the reference's Jetson finishing a response nobody waits
        for; its stale completion never overwrites ``last_result``.
        While an abandoned call is still outstanding on a serialized
        engine, new requests fail fast instead of spawning workers that
        would only queue behind the wedged call."""
        if self.faults is not None:
            fault = self.faults.intercept(self.name)
            if fault is not None:
                return fault

        timeout = self.tier.request_timeout_s
        if timeout is None:
            resp, result = self._process_body(history)
            if result is not None:
                self.last_result = result
            return resp
        if self._abandoned and not self._engine_concurrent_safe():
            logger.warning("tier %s has an abandoned timed-out call "
                           "outstanding — failing fast", self.name)
            return {"error": f"Request failed: {self.name} is busy with "
                             f"an abandoned timed-out request"}
        box: Dict[str, Any] = {}
        done = threading.Event()

        def work():
            resp: Dict[str, Any] = {"error": "Request failed: worker died"}
            result = None
            try:
                resp, result = self._process_body(history)
            finally:
                # Atomic with the caller's abandon decision: either
                # done is set HERE first (caller sees the result) or the
                # caller marked abandoned first (stale completion never
                # touches last_result).
                with self._abandoned_lock:
                    box["out"] = resp
                    done.set()
                    if box.get("abandoned"):
                        self._abandoned -= 1
                    elif result is not None:
                        self.last_result = result

        threading.Thread(target=work, daemon=True,
                         name=f"{self.name}-request").start()
        if not done.wait(timeout):
            with self._abandoned_lock:
                if not done.is_set():
                    box["abandoned"] = True
                    self._abandoned += 1
            if box.get("abandoned"):
                logger.warning("tier %s request exceeded %.0fs — abandoning "
                               "the device call and reporting failure",
                               self.name, timeout)
                return {"error": f"Request failed: {self.name} timed out "
                                 f"after {timeout:.0f}s"}
        return box.get("out", {"error": "Request failed: worker died"})

    def _engine_concurrent_safe(self) -> bool:
        """Best-effort concurrent_safe probe: abandoned workers only
        serialize engines that assume serialized callers."""
        try:
            if self.server_manager.is_server_running():
                return getattr(self.server_manager.engine(),
                               "concurrent_safe", False)
        except Exception:
            pass
        return False

    def _process_body(self, history: History
                      ) -> Tuple[Dict[str, Any], Optional[GenerationResult]]:
        """Returns (response dict, result or None).  The CALLER owns the
        last_result update — on the timeout path it must be atomic with
        the abandon decision, so it cannot live here."""
        try:
            if not self.server_manager.is_server_running():
                logger.info("No running %s engine found, starting...", self.name)
                self.server_manager.start_server()
            engine = self.server_manager.engine()
            if getattr(engine, "concurrent_safe", False):
                result = engine.generate(history)
            else:
                with self._engine_lock:
                    result = engine.generate(history)
        except Exception as exc:   # engine failure → reference error shape
            return {"error": f"Request failed: {exc}"}, None

        if result is None:
            # A stopped/abandoned request can complete with neither a
            # result nor an error (engine shut down mid-flight) — report
            # the reference error shape instead of crashing the worker.
            return {"error": f"Request failed: {self.name} engine "
                             f"returned no result"}, None
        # Single-turn semantic: the corpus-trained LM continues the
        # transcript past its own turn; the serving layer clips it
        # (serving/turns.py — the reference gets this from Ollama's
        # instruction-tuned models).
        return {"response": clip_turn(result.text)}, result

    def process_stream(self, history: History):
        """Streaming twin of ``process``: returns a primed stream handle,
        or the reference error-dict shape on any setup failure.  Fault
        injection applies exactly like the sync path, and the stream is
        PRIMED (first token pulled, i.e. prefill has run) before this
        returns — engine errors are lazy, surfacing at first iteration,
        so priming is what makes setup-time failover able to catch real
        engine failures, not just injected ones.

        No per-token timeout here (unlike ``process``): a stream is
        consumed incrementally by the caller, so there is no single
        bounded wait to cap — a wedged chip stalls the SSE consumer,
        which owns its own disconnect policy.  Sequential engines DO
        take the tier lock for the stream's whole life (released on
        exhaustion, close, or GC): a timeout-abandoned sync worker must
        not interleave with a stream on an engine that assumes
        serialized callers.  The lock ACQUIRE is bounded by
        ``request_timeout_s`` though: if an abandoned worker (wedged
        chip) or a stalled live stream holds it, this returns the
        reference error shape so Router stream failover and the perf
        failure penalty fire instead of the serving thread hanging
        forever before priming."""
        if self.faults is not None:
            fault = self.faults.intercept(self.name)
            if fault is not None:
                return fault
        try:
            if not self.server_manager.is_server_running():
                logger.info("No running %s engine found, starting...", self.name)
                self.server_manager.start_server()
            engine = self.server_manager.engine()
            if not hasattr(engine, "generate_stream"):
                return {"error": "Request failed: engine does not support "
                                 "token streaming"}
            if getattr(engine, "concurrent_safe", False):
                return _PrimedStream(
                    ClippedStream(engine.generate_stream(history)))
            timeout = self.tier.request_timeout_s
            acquired = (self._engine_lock.acquire(timeout=timeout)
                        if timeout is not None
                        else self._engine_lock.acquire())
            if not acquired:
                logger.warning("tier %s stream setup could not take the "
                               "engine lock within %.0fs — failing over",
                               self.name, timeout)
                return {"error": f"Request failed: {self.name} engine busy "
                                 f"after {timeout:.0f}s"}
            try:
                return _PrimedStream(
                    ClippedStream(engine.generate_stream(history)),
                    release=self._engine_lock.release)
            except BaseException:
                self._engine_lock.release()
                raise
        except Exception as exc:
            return {"error": f"Request failed: {exc}"}


class _PrimedStream:
    """A stream handle whose first delta has already been pulled (raising
    setup/prefill errors eagerly); iteration replays it then continues.

    ``release`` (the tier's engine-lock release) is invoked exactly once
    when the stream finishes — normal exhaustion, generator close (an
    SSE client disconnect closes the response generator chain), or GC of
    an unconsumed handle."""

    def __init__(self, handle, release=None):
        self._release_fn = release
        self._handle = handle
        self._it = iter(handle)
        self._first: Optional[str] = None
        self._exhausted = False
        try:
            self._first = next(self._it)
        except StopIteration:
            self._exhausted = True
        except BaseException:
            # Setup failure: the CALLER still holds (and releases) the
            # lock — neutralize ours so __del__ of this half-built
            # object can't double-release.
            self._release_fn = None
            raise

    def _release_once(self) -> None:
        fn, self._release_fn = self._release_fn, None
        if fn is not None:
            fn()

    def __iter__(self):
        try:
            if self._first is not None:
                yield self._first
            if not self._exhausted:
                yield from self._it
        finally:
            self._release_once()

    def __del__(self):
        self._release_once()

    @property
    def result(self):
        return self._handle.result


def build_tiers(
    cluster: ClusterConfig,
    devices: Optional[Sequence[jax.Device]] = None,
    fault_injector: Optional[FaultInjector] = None,
    warmup_on_start: bool = True,
) -> Dict[str, TierClient]:
    """Carve submeshes and wire a client per tier (registry, not classes).
    Tiers with an ``endpoint`` dispatch across hosts (serving/remote.py)
    instead of building a local engine."""
    meshes = carve_tier_meshes(cluster, devices=devices)
    tiers: Dict[str, TierClient] = {}
    for tier in cluster.tiers():
        if tier.endpoint:
            from .remote import RemoteTierClient
            tiers[tier.name] = RemoteTierClient(
                tier.name, tier.endpoint, fault_injector=fault_injector,
                spawn_cmd=tier.spawn_cmd)
            continue
        mesh = meshes[tier.name]
        # A 1-device mesh adds partitioning overhead for no benefit: pin to
        # the single device instead.
        if mesh.size == 1:
            manager = EngineManager(
                tier, devices=list(mesh.devices.flat), seed=cluster.seed,
                warmup_on_start=warmup_on_start)
        else:
            manager = EngineManager(
                tier, mesh=mesh, seed=cluster.seed,
                warmup_on_start=warmup_on_start)
        tiers[tier.name] = TierClient(tier, manager, fault_injector)
    return tiers
