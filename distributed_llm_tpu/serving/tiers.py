"""Tier clients — the device-client layer over in-process TPU engines.

Reference parity: src/models/nano.py / src/models/orin.py.  A TierClient has
the same surface (``.process(history)`` returning {"response": text} or an
error dict, plus ``.server_manager``) but dispatches to an InferenceEngine on
a chip submesh instead of POSTing through an SSH tunnel.  A registry replaces
the reference's two hard-coded classes, so tiers are config, not code.

Error-dict shapes match the reference client exactly (src/models/nano.py:
30-40) so Router failover and `_is_error` behave identically; faults come
from the injectable fault model (utils/faults.py) since there is no network
to fail naturally.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import jax

from ..config import ClusterConfig, TierConfig
from ..engine.inference import GenerationResult
from ..engine.manager import EngineManager
from ..obs import spans as obs_spans
from ..obs.spans import current_trace, use_trace
from ..parallel.mesh import carve_tier_meshes
from ..utils.faults import FaultInjector
from .tenants import DEFAULT_TENANT, TenantQuotas
from .turns import ClippedStream, clip_turn

logger = logging.getLogger(__name__)

History = Union[str, List[Dict[str, Any]]]

# Chars a fully-clipped stream may silently drain during _PrimedStream's
# eager first-delta pull before ClippedStream releases the primer with an
# empty delta: small enough that priming never stalls ~a whole
# generation, large enough that ordinary clipped turns finish their
# drain inside the prime.  WORST-CASE PRIME-DRAIN BOUND (ADVICE r5
# tiers.py:204): a stream whose model emits a role marker from token one
# drains at most THIS many characters — ≈ PRIME_DRAIN_CHARS / 3.5 ≈ 74
# BPE tokens of decoding (~3.5 chars/token on the bench sets) — inside
# ``process_stream`` while holding a sequential engine's lock, before
# the "" sentinel releases the primer; without the cap the same prime
# blocked for the full max_new_tokens decode budget (48-128 tokens on
# the shipped clusters, up to 256 on the dataclass default).  See
# ClippedStream (serving/turns.py) for the mechanism.
PRIME_DRAIN_CHARS = 256


class AdmissionController:
    """Bounded per-tier admission with predictive fail-fast.

    The concurrency story for a batched tier is no longer a lock queue:
    requests admit freely up to the engine's ``decode_batch`` slots, and
    beyond that a bounded waiting line.  A request is REJECTED (reference
    error shape, so Router failover and the perf fail penalty fire) when
    either

    - the waiting line is full (``tier.admission_max_queue`` requests
      already waiting beyond the slots), or
    - the EWMA of recent service times predicts this request would wait
      past ``tier.request_timeout_s`` anyway — failing in microseconds
      what would otherwise fail by timeout after blocking a thread for
      the full cap, or
    - (``tier.kv_admission``, batched tiers) the request's PROJECTED KV
      block demand — prompt bucket + decode budget — exceeds the paged
      pool's free blocks plus the blocks reclaimable by evicting parked
      prefixes: a fixed HBM block pool admits by blocks, not slots, and
      a request that must starve should fail over now (reference error
      shape + ``retry_after_s``) instead of queuing forever, or
    - the tier is DRAINING (graceful shutdown, EngineManager.drain):
      rejection with ``retry_after_s`` so clients retry elsewhere/later.

    Composes with the abandoned-worker accounting: an abandoned
    timed-out call keeps its admission slot until the worker really
    finishes (the engine genuinely is busy with it), so a wedged tier's
    predicted wait grows and new traffic sheds to the healthy tier.
    """

    def __init__(self, tier: TierConfig, slots: Optional[int] = None):
        self.tier = tier
        # ``slots`` = the engine's REAL concurrency when the caller
        # knows it differs from decode_batch (the speculative fallback
        # serves sequentially) — admission believing in concurrency the
        # engine doesn't have would admit N× what can be served.
        self.slots = max(1, slots if slots is not None
                         else tier.decode_batch)
        self.max_queue = tier.admission_max_queue
        self.timeout_s = tier.request_timeout_s
        self._lock = threading.Lock()
        self._inflight = 0
        self._ewma_s: Optional[float] = None
        self._alpha = 0.25                    # EWMA smoothing
        self.admitted = 0
        self.rejected = 0
        self.kv_rejected = 0
        # Graceful drain (EngineManager.drain): while set, every request
        # is rejected with the drain reason; retry_after_s carries the
        # drain deadline as the client's retry hint.
        self._draining = False
        self._drain_retry_after: Optional[float] = None

    def try_admit(self, kv_demand: Optional[int] = None,
                  kv_supply: Optional[int] = None) -> Optional[str]:
        """None = admitted (caller MUST release exactly once); else the
        human-readable rejection reason.  ``kv_demand``/``kv_supply``
        (projected blocks needed vs free + reclaimable, from the tier's
        paged engine) arm the KV-pressure gate; either None skips it."""
        with self._lock:
            if self._draining:
                self.rejected += 1
                return "draining (graceful shutdown in progress)"
            waiting = max(0, self._inflight - self.slots)
            # The line this request would JOIN: cap 0 means "slots only,
            # nobody waits", not "reject even with free slots".
            waiting_after = max(0, self._inflight + 1 - self.slots)
            enabled = self.max_queue is not None   # None = control off
            if enabled and waiting_after > self.max_queue:
                self.rejected += 1
                return (f"queue full ({waiting} waiting, "
                        f"cap {self.max_queue})")
            if enabled and self.timeout_s is not None and self._ewma_s:
                # Queue wait only (queue_depth × EWMA / slots): a slow
                # request with a free slot is the per-request timeout's
                # job; admission rejects what would spend its whole
                # budget WAITING.
                predicted = (waiting / self.slots) * self._ewma_s
                if predicted > self.timeout_s:
                    self.rejected += 1
                    return (f"predicted queue wait {predicted:.1f}s "
                            f"exceeds the {self.timeout_s:.0f}s request "
                            f"timeout (queue_depth={waiting}, "
                            f"ewma_service={self._ewma_s:.2f}s)")
            if (kv_demand is not None and kv_supply is not None
                    and self._inflight < self.slots
                    and kv_demand > kv_supply):
                # A slot is FREE but the block pool cannot serve the
                # request (starvation / constrained pool) — the anomaly
                # this gate exists for: the request would sit in the
                # engine queue invisible to the wait predictor.  Shed
                # now, while the Router can still fail over.  At full
                # slot occupancy the gate stands down: blocks free when
                # slots finish, and the bounded queue + EWMA predictor
                # already model that wait in time units (shedding there
                # would reject saturated-load requests that queue fine).
                self.rejected += 1
                self.kv_rejected += 1
                return (f"projected KV demand {kv_demand} blocks exceeds "
                        f"{kv_supply} free+reclaimable (pool pressure)")
            self._inflight += 1
            self.admitted += 1
            return None

    # -- drain (EngineManager.drain) ---------------------------------------

    def start_drain(self, retry_after_s: Optional[float] = None) -> None:
        with self._lock:
            self._draining = True
            self._drain_retry_after = retry_after_s

    def end_drain(self) -> None:
        with self._lock:
            self._draining = False
            self._drain_retry_after = None

    @property
    def draining(self) -> bool:
        return self._draining

    def retry_after_s(self) -> float:
        """Client retry hint for a rejection: the drain deadline while
        draining, else the EWMA service time (one slot finishing frees
        capacity/blocks), else a 1 s floor."""
        with self._lock:
            if self._draining and self._drain_retry_after:
                return round(float(self._drain_retry_after), 2)
            if self._ewma_s:
                return max(0.1, round(self._ewma_s, 2))
        return 1.0

    def release(self, service_s: Optional[float] = None) -> None:
        """End of an admitted request.  ``service_s`` (wall time the
        engine was actually occupied — including timed-out calls, which
        are exactly the slow evidence the EWMA exists to capture) feeds
        the service-time estimate; pass None for requests that never
        reached the engine (injected faults, setup failures)."""
        with self._lock:
            self._inflight = max(0, self._inflight - 1)
            if service_s is not None and service_s >= 0:
                self._ewma_s = (service_s if self._ewma_s is None
                                else (1 - self._alpha) * self._ewma_s
                                + self._alpha * service_s)

    # -- observability -----------------------------------------------------

    def queue_depth(self) -> int:
        with self._lock:
            return max(0, self._inflight - self.slots)

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            waiting = max(0, self._inflight - self.slots)
            return {
                "inflight": self._inflight,
                "queue_depth": waiting,
                "slots": self.slots,
                "max_queue": self.max_queue,
                "ewma_service_ms": (round(self._ewma_s * 1000.0, 2)
                                    if self._ewma_s is not None else None),
                "admitted": self.admitted,
                "rejected": self.rejected,
                "kv_rejected": self.kv_rejected,
                "draining": self._draining,
            }


class TierClient:
    def __init__(
        self,
        tier: TierConfig,
        manager: EngineManager,
        fault_injector: Optional[FaultInjector] = None,
    ):
        self.tier = tier
        self.name = tier.name
        self.server_manager = manager          # name matches reference surface
        self.faults = fault_injector
        self.last_result: Optional[GenerationResult] = None
        # Bounded admission replaces lock-serialization as the
        # concurrency story; registered on the manager so health()
        # snapshots expose queue depth next to slot occupancy.
        # Slot count mirrors EngineManager's engine choice.  A draft
        # with decode_batch>1 serves the BATCHED speculative path
        # (ISSUE 15 retired the PR 1 sequential fallback), so admission
        # believes in the real decode_batch slots; the only engine that
        # serves one stream — the sequential SpeculativeEngine — is
        # selected exactly when decode_batch<=1, where max(1, ...) is
        # already 1.
        slots = max(1, tier.decode_batch)
        self.admission = AdmissionController(tier, slots=slots)
        # Per-tenant quota layer (ISSUE 17) — constructed ONLY when the
        # tier opts in; ``tenant_quotas=None`` keeps every request on
        # the exact pre-tenant code path (byte-identity contract).
        self.tenants: Optional[TenantQuotas] = (
            TenantQuotas(tier) if tier.tenant_quotas is not None else None)
        try:
            manager.admission = self.admission
        except Exception:
            pass                               # stub managers in tests
        # Serializes the sequential engines once request timeouts can
        # abandon a still-running worker thread (engines without
        # ``concurrent_safe`` assume serialized callers); the batched
        # engine opts out via that attribute.
        self._engine_lock = threading.Lock()
        # Abandoned-worker accounting: while a timed-out worker is still
        # running (wedged chip), new sync requests on a serialized engine
        # would only queue behind it — fail them fast instead of growing
        # an unbounded daemon-thread backlog that drains serially on
        # recovery, each running a generation nobody reads.
        self._abandoned_lock = threading.Lock()
        self._abandoned = 0

    def process(self, history: History) -> Dict[str, Any]:
        """Run inference; error dicts mirror the reference client shapes.

        ``tier.request_timeout_s`` mirrors the reference clients' HTTP
        read timeout (src/models/nano.py:28, timeout=(5, 180)): the
        engine call runs in a worker thread, and past the cap this
        returns the reference error-dict shape — so Router failover and
        the perf strategy's failure penalty fire even though an
        in-process call on a wedged chip can never be cancelled.  The
        abandoned worker finishes (or hangs) in the background, exactly
        like the reference's Jetson finishing a response nobody waits
        for; its stale completion never overwrites ``last_result``.
        While an abandoned call is still outstanding on a serialized
        engine, new requests fail fast instead of spawning workers that
        would only queue behind the wedged call.

        Admission control runs FIRST (before fault injection, so a
        rejected request cannot consume a one-shot injected fault): a
        full waiting line or a predicted wait past the timeout returns
        the reference error shape in microseconds instead of blocking a
        serving thread for the full cap (AdmissionController)."""
        trace = current_trace()
        tenant = self._tenant_of(trace)
        # Tenant quota gate runs BEFORE the tier controller: a shed
        # over-quota tenant never consumes tier admission state (queue
        # slot, EWMA evidence, KV gate work) — the isolation property
        # the noisy-neighbor bench pins.  No-op when quotas are off.
        tenant_err = self._tenant_try_admit(trace, tenant)
        if tenant_err is not None:
            logger.warning("tier %s tenant quota rejected a request: %s",
                           self.name, tenant_err)
            return self._admission_error(tenant_err, tenant=tenant)

        def release_tenant():
            if self.tenants is not None:
                self.tenants.release(tenant)

        kv_demand, kv_supply = self._kv_admission_args(history)
        with obs_spans.span(trace, "admission", tier=self.name) as adm_sp:
            admit_err = self.admission.try_admit(kv_demand, kv_supply)
            if admit_err is not None:
                adm_sp.annotate(rejected=admit_err)
        if admit_err is not None:
            release_tenant()
            logger.warning("tier %s admission rejected a request: %s",
                           self.name, admit_err)
            return self._admission_error(admit_err)
        if self.faults is not None:
            fault = self.faults.intercept(self.name)
            if fault is not None:
                self.admission.release()     # never reached the engine
                release_tenant()
                return fault

        timeout = self.tier.request_timeout_s
        if timeout is None:
            t0 = time.perf_counter()
            try:
                resp, result = self._process_body(history)
            finally:
                self.admission.release(time.perf_counter() - t0)
                release_tenant()
            if result is not None:
                # Same lock as the timeout path's worker: last_result is
                # read/written cross-thread once timeouts can abandon
                # workers, so every rebind goes through _abandoned_lock
                # (the lock-mixed-guard lint pins this discipline).
                with self._abandoned_lock:
                    self.last_result = result
            return resp
        with self._abandoned_lock:
            abandoned_outstanding = self._abandoned
        if abandoned_outstanding and not self._engine_concurrent_safe():
            self.admission.release()
            release_tenant()
            logger.warning("tier %s has an abandoned timed-out call "
                           "outstanding — failing fast", self.name)
            return {"error": f"Request failed: {self.name} is busy with "
                             f"an abandoned timed-out request"}
        box: Dict[str, Any] = {}
        done = threading.Event()

        def work():
            resp: Dict[str, Any] = {"error": "Request failed: worker died"}
            result = None
            t0 = time.perf_counter()
            try:
                # Context vars don't cross thread spawns: re-bind the
                # request's trace so the engine's spans/timeline attach
                # to the right tree (obs/spans.py propagation contract).
                with use_trace(trace):
                    resp, result = self._process_body(history)
            finally:
                # Atomic with the caller's abandon decision: either
                # done is set HERE first (caller sees the result) or the
                # caller marked abandoned first (stale completion never
                # touches last_result).
                with self._abandoned_lock:
                    box["out"] = resp
                    done.set()
                    if box.get("abandoned"):
                        self._abandoned -= 1
                    elif result is not None:
                        self.last_result = result
                # The admission slot is held for the worker's whole
                # life — an abandoned worker still occupies the engine,
                # and its true duration is exactly the slow evidence
                # the EWMA should see.  Same lifetime for the tenant
                # quota slot: an abandoned worker still burns the
                # tenant's share of the engine.
                self.admission.release(time.perf_counter() - t0)
                release_tenant()

        threading.Thread(target=work, daemon=True,
                         name=f"{self.name}-request").start()
        if not done.wait(timeout):
            with self._abandoned_lock:
                if not done.is_set():
                    box["abandoned"] = True
                    self._abandoned += 1
            if box.get("abandoned"):
                logger.warning("tier %s request exceeded %.0fs — abandoning "
                               "the device call and reporting failure",
                               self.name, timeout)
                obs_spans.event(trace, "timeout_abandoned", tier=self.name,
                                timeout_s=timeout)
                return {"error": f"Request failed: {self.name} timed out "
                                 f"after {timeout:.0f}s"}
        return box.get("out", {"error": "Request failed: worker died"})

    def _kv_admission_args(self, history: History):
        """(projected block demand, available block supply) for the KV
        admission gate, or (None, None) when it doesn't apply: gate off,
        engine not running, or not a paged engine.  Peeks the live engine
        without lazy-starting it — a stopped tier's pool has no pressure
        to gate on."""
        if not self.tier.kv_admission:
            return None, None
        engine = getattr(self.server_manager, "_engine", None)
        demand_fn = getattr(engine, "projected_demand_blocks", None)
        stats_fn = getattr(engine, "kv_stats", None)
        if not (callable(demand_fn) and callable(stats_fn)):
            return None, None
        try:
            st = stats_fn()
            # reclaimable_blocks is pin- and refcount-aware (ISSUE 10):
            # parked entries with live sharers, and parked blocks whose
            # eviction would only drop one of several references, are
            # already excluded by the engine's PrefixCache — the gate
            # never promises supply that sharing has pinned.
            supply = (int(st["free_blocks"])
                      + int(st["reclaimable_blocks"])
                      # The in-flight chunked prefill's remaining block
                      # demand is spoken for: the allocator still counts
                      # those blocks free, but an admission that took
                      # them would force the scheduler to cancel the
                      # half-absorbed prompt (engine/batching.py
                      # kv_stats).
                      - int(st.get("prefill_pending_blocks", 0)))
            worst = getattr(engine, "max_demand_blocks", None)
            if callable(worst) and supply >= int(worst()):
                # Pool trivially covers ANY request: skip the per-request
                # prompt tokenization (the gate cannot fire) — the hot
                # path only pays the precise estimate under pressure.
                return None, None
            return int(demand_fn(history)), supply
        except Exception:
            return None, None               # estimation must never reject

    def _admission_error(self, admit_err: str,
                         tenant: Optional[str] = None) -> Dict[str, Any]:
        """Reference error shape for an admission rejection.  Drain and
        KV-pressure rejections carry the sanctioned ``retry_after_s``
        hint (serving/errors.py): both are transient-by-design states a
        client should retry past, unlike a full waiting line where
        failover is the productive move.  Tenant-quota rejections
        (ISSUE 17) always carry the hint, computed from the TENANT's
        own budget (token-bucket time-to-positive) rather than the
        tier EWMA — the tier may be idle while this tenant is shed."""
        from .errors import error_dict
        msg = (f"Request failed: {self.name} admission rejected: "
               f"{admit_err}")
        if (tenant is not None and self.tenants is not None
                and "tenant '" in admit_err):
            return error_dict(
                msg, retry_after_s=self.tenants.retry_after_s(tenant))
        if "draining" in admit_err or "KV demand" in admit_err:
            return error_dict(msg,
                              retry_after_s=self.admission.retry_after_s())
        return {"error": msg}

    def _tenant_of(self, trace) -> str:
        """The request's tenant identity, annotated onto the trace by
        the Router (serving/app.py validated it at the edge); requests
        arriving without one — direct TierClient callers, tests —
        bill to the shared default tenant."""
        try:
            t = trace.attrs.get("tenant") if trace is not None else None
        except Exception:
            t = None
        return t if isinstance(t, str) and t else DEFAULT_TENANT

    def _tenant_try_admit(self, trace, tenant: str) -> Optional[str]:
        """Quota-layer admission (None when quotas are off or the
        tenant is in budget; else the rejection reason).  The KV bill
        fed to the per-tenant block budget is the tenant's LIVE
        resident bill at 1/refcount from the engine — dedup lowers it,
        so a tenant whose prompts share prefixes is billed for its
        marginal footprint, not its nominal one."""
        if self.tenants is None:
            return None
        kv_bill = None
        if self.tenants.kv_budget(tenant) is not None:
            engine = getattr(self.server_manager, "_engine", None)
            bill_fn = getattr(engine, "tenant_kv_blocks", None)
            if callable(bill_fn):
                try:
                    kv_bill = bill_fn(tenant)
                except Exception:
                    kv_bill = None       # billing must never reject
        with obs_spans.span(trace, "tenant_admission", tier=self.name,
                            tenant=tenant) as t_sp:
            tenant_err = self.tenants.try_admit(tenant, kv_bill)
            if tenant_err is not None:
                t_sp.annotate(rejected=tenant_err)
        return tenant_err

    def _maybe_break_stream(self, handle):
        """Apply a scripted mid-stream kill (FaultInjector.
        fail_stream_after): the returned stream dies after N chunks —
        the wedge-after-first-token failure mode the Router's mid-stream
        failover exists for.  No kill scheduled → the handle unchanged."""
        from ..utils.faults import maybe_break_stream
        return maybe_break_stream(self.faults, self.name, handle)

    def _engine_concurrent_safe(self) -> bool:
        """Best-effort concurrent_safe probe: abandoned workers only
        serialize engines that assume serialized callers."""
        try:
            if self.server_manager.is_server_running():
                return getattr(self.server_manager.engine(),
                               "concurrent_safe", False)
        except Exception:
            pass
        return False

    def _process_body(self, history: History
                      ) -> Tuple[Dict[str, Any], Optional[GenerationResult]]:
        """Returns (response dict, result or None).  The CALLER owns the
        last_result update — on the timeout path it must be atomic with
        the abandon decision, so it cannot live here."""
        try:
            if not self.server_manager.is_server_running():
                logger.info("No running %s engine found, starting...", self.name)
                with obs_spans.span(current_trace(), "engine_start",
                                    tier=self.name):
                    self.server_manager.start_server()
            engine = self.server_manager.engine()
            if getattr(engine, "concurrent_safe", False):
                result = engine.generate(history)
            else:
                with self._engine_lock:
                    result = engine.generate(history)  # dllm-lint: disable=lock-blocking-call -- the engine lock IS the queue: sequential engines require serialized callers, and admission + request_timeout_s bound the wait
        except Exception as exc:   # engine failure → reference error shape
            # Engine-stopped failures (shutdown/drain deadline) carry the
            # schema-validated shape already — forward it verbatim.
            shape = getattr(exc, "shape", None)
            if isinstance(shape, dict) and "error" in shape:
                return dict(shape), None
            return {"error": f"Request failed: {exc}"}, None

        if result is None:
            # A stopped/abandoned request can complete with neither a
            # result nor an error (engine shut down mid-flight) — report
            # the reference error shape instead of crashing the worker.
            return {"error": f"Request failed: {self.name} engine "
                             f"returned no result"}, None
        # Single-turn semantic: the corpus-trained LM continues the
        # transcript past its own turn; the serving layer clips it
        # (serving/turns.py — the reference gets this from Ollama's
        # instruction-tuned models).  Per-request timing rides in the
        # raw dict (additive keys; _extract_text/_is_error only read
        # "response"/"error"): under concurrent clients the shared
        # ``last_result`` can belong to another request, so this is the
        # only race-free per-request TTFT a caller can observe.
        resp: Dict[str, Any] = {"response": clip_turn(result.text)}
        for key in ("ttft_ms", "total_ms", "gen_tokens"):
            val = getattr(result, key, None)   # stub results may omit these
            if val is not None:
                resp[key] = round(val, 3) if isinstance(val, float) else val
        return resp, result

    def process_stream(self, history: History):
        """Streaming twin of ``process``: returns a primed stream handle,
        or the reference error-dict shape on any setup failure.  Fault
        injection applies exactly like the sync path, and the stream is
        PRIMED (first token pulled, i.e. prefill has run) before this
        returns — engine errors are lazy, surfacing at first iteration,
        so priming is what makes setup-time failover able to catch real
        engine failures, not just injected ones.

        No per-token timeout here (unlike ``process``): a stream is
        consumed incrementally by the caller, so there is no single
        bounded wait to cap — a wedged chip stalls the SSE consumer,
        which owns its own disconnect policy.  Sequential engines DO
        take the tier lock for the stream's whole life (released on
        exhaustion, close, or GC): a timeout-abandoned sync worker must
        not interleave with a stream on an engine that assumes
        serialized callers.  The lock ACQUIRE is bounded by
        ``request_timeout_s`` though: if an abandoned worker (wedged
        chip) or a stalled live stream holds it, this returns the
        reference error shape so Router stream failover and the perf
        failure penalty fire instead of the serving thread hanging
        forever before priming.

        Streams occupy engine capacity like sync requests, so admission
        control gates them the same way; the admission slot is released
        exactly once when the stream finishes (exhaustion, close, or GC
        of an unconsumed handle).  Holding the slot until the CONSUMER
        drains is deliberate backpressure — slow SSE clients bound how
        many streams a tier buffers — but the EWMA service time uses the
        ENGINE-TRUE generation time from the final result when available
        (wall drain time is dominated by client read pace, and feeding
        it to the EWMA would let slow readers poison the predictive
        fail-fast against an idle engine)."""
        trace = current_trace()
        tenant = self._tenant_of(trace)
        tenant_err = self._tenant_try_admit(trace, tenant)
        if tenant_err is not None:
            logger.warning("tier %s tenant quota rejected a stream: %s",
                           self.name, tenant_err)
            return self._admission_error(tenant_err, tenant=tenant)

        def release_tenant():
            if self.tenants is not None:
                self.tenants.release(tenant)

        kv_demand, kv_supply = self._kv_admission_args(history)
        with obs_spans.span(trace, "admission", tier=self.name) as adm_sp:
            admit_err = self.admission.try_admit(kv_demand, kv_supply)
            if admit_err is not None:
                adm_sp.annotate(rejected=admit_err)
        if admit_err is not None:
            release_tenant()
            logger.warning("tier %s admission rejected a stream: %s",
                           self.name, admit_err)
            return self._admission_error(admit_err)
        t0 = time.perf_counter()
        handle_box: Dict[str, Any] = {}

        def finish_admission():
            result = getattr(handle_box.get("handle"), "result", None)
            engine_ms = getattr(result, "total_ms", 0) if result else 0
            self.admission.release(engine_ms / 1000.0 if engine_ms
                                   else time.perf_counter() - t0)
            release_tenant()

        try:
            if self.faults is not None:
                fault = self.faults.intercept(self.name)
                if fault is not None:
                    self.admission.release()   # never reached the engine
                    release_tenant()
                    return fault
            if not self.server_manager.is_server_running():
                logger.info("No running %s engine found, starting...", self.name)
                with obs_spans.span(trace, "engine_start", tier=self.name):
                    self.server_manager.start_server()
            engine = self.server_manager.engine()
            if not hasattr(engine, "generate_stream"):
                self.admission.release()
                release_tenant()
                return {"error": "Request failed: engine does not support "
                                 "token streaming"}
            if getattr(engine, "concurrent_safe", False):
                clipped = ClippedStream(
                    engine.generate_stream(history),
                    prime_drain_chars=PRIME_DRAIN_CHARS)
                handle_box["handle"] = clipped
                return _PrimedStream(self._maybe_break_stream(clipped),
                                     release=finish_admission)
            timeout = self.tier.request_timeout_s
            # A sequential engine's lock IS its queue: the wait here is
            # the streaming twin of the batching engine's queue_wait.
            with obs_spans.span(trace, "engine_lock_wait", tier=self.name):
                # timeout=-1 is threading's own "block forever" sentinel,
                # so the two branches collapse to ONE acquire site.
                # dllm-lint: disable=thread-acquire-leak -- the STREAM owns this lock past the frame: release_all/_PrimedStream release it on exhaustion/close/GC, and the except-BaseException below releases on setup failure — a try/finally here would release while the stream is still decoding
                acquired = self._engine_lock.acquire(
                    timeout=timeout if timeout is not None else -1)
            if not acquired:
                self.admission.release()
                release_tenant()
                logger.warning("tier %s stream setup could not take the "
                               "engine lock within %.0fs — failing over",
                               self.name, timeout)
                return {"error": f"Request failed: {self.name} engine busy "
                                 f"after {timeout:.0f}s"}

            def release_all():
                self._engine_lock.release()
                finish_admission()

            try:
                clipped = ClippedStream(
                    engine.generate_stream(history),  # dllm-lint: disable=lock-blocking-call -- a sequential engine's stream must hold the engine lock for its whole life (released by _PrimedStream on exhaustion/close/GC); the acquire above is bounded by request_timeout_s
                    prime_drain_chars=PRIME_DRAIN_CHARS)
                handle_box["handle"] = clipped
                return _PrimedStream(self._maybe_break_stream(clipped),
                                     release=release_all)
            except BaseException:
                self._engine_lock.release()
                raise
        except Exception as exc:
            self.admission.release()
            release_tenant()
            shape = getattr(exc, "shape", None)
            if isinstance(shape, dict) and "error" in shape:
                return dict(shape)         # engine-stopped: exact shape
            return {"error": f"Request failed: {exc}"}

    def load_snapshot(self) -> Dict[str, Any]:
        """Live load signal for queue-aware perf routing: requests
        waiting beyond the engine's concurrent slots, plus slot
        occupancy.  Never starts an engine (a stopped tier reads idle);
        cheap in-memory counters only."""
        adm = self.admission.snapshot()
        out = {"queue_depth": adm["queue_depth"],
               "active_slots": min(adm["inflight"], adm["slots"]),
               "max_slots": adm["slots"]}
        engine = getattr(self.server_manager, "_engine", None)
        slots = getattr(engine, "slot_stats", None)
        if callable(slots):
            try:
                st = slots()
                # The scheduler's view is sharper than admission's: its
                # queue counts submitted-not-admitted requests.
                out["queue_depth"] = max(out["queue_depth"],
                                         st["queue_depth"])
                out["active_slots"] = st["active_slots"]
                out["max_slots"] = st["max_slots"]
            except Exception:
                pass
        return out


class _PrimedStream:
    """A stream handle whose first delta has already been pulled (raising
    setup/prefill errors eagerly); iteration replays it then continues.

    ``release`` (the tier's engine-lock release) is invoked exactly once
    when the stream finishes — normal exhaustion, generator close (an
    SSE client disconnect closes the response generator chain), or GC of
    an unconsumed handle."""

    def __init__(self, handle, release=None):
        self._release_fn = release
        self._handle = handle
        self._it = iter(handle)
        self._first: Optional[str] = None
        self._exhausted = False
        try:
            self._first = next(self._it)
            if self._first == "":
                # ClippedStream's prime-release sentinel (a fully-
                # clipped stream capping its silent drain): the prime
                # succeeded, but there is no real first delta to replay.
                self._first = None
        except StopIteration:
            self._exhausted = True
        except BaseException:
            # Setup failure: the CALLER still holds (and releases) the
            # lock — neutralize ours so __del__ of this half-built
            # object can't double-release.
            self._release_fn = None
            raise

    def _release_once(self) -> None:
        fn, self._release_fn = self._release_fn, None
        if fn is not None:
            fn()

    def __iter__(self):
        try:
            if self._first is not None:
                yield self._first
            if not self._exhausted:
                yield from self._it
        finally:
            self._release_once()

    def __del__(self):
        self._release_once()

    @property
    def result(self):
        return self._handle.result


def build_tiers(
    cluster: ClusterConfig,
    devices: Optional[Sequence[jax.Device]] = None,
    fault_injector: Optional[FaultInjector] = None,
    warmup_on_start: bool = True,
) -> Dict[str, TierClient]:
    """Carve submeshes and wire a client per tier (registry, not classes).
    Tiers with an ``endpoint`` dispatch across hosts (serving/remote.py)
    instead of building a local engine."""
    meshes = carve_tier_meshes(cluster, devices=devices)
    tiers: Dict[str, TierClient] = {}
    for tier in cluster.tiers():
        if tier.endpoint:
            from .remote import RemoteTierClient
            tiers[tier.name] = RemoteTierClient(
                tier.name, tier.endpoint, fault_injector=fault_injector,
                spawn_cmd=tier.spawn_cmd)
            continue
        mesh = meshes[tier.name]
        if tier.replicas > 1 or tier.autoscale:
            # Replicated tier (ISSUE 12, serving/replicas.py): N engine
            # replicas behind one tier client with prefix-affinity
            # dispatch.  An autoscale-armed tier takes this path even
            # at replicas=1 — elastic membership (ISSUE 18) needs the
            # replica layer to actuate, and min may be 1.  Plain
            # replicas=1 WITHOUT autoscale never takes it — the
            # TierClient below stays byte-identical to pre-replica
            # behavior.
            from .replicas import ReplicatedTierClient
            tiers[tier.name] = ReplicatedTierClient(
                tier, cluster, mesh=mesh, fault_injector=fault_injector,
                warmup_on_start=warmup_on_start, seed=cluster.seed)
            continue
        # A 1-device mesh adds partitioning overhead for no benefit: pin to
        # the single device instead.
        if mesh.size == 1:
            manager = EngineManager(
                tier, devices=list(mesh.devices.flat), seed=cluster.seed,
                warmup_on_start=warmup_on_start)
        else:
            manager = EngineManager(
                tier, mesh=mesh, seed=cluster.seed,
                warmup_on_start=warmup_on_start)
        tiers[tier.name] = TierClient(tier, manager, fault_injector)
    return tiers
