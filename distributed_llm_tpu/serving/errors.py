"""The reference error-dict shape — single schema constant.

The reference clients (src/models/nano.py:30-40) report every failure as
``{"error": "<message>"}``; Router failover, ``_is_error``, the perf
strategy's failure penalty, the circuit breaker, and the benchmark
harness's parity with routing_chatbot_tester.py all key off exactly that
shape.  PR 2 added one sanctioned extension: ``retry_after_s`` (numeric)
on the degraded fail-fast path.

This module is the one place the shape is defined.  Producers either
call ``error_dict`` or write a literal that the ``error-shape`` lint
checker (distributed_llm_tpu/lint) validates against these constants —
so src/app.py parity can't silently drift.  Stdlib-only: the lint CLI
imports it without pulling jax.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

# The discriminating key: a dict is error-shaped iff it carries it.
ERROR_KEY = "error"

# Every key an error dict may carry.  ``retry_after_s`` is the degraded
# fail-fast hint (serving/router.py); anything else is drift.
ALLOWED_KEYS = frozenset({ERROR_KEY, "retry_after_s"})

# Keys with a typing contract the checker enforces on literals.
NUMERIC_KEYS = frozenset({"retry_after_s"})


def error_dict(message: str,
               retry_after_s: Optional[float] = None) -> Dict[str, Any]:
    """Construct a conforming reference error dict."""
    out: Dict[str, Any] = {ERROR_KEY: message}
    if retry_after_s is not None:
        out["retry_after_s"] = round(float(retry_after_s), 2)
    return out


def is_error_shape(raw: Any) -> bool:
    """The reference ``_is_error`` predicate (src/router.py:277-282):
    any dict carrying the error key."""
    return isinstance(raw, dict) and ERROR_KEY in raw
