"""SLO-driven replica autoscaler — the elastic-capacity control plane.

ROADMAP item 5's closing arc: PR 12 gave tiers N replicas, PR 7 gave
them goodput/SLO windows, PR 5 gave them graceful drain, and PR 13 gave
drained KV a place to survive — but capacity was still a static config
while traffic is not.  This module closes the loop: a per-tier
``ReplicaAutoscaler`` control thread reads the signals the system
ALREADY emits and actuates membership through
``ReplicatedTierClient.scale_to`` (serving/replicas.py), making
goodput-per-replica-second the economic headline the bench's elastic
leg measures (the serving-cost framing the Gemma-on-TPU comparison in
PAPERS.md judges TPU deployments by).

Signal taxonomy — nothing here is a new measurement; the controller is
a pure READER of existing surfaces:

- **SLO goodput** (obs/slo.py ``SLOMonitor.goodput(tier=...)``): the
  windowed fraction of requests meeting their TTFT/TPOT targets, fed
  only by real request outcomes in ``Router._finish_request``.  Below
  ``autoscale_goodput_floor`` = the tier is failing users.
- **Queue growth** (the tier's summed ``load_snapshot``): queue depth
  above ``autoscale_queue_high × live replicas`` = backlog is growing
  faster than service drains it — the leading indicator that fires
  BEFORE goodput collapses (goodput is a trailing window).
- **Admission shed rate** (each replica's admission-controller
  ``rejected`` counter deltas): sheds mean the bounded queue overflowed —
  capacity is short NOW, whatever the goodput window still says.

Decision rules (hysteresis + per-direction cooldowns so the loop never
flaps):

- **Scale UP** when any breach signal has been CONTINUOUSLY true for
  ``autoscale_breach_window_s`` (one-sample spikes don't actuate), the
  last membership event is at least ``autoscale_up_cooldown_s`` old,
  and membership is below ``autoscale_max_replicas``.  The new replica
  warms off-membership (deferred go-live riding replica 0's XLA
  compile cache), so dispatch never blocks on a cold start.
- **Scale DOWN** when the tier has been CONTINUOUSLY idle (no queue,
  no active slots, no sheds, goodput at/above floor + margin) for
  ``autoscale_idle_window_s``, the last event is at least
  ``autoscale_down_cooldown_s`` old, and membership is above
  ``autoscale_min_replicas``.  The idle window and down cooldown are
  deliberately longer than their up twins: adding capacity late costs
  SLO, removing it late only costs replica-seconds.  Scale-down drains
  through the PR 13 spill tier — the retiring replica's refcount-1
  parked prefixes demote to host RAM and hand off to a survivor, so
  the shrink costs warm TTFT at most, never correctness.

Every transition appends a signal snapshot to a bounded decision ledger
(``GET /stats`` surfaces it next to the breaker/SLO blocks) and bumps
``dllm_autoscale_events_total{tier,direction,reason}``; membership
itself is the ``dllm_replica_count{tier}`` gauge (sampled).

The controller thread follows the sampler's lifecycle discipline
(obs/sampler.py): daemon, named, stop() sets the event and joins
bounded — the Router starts it per armed tier and stops it in drain().
``DLLM_AUTOSCALE=0`` (or ``TierConfig.autoscale=False``, the default)
means no controller exists at all: the static PR 12 membership path
stays byte-identical (pinned by test).
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

logger = logging.getLogger(__name__)

# Scale-down hysteresis margin over the goodput floor: idle requires
# goodput at/above floor + margin (when a window exists), mirroring the
# SLO monitor's own recover-margin asymmetry — the bar to shed capacity
# is higher than the bar that added it.
IDLE_GOODPUT_MARGIN = 0.1

# Bounded decision ledger (GET /stats): enough history to read a whole
# diurnal cycle's transitions without growing with uptime.
LEDGER_CAP = 32


class ReplicaAutoscaler:
    """One tier's control loop: signals in, ``scale_to`` out."""

    def __init__(self, name: str, tier_cfg, client, slo,
                 metrics=None, clock=time.monotonic):
        """``client`` is the tier's ReplicatedTierClient (must expose
        ``scale_to``/``replica_count``/``load_snapshot``/``clients``);
        ``slo`` the router's SLOMonitor; ``clock`` injectable for
        deterministic tests (drive ``tick()`` directly — no thread
        needed)."""
        self.name = name
        self.tier = tier_cfg
        self.client = client
        self.slo = slo
        self._metrics = metrics
        self._clock = clock
        g = lambda f, d: getattr(tier_cfg, f, d)
        self.interval_s = max(0.05, float(g("autoscale_interval_s", 1.0)))
        self.min_replicas = max(1, int(g("autoscale_min_replicas", 1)))
        self.max_replicas = max(self.min_replicas,
                                int(g("autoscale_max_replicas", 4)))
        self.goodput_floor = float(g("autoscale_goodput_floor", 0.5))
        self.queue_high = float(g("autoscale_queue_high", 2.0))
        self.breach_window_s = float(g("autoscale_breach_window_s", 3.0))
        self.idle_window_s = float(g("autoscale_idle_window_s", 10.0))
        self.up_cooldown_s = float(g("autoscale_up_cooldown_s", 5.0))
        self.down_cooldown_s = float(g("autoscale_down_cooldown_s", 15.0))
        self._stop_evt = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        self.ledger: "deque[Dict[str, Any]]" = deque(maxlen=LEDGER_CAP)
        self.events_total = {"up": 0, "down": 0}
        # Streak state: when did the current breach/idle stretch start
        # (None = not currently breaching/idle).
        self._breach_since: Optional[float] = None
        self._idle_since: Optional[float] = None
        self._last_event: Optional[float] = None
        self._last_shed_total: Optional[int] = None
        self._last_signals: Dict[str, Any] = {}

    # -- signals ------------------------------------------------------------

    def _shed_total(self) -> int:
        """Summed admission rejections over the live replicas (lifetime
        counters; the controller differences consecutive reads)."""
        total = 0
        for c in list(getattr(self.client, "clients", ())):
            try:
                snap = c.admission.snapshot()
                total += int(snap.get("rejected", 0) or 0)
            except Exception:
                continue
        return total

    def read_signals(self) -> Dict[str, Any]:
        """One snapshot of every decision input (also the ledger's
        per-transition record)."""
        try:
            n = int(self.client.replica_count())
        except Exception:
            n = len(list(getattr(self.client, "clients", ()))) or 1
        try:
            load = self.client.load_snapshot()
        except Exception:
            load = {}
        goodput = None
        try:
            goodput = self.slo.goodput(tier=self.name)
        except Exception:
            pass
        shed_total = self._shed_total()
        last = self._last_shed_total
        self._last_shed_total = shed_total
        return {
            "replicas": n,
            "goodput": (round(goodput, 4)
                        if goodput is not None else None),
            "queue_depth": int(load.get("queue_depth", 0) or 0),
            "active_slots": int(load.get("active_slots", 0) or 0),
            "shed_delta": (max(0, shed_total - last)
                           if last is not None else 0),
        }

    # -- decision -----------------------------------------------------------

    def _breach_reason(self, sig: Dict[str, Any]) -> Optional[str]:
        if sig["shed_delta"] > 0:
            return "shed"
        if (sig["goodput"] is not None
                and sig["goodput"] < self.goodput_floor):
            return "goodput_floor"
        if sig["queue_depth"] > self.queue_high * max(1, sig["replicas"]):
            return "queue_growth"
        return None

    def _is_idle(self, sig: Dict[str, Any]) -> bool:
        if sig["queue_depth"] or sig["active_slots"] or sig["shed_delta"]:
            return False
        return (sig["goodput"] is None
                or sig["goodput"] >= self.goodput_floor
                + IDLE_GOODPUT_MARGIN)

    def tick(self) -> Optional[str]:
        """One control decision: read signals, advance the streaks,
        maybe actuate.  Public so tests drive the controller
        deterministically with an injected clock — the thread just
        calls this at cadence.  Returns 'up'/'down' when membership
        changed, else None."""
        now = self._clock()
        sig = self.read_signals()
        self._last_signals = sig
        n = sig["replicas"]
        reason = self._breach_reason(sig)
        if reason is not None:
            if self._breach_since is None:
                self._breach_since = now
        else:
            self._breach_since = None
        if self._is_idle(sig):
            if self._idle_since is None:
                self._idle_since = now
        else:
            self._idle_since = None

        cooldown_ok_up = (self._last_event is None
                          or now - self._last_event >= self.up_cooldown_s)
        cooldown_ok_down = (self._last_event is None
                            or now - self._last_event
                            >= self.down_cooldown_s)
        if (reason is not None and n < self.max_replicas
                and self._breach_since is not None
                and now - self._breach_since >= self.breach_window_s
                and cooldown_ok_up):
            return self._actuate(n + 1, "up", reason, sig, now)
        if (n > self.min_replicas
                and self._idle_since is not None
                and now - self._idle_since >= self.idle_window_s
                and cooldown_ok_down):
            return self._actuate(n - 1, "down", "idle", sig, now)
        return None

    def _actuate(self, target: int, direction: str, reason: str,
                 sig: Dict[str, Any], now: float) -> Optional[str]:
        try:
            result = self.client.scale_to(target, reason=reason)
        except Exception:
            logger.exception("autoscaler %s: scale_to(%d) failed",
                             self.name, target)
            result = {"errors": ["scale_to raised"]}
        changed = (result.get("added") or result.get("removed")
                   if isinstance(result, dict) else False)
        entry = {
            "ts": time.time(),
            "direction": direction,
            "reason": reason,
            "from_replicas": sig["replicas"],
            "to_replicas": (result.get("replicas", target)
                            if isinstance(result, dict) else target),
            "ok": bool(changed),
            "signals": dict(sig),
        }
        with self._lock:
            self.ledger.append(entry)
        if not changed:
            # A refused actuation (scale errors, already at bound)
            # doesn't re-arm the cooldown: the condition persists and
            # the next tick retries.
            return None
        self._last_event = now
        self._breach_since = None
        self._idle_since = None
        self.events_total[direction] += 1
        logger.info("autoscaler %s: %s -> %d replicas (%s; goodput=%s "
                    "queue=%d shed=%d)", self.name, direction,
                    entry["to_replicas"], reason, sig["goodput"],
                    sig["queue_depth"], sig["shed_delta"])
        try:
            m = self._metrics
            if m is not None:
                m.autoscale_events.labels(self.name, direction,
                                          reason).inc()
                m.replica_count_g.labels(self.name).set(
                    entry["to_replicas"])
        except Exception:
            pass
        return direction

    # -- lifecycle (the sampler's thread discipline) ------------------------

    def start(self) -> None:
        """Idempotent: one controller thread per autoscaler."""
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return
            self._stop_evt.clear()
            self._thread = threading.Thread(
                target=self._loop, daemon=True,
                name=f"autoscaler-{self.name}")
            self._thread.start()

    def _loop(self) -> None:
        while not self._stop_evt.wait(self.interval_s):
            try:
                self.tick()
            except Exception:
                # The controller must outlive a bad read — a dead
                # autoscaler is a silent return to static capacity.
                logger.exception("autoscaler %s: tick failed", self.name)

    def stop(self, timeout_s: float = 5.0) -> None:
        """Stop the controller (bounded join; the current tick may be
        inside scale_to, which can take a drain — the join bound keeps
        Router.drain from hanging on it; the daemon flag keeps an
        overrunning tick from blocking interpreter exit)."""
        self._stop_evt.set()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout=timeout_s)

    # -- observability ------------------------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        """The GET /stats block: bounds/windows, live membership, streak
        state, event counters, and the bounded decision ledger."""
        with self._lock:
            ledger = list(self.ledger)
        try:
            n = int(self.client.replica_count())
        except Exception:
            n = None
        return {
            "enabled": True,
            "replicas": n,
            "min_replicas": self.min_replicas,
            "max_replicas": self.max_replicas,
            "goodput_floor": self.goodput_floor,
            "queue_high_per_replica": self.queue_high,
            "breach_window_s": self.breach_window_s,
            "idle_window_s": self.idle_window_s,
            "up_cooldown_s": self.up_cooldown_s,
            "down_cooldown_s": self.down_cooldown_s,
            "interval_s": self.interval_s,
            "breaching": self._breach_since is not None,
            "idle": self._idle_since is not None,
            "events_total": dict(self.events_total),
            "last_signals": dict(self._last_signals),
            "ledger": ledger,
        }
