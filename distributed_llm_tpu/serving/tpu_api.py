"""Per-tier device-server HTTP surface — the `tpu_api.py` of the north star.

Reference parity: src/devices/nano_api.py and src/devices/orin_api.py (the
Flask servers that ran ON the Jetsons, fronting Ollama).  In-process dispatch
makes this layer optional for the TPU framework, but the surface is preserved
so deployments that want network-separated tiers (e.g. tiers on different
hosts of a pod, reached over DCN) keep the exact contract:

  GET  /         liveness text
  GET  /health   {"ok": true}
  POST /query    {"query": list[{role,content}] | str,
                  "num_predict": int (optional, -1 = tier default cap),
                  "temperature": float (optional)}   -> {"response": text}
                  errors: 400 bad input, 500 engine failure, 504 timeout

One factory replaces the two copy-pasted per-device files; the tier is
config (`--tier nano|orin`), not a fork of the source.
"""

from __future__ import annotations

import argparse
import logging
from typing import Any, Dict, Optional


from ..config import ClusterConfig
from ..utils.http_compat import (Flask, jsonify, request, sse_done_event,
                                 sse_event, streaming_response)
from ..engine.manager import EngineManager
from .router import default_cluster
from .tiers import build_tiers

logger = logging.getLogger(__name__)

# Reference defaults (src/devices/nano_api.py:18-21).
DEFAULT_NUM_PREDICT = -1
DEFAULT_TEMPERATURE = 0.0

TIER_PORTS = {"nano": 5001, "orin": 5000}   # reference ports


def _validate_history(query) -> Optional[str]:
    """None = well-formed; else the 400 message.  A list history must be
    role/content dicts with string fields (the reference clients build
    exactly that shape) — a malformed entry used to crash downstream in
    the tokenizer's history join instead of failing at the edge."""
    if isinstance(query, str):
        return None
    for m in query:
        if not isinstance(m, dict):
            return ("Invalid history entry: expected "
                    "{role, content} objects")
        if not isinstance(m.get("role", ""), str) \
                or not isinstance(m.get("content", ""), str):
            return "Invalid history entry: role/content must be strings"
    return None


class _ReleaseOnce:
    """Invoke ``fn`` exactly once — explicitly or via GC.  The stream
    route's admission release lives in its generator's ``finally``, but
    a WSGI layer can drop the response without ever STARTING the
    generator (client gone before the first byte); close() on a
    never-started generator runs no body, which would leak the slot
    forever.  Holding the release in an object the generator (and only
    the generator) references makes GC the backstop."""

    def __init__(self, fn):
        self._fn = fn

    def __call__(self) -> None:
        fn, self._fn = self._fn, None
        if fn is not None:
            fn()

    def __del__(self):
        self()


def create_tier_app(tier_name: str,
                    cluster: Optional[ClusterConfig] = None,
                    manager: Optional[EngineManager] = None) -> Flask:
    app = Flask(f"dllm_tpu_{tier_name}")

    if manager is None:
        tiers = build_tiers(cluster or default_cluster(),
                            warmup_on_start=False)
        if tier_name not in tiers:
            raise ValueError(f"unknown tier {tier_name!r}")
        manager = tiers[tier_name].server_manager
    app.extensions["dllm_manager"] = manager
    # Admission also gates the CROSS-HOST path: in-process requests go
    # through TierClient (which registers the controller on the
    # manager), but a remote router POSTs here directly — without this
    # gate a saturated remote tier would queue unboundedly.  A rejected
    # request gets 503 (urllib surfaces it as an error → RemoteTierClient
    # returns the reference error shape → Router failover fires).
    # Directly-passed managers (unit tests, bespoke deployments) may
    # carry no controller; then the gate is a no-op.
    admission = getattr(manager, "admission", None)

    @app.route("/")
    def home():
        return "Server is running!\n", 200

    @app.route("/health", methods=["GET"])
    def health():
        """Liveness contract {"ok": true} (reference nano_api.py) — a
        LAZY not-yet-started engine is healthy (readiness polling after
        spawn depends on it), but a WEDGED decode loop (stalled step
        progress past the tier's watchdog deadline, engine/batching.py)
        reports ok=false so a remote router's HealthMonitor can revive
        this process instead of probing a zombie forever.  Deliberately
        LOCK-FREE (plain attribute reads, not manager.health()): the
        manager's lifecycle lock is held for minutes through an engine
        build/warmup, and a blocked /health would make a merely-
        compiling tier read as dead to the remote prober."""
        try:
            engine = getattr(manager, "_engine", None)
            stall_fn = getattr(engine, "progress_stall_s", None)
            deadline = getattr(getattr(manager, "tier", None),
                               "watchdog_stall_s", None)
            if callable(stall_fn) and deadline is not None:
                stall_s = float(stall_fn())
                if stall_s > deadline:
                    # dllm-lint: disable=error-shape -- health-probe snapshot (GET /health surface: ok+wedged+error), not the tier error path
                    return jsonify({
                        "ok": False, "wedged": True,
                        "error": (f"decode watchdog: no step progress "
                                  f"for {stall_s:.1f}s (deadline "
                                  f"{deadline:.0f}s)")}), 200
        except Exception:
            pass
        return jsonify({"ok": True}), 200

    @app.route("/query", methods=["POST"])
    def process_query():
        data: Dict[str, Any] = request.get_json(silent=True) or {}
        query = data.get("query")

        if not query:
            return jsonify({"error": "No query provided"}), 400
        if not isinstance(query, (list, str)):
            return jsonify({"error": "Invalid query format. "
                                     "Expect list[role/content] or string."}), 400
        bad = _validate_history(query)
        if bad is not None:
            return jsonify({"error": bad}), 400

        try:
            num_predict = int(data.get("num_predict") or DEFAULT_NUM_PREDICT)
            temperature = float(data.get("temperature") or DEFAULT_TEMPERATURE)
        except (TypeError, ValueError):
            return jsonify({"error": "num_predict/temperature must be numeric"}), 400
        max_new = num_predict if num_predict > 0 else None

        if admission is not None:
            admit_err = admission.try_admit()
            if admit_err is not None:
                return jsonify({"error": f"Request failed: {tier_name} "
                                         f"admission rejected: "
                                         f"{admit_err}"}), 503
        import time as _time
        t0 = _time.perf_counter()
        try:
            result = manager.engine().generate(
                query, max_new_tokens=max_new, temperature=temperature)
            from .turns import clip_turn
            payload: Dict[str, Any] = {"response": clip_turn(result.text)}
            if data.get("stats"):
                # Opt-in extension (the bare reply stays reference-faithful,
                # src/devices/nano_api.py:83): generation metrics so a
                # cross-host caller (serving/remote.py) can feed the perf
                # strategy and TTFT accounting without a second request.
                payload["stats"] = {
                    "prompt_tokens": result.prompt_tokens,
                    "gen_tokens": result.gen_tokens,
                    "ttft_ms": round(result.ttft_ms, 3),
                    "total_ms": round(result.total_ms, 3),
                }
            return jsonify(payload)
        except TimeoutError:
            return jsonify({"error": "Inference timed out"}), 504
        except Exception as exc:
            logger.exception("inference failed")
            return jsonify({"error": f"Inference failed: {exc}"}), 500
        finally:
            if admission is not None:
                admission.release(_time.perf_counter() - t0)

    @app.route("/query/stream", methods=["POST"])
    def process_query_stream():
        """SSE token streaming (batched tiers only): `data: {"delta"}`
        events, then a final `data: {"done", "tokens", "ttft_ms"}`.  The
        reference API is non-streaming (stream:false, src/devices/
        nano_api.py:67); this is the TTFT-native extension."""
        data: Dict[str, Any] = request.get_json(silent=True) or {}
        query = data.get("query")
        if not query or not isinstance(query, (list, str)):
            return jsonify({"error": "No/invalid query provided"}), 400
        bad = _validate_history(query)
        if bad is not None:
            return jsonify({"error": bad}), 400
        engine = manager.engine()
        if not hasattr(engine, "generate_stream"):
            return jsonify({"error": "this tier's engine does not support "
                                     "token streaming"}), 501
        try:
            num_predict = int(data.get("num_predict") or DEFAULT_NUM_PREDICT)
            temperature = float(data.get("temperature")
                                or DEFAULT_TEMPERATURE)
        except (TypeError, ValueError):
            return jsonify({"error": "num_predict/temperature must be "
                                     "numeric"}), 400
        max_new = num_predict if num_predict > 0 else None
        if admission is not None:
            admit_err = admission.try_admit()
            if admit_err is not None:
                return jsonify({"error": f"Request failed: {tier_name} "
                                         f"admission rejected: "
                                         f"{admit_err}"}), 503
        import time as _time
        t0 = _time.perf_counter()
        try:
            from .turns import ClippedStream
            handle = ClippedStream(
                engine.generate_stream(query, max_new_tokens=max_new,
                                       temperature=temperature))
        except NotImplementedError as exc:
            # e.g. the speculative engine is greedy-only: keep the JSON
            # error contract instead of a framework 500 page.
            if admission is not None:
                admission.release()
            return jsonify({"error": str(exc)}), 501
        except Exception as exc:
            logger.exception("stream setup failed")
            if admission is not None:
                admission.release()
            return jsonify({"error": f"Inference failed: {exc}"}), 500

        def _release_slot():
            if admission is None:
                return
            # Engine-true generation time when the stream completed;
            # wall time otherwise (client disconnect mid-generation).
            result = getattr(handle, "result", None)
            engine_ms = getattr(result, "total_ms", 0) if result else 0
            admission.release(engine_ms / 1000.0 if engine_ms
                              else _time.perf_counter() - t0)

        release = _ReleaseOnce(_release_slot)

        def events():
            try:
                for delta in handle:
                    yield sse_event({"delta": delta})
                yield sse_done_event(handle.result)
            except Exception as exc:
                yield sse_event({"error": str(exc)})
            finally:
                # Exactly once: exhaustion, client disconnect (generator
                # close), or — if the generator is dropped before it ever
                # starts — GC of the _ReleaseOnce it closes over.
                release()

        return streaming_response(events())

    return app


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--tier", choices=sorted(TIER_PORTS), default="nano")
    parser.add_argument("--port", type=int, default=None)
    args = parser.parse_args()

    logging.basicConfig(level=logging.INFO)
    app = create_tier_app(args.tier)
    port = args.port if args.port is not None else TIER_PORTS[args.tier]
    app.run(host="0.0.0.0", port=port, threaded=True)


if __name__ == "__main__":
    main()
