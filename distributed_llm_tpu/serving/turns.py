"""Turn clipping for served replies.

The reference's device servers return Ollama chat-model output, and an
instruction-tuned model stops at its turn boundary on its own
(src/devices/nano_api.py:76 just forwards the text).  This framework's
tiers serve LMs pretrained on the raw ``role: content`` chat corpus
(training/data.py), so an un-clipped generation happily continues the
TRANSCRIPT — emitting ``user:`` / ``assistant:`` turns after its own
reply.  The serving layer owns restoring the single-turn semantic: clip
the reply at the first role marker the model hallucinates, both on the
sync path and (with a hold-back buffer) on the token stream.
"""

from __future__ import annotations

from typing import Iterator, Optional

# Role labels as they appear in the training corpus / prompt format
# (engine/tokenizer.py format_history): "role: content" lines.
_ROLES = ("user:", "assistant:", "system:")
# Longest text a marker can span, for the streaming hold-back (11 chars:
# "assistant:" + newline).  WORST CASE of the hold-back (ADVICE r5
# tiers.py:204): nothing is emitted until >HOLDBACK chars accumulate,
# and a stream whose model emits a role marker from token one NEVER
# emits — ClippedStream then silently drains the rest of the generation
# for its result/lock, so an eager first-delta primer
# (serving/tiers.py _PrimedStream) would block a serving thread for the
# whole decode budget.  ClippedStream's ``prime_drain_chars`` bounds
# that drain (the primer is released with one "" sentinel after at most
# PRIME_DRAIN_CHARS drained chars ≈ 74 BPE tokens at ~3.5 chars/token —
# see the constant's definition in serving/tiers.py).
HOLDBACK = max(len(r) for r in _ROLES) + 1          # +1 for the newline


def _marker_pos(text: str, at_line_start: bool = True) -> Optional[int]:
    """Position of the earliest role marker at a line start (markers
    mid-line are quoted text, not turns), or None.  ``at_line_start``
    says whether position 0 of ``text`` begins a line — False when the
    caller holds a buffer whose origin is mid-line (the streaming
    hold-back cut)."""
    best: Optional[int] = None
    for role in _ROLES:
        start = 0
        while True:
            i = text.find(role, start)
            if i < 0:
                break
            if (i == 0 and at_line_start) or (i > 0 and text[i - 1] == "\n"):
                best = i if best is None else min(best, i)
                break
            start = i + 1
    return best


def clip_turn(text: str) -> str:
    """The reply's own turn: drop a leading ``assistant:`` label if the
    model echoed one, then cut at the first subsequent role marker.  A
    clip that would leave nothing returns the stripped original (a
    degenerate transcript beats an empty reply)."""
    stripped = text.lstrip()
    for role in _ROLES:
        if stripped.startswith(role):
            stripped = stripped[len(role):].lstrip()
            break
    pos = _marker_pos(stripped)
    clipped = stripped[:pos] if pos is not None else stripped
    clipped = clipped.rstrip()
    return clipped if clipped else text.strip()


class ClippedStream:
    """Delta-stream wrapper applying ``clip_turn`` semantics on the fly.

    Holds back the last ``HOLDBACK`` characters so a role marker split
    across deltas is still caught before it is emitted.  Once a marker
    is confirmed, remaining deltas are DRAINED silently rather than the
    stream closed: closing mid-stream would leave ``handle.result``
    None (no token counts for the done event, no perf-strategy
    feedback) and skip the engine's end-of-stream prefix-cache parking,
    so the next turn would lose its KV reuse.  The drain's dead air is
    bounded by the tier's ``max_new_tokens`` decode cap (48-128 across
    the shipped clusters) — the same budget the sync path always
    spends, since it clips after the fact.

    WORST CASE (and the ``prime_drain_chars`` cap): when the model emits
    a role marker from token one, nothing is ever emitted and a single
    ``next()`` on this stream blocks for the ENTIRE drain — up to
    max_new_tokens of decoding.  A caller that eagerly primes the first
    delta before handing the stream out (serving/tiers.py
    ``_PrimedStream``, which holds the sequential engine lock while
    priming) would stall its serving thread for a full generation before
    the handle is even returned.  ``prime_drain_chars`` caps that: once
    a fully-clipped stream has silently drained that many characters, an
    EMPTY delta is yielded once so the primer's ``next()`` returns; the
    remaining drain then happens lazily as the consumer iterates.
    Consumers must tolerate one "" delta (``_PrimedStream`` swallows
    it).  None keeps the uncapped r5 behavior.
    """

    def __init__(self, handle, prime_drain_chars: Optional[int] = None):
        self._handle = handle
        self._prime_drain_chars = prime_drain_chars
        self._emitted_any = False

    def __iter__(self) -> Iterator[str]:
        buf = ""                  # text received but not yet emitted
        # Whether position 0 of buf begins a line: True until a
        # hold-back cut leaves a mid-line origin (a quoted "user:" that
        # lands exactly on a cut boundary must not read as a turn).
        buf_line_start = True
        label_checked = False
        clipped = False
        drained = 0               # chars silently drained after a clip
        prime_released = False
        for delta in self._handle:
            if clipped:
                # Drain for result/lock, emit nothing — but release an
                # eager primer once (see class docstring worst case).
                drained += len(delta)
                if (self._prime_drain_chars is not None
                        and not self._emitted_any and not prime_released
                        and drained >= self._prime_drain_chars):
                    prime_released = True
                    yield ""
                continue
            buf += delta
            if not label_checked:
                # Wait until the buffer can't be a partial leading label.
                probe = buf.lstrip()
                if (len(probe) < HOLDBACK
                        and any(r.startswith(probe) or probe.startswith(r)
                                for r in _ROLES)):
                    continue
                for role in _ROLES:
                    if probe.startswith(role):
                        buf = probe[len(role):].lstrip()
                        break
                label_checked = True
            pos = _marker_pos(buf, at_line_start=buf_line_start)
            if pos is not None:
                out = buf[:pos].rstrip()
                if out:
                    self._emitted_any = True
                    yield out
                buf = ""
                clipped = True
                continue
            if len(buf) > HOLDBACK:
                out, buf = buf[:-HOLDBACK], buf[-HOLDBACK:]
                buf_line_start = out.endswith("\n")
                if out:
                    self._emitted_any = True
                    yield out
        if not clipped:
            tail = buf.rstrip() if self._emitted_any else clip_turn(buf)
            if tail:
                self._emitted_any = True
                yield tail
        # A fully-clipped stream (marker from token one) still owes the
        # caller SOMETHING; mirror clip_turn's degenerate fallback.
        if not self._emitted_any:
            result = getattr(self._handle, "result", None)
            text = getattr(result, "text", "") or ""
            fallback = text.strip()
            if fallback:
                yield fallback

    def close(self) -> None:
        close = getattr(self._handle, "close", None)
        if close is not None:
            close()

    @property
    def result(self):
        return getattr(self._handle, "result", None)
